"""Headline benchmark: committed linearizable ops/sec over batched Raft groups.

BASELINE.md metric: "committed ops/sec over 10k Raft groups". The reference
publishes no numbers (BASELINE.md §published — absence verified), so
``vs_baseline`` is reported against the BASELINE.json north-star target of
1M linearizable ops/sec.

Prints ONE JSON line on stdout; all diagnostics go to stderr.

Scenarios (``COPYCAT_BENCH_SCENARIO``, BASELINE.md benchmark configs):

- ``counter`` (default, config #1 scaled out): every submit slot carries a
  ``DistributedLong.addAndGet``; G groups × 3 peers; R rounds under
  ``lax.scan``. Each committed entry is a quorum-replicated, leader-applied
  linearizable command.
- ``election`` (config #2): 1k groups; a random peer is isolated every few
  rounds (device-side nemesis masks), forcing re-elections; measures
  elections completed/sec (batched RequestVote tally path).
- ``map`` (config #3): put/get mix through the hashed map apply kernel.
- ``lock`` (config #4): acquire→queue→release→grant chains in every group
  (event-push grant path).
- ``mixed`` (config #5): counter+map+lock mix with per-round random peer
  isolation (nemesis) across all groups.
- ``host``: client-visible throughput through the full host runtime
  (queue-managed ``submit_batch`` → harvest → results), the number a
  framework client actually sees.
- ``spi``: client-visible throughput through the PUBLIC resource API —
  ``COPYCAT_BENCH_SPI_INSTANCES`` (default 1000) device-backed
  ``DistributedAtomicLong``s on an ``AtomixServer(executor="tpu")``,
  pipelined increments over real sessions, ``COPYCAT_BENCH_SPI_BURSTS``
  bursts; reports on-device instance count + total engine rounds.
- ``readmix``: read-dominated (90/10) traffic through the public API —
  the batched read pump's A/B scenario (``COPYCAT_SERVER_READ_PUMP``);
  headline value is client-visible reads/sec.
- ``cluster``: the first REPLICATED-cluster scenario — a 3-member
  ``RaftServer`` cluster over the local transport with a nemesis-injected
  per-message latency (a realistic LAN RTT; without it an in-process
  "network" hides exactly the stop-and-wait stall this scenario exists
  to measure), writes through the public ``RaftClient`` API; headline
  value is committed ops/sec. The pipelined replication plane's A/B
  knob is ``COPYCAT_REPL_PIPELINE`` (docs/REPLICATION.md); ``--storage
  {memory,mapped,disk}`` runs the same workload on a durable log level
  (the durability A/B, docs/DURABILITY.md).
- ``sharded``: the multi-raft keyspace-sharding scenario
  (docs/SHARDING.md) — a 3-member cluster hosting ``--groups N`` Raft
  groups with leadership spread, many clients, zipfian keys, under a
  cross-region wire delay where the bounded replication window caps a
  single ordered log; headline value is committed ops/sec, with
  groups-led / per-group-commit / routing-mix in the artifact. The A/B
  knob is ``--groups 1`` (the single-group plane, which
  ``COPYCAT_MULTI_GROUP=0`` pins bit-identically).
- ``apply``: the apply-limited scenario (docs/SHARDING.md "Apply
  ordering") — a single member hosting ``--groups N`` Raft groups,
  many sessions, hot/cold zipfian device counters, and an interleaved
  eligible/ineligible op stream that collapses the contiguous vector
  classifier to the per-entry lane; headline value is committed
  ops/sec, with the ``apply.*`` family (spans, conflicts, fused
  dispatches, rows/runs per dispatch) in the artifact. The A/B knobs
  are ``COPYCAT_PARALLEL_APPLY=0`` / ``COPYCAT_APPLY_FUSE=0`` (the
  contiguous/per-group plane).
- ``recovery``: the crash-recovery scenario — a fresh member catching up
  to a loaded, compacted cluster via snapshot-install streaming vs full
  log replay (``COPYCAT_SNAPSHOTS`` A/B inside one run); headline value
  is the catch-up speedup, with ``snap.*`` metrics in the artifact.
- ``fanout``: the edge read tier scenario (docs/EDGE_READS.md) — few
  writers, a sweep of reader-session counts over zipfian counters;
  with ``COPYCAT_EDGE_READS`` on, SEQUENTIAL reads serve from
  client-local CRDT replicas fed by per-resource deltas and reads/s
  scales with the reader count while cluster commits stay flat; the
  knob-off lane pins reads/s to server read capacity (the A/B).
  The artifact embeds the cache-served-read trace proof (client-side
  spans only) and the aggregated ``edge.*`` client family.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from functools import partial

import jax

from .utils import knobs
from .utils.platform import honor_jax_platforms_env

honor_jax_platforms_env()

import jax.numpy as jnp
import numpy as np

from copycat_tpu.ops import apply as ap
from copycat_tpu.ops.apply import ResourceConfig
from copycat_tpu.utils.profiling import xla_trace
from copycat_tpu.ops.consensus import (
    Config,
    Submits,
    current_leader,
    full_delivery,
    init_state,
    install_snapshots,
    make_submits,
    query_step,
    step,
)

# Pool state is carried through every step (HBM traffic), so each scenario
# compiles in only the pools its groups actually host (ResourceConfig
# zero-size pools are compiled out of the kernel).
RESOURCE_CONFIGS = {
    "counter": ResourceConfig.counters_only(),
    "election": ResourceConfig.counters_only(),
    "map": ResourceConfig(set_slots=0, queue_slots=0, wait_slots=0,
                          listener_slots=0, event_slots=0,
                          multimap_slots=0, topic_slots=0),
    "lock": ResourceConfig(map_slots=0, set_slots=0, queue_slots=0,
                           listener_slots=0, multimap_slots=0,
                           topic_slots=0),
    # config #5 keeps its round-2 definition (the six original kernels)
    # so numbers stay comparable; multimap/topic have their own coverage
    "mixed": ResourceConfig(multimap_slots=0, topic_slots=0),
}

SCENARIO = knobs.get_str("COPYCAT_BENCH_SCENARIO")
GROUPS = knobs.get_int(
    "COPYCAT_BENCH_GROUPS", default=1000 if SCENARIO == "election" else 10000)
PEERS = knobs.get_int("COPYCAT_BENCH_PEERS")
# The mixed config is [G,P,L]-bandwidth-bound: L=32 measured +11%
# throughput and p50 106->31 ms vs L=64 at 100k x 5 (PERF.md round-3
# continuation); the ring only needs to cover in-flight depth (S=16 with
# backpressure). Other configs are smaller and keep the roomier default.
LOG_SLOTS = knobs.get_int("COPYCAT_BENCH_LOG_SLOTS",
                          default=32 if SCENARIO == "mixed" else 64)
ROUNDS = knobs.get_int("COPYCAT_BENCH_ROUNDS")
# Best-of-N: 5 reps (~0.3s each) buys insurance against tunnel/dispatch
# jitter on the recorded number — observed session-to-session swings of
# ±30% on otherwise-identical code come from the environment, not the
# step (BENCH_SCENARIOS.md note ¹).
REPEATS = knobs.get_int("COPYCAT_BENCH_REPEATS")
SUBMIT_SLOTS = knobs.get_int("COPYCAT_BENCH_SUBMIT_SLOTS")
NORTH_STAR_OPS = 1_000_000.0
# Default the Pallas quorum-tally kernel ON for TPU: measured at parity
# with the jnp path after the one-hot rewrite (PERF.md §Pallas A/B — the
# step is dispatch-bound, not tally-bound), and running it keeps the
# production kernel exercised. CPU keeps the jnp path (interpret mode is
# test-only). Resolved LAZILY: jax.default_backend() initializes the
# backend, which must not happen at import time — _require_devices()
# gates it with a timeout first (a dead tunnel hangs enumeration).
_PALLAS_ENV = knobs.get_raw("COPYCAT_BENCH_PALLAS")


def use_pallas() -> bool:
    if _PALLAS_ENV is not None:
        return _PALLAS_ENV == "1"
    return jax.default_backend() == "tpu"
# Per-pool apply budgets (value,map,set,queue,lock,election): budgets
# select the conflict-partitioned apply path (ops/consensus.py
# Config.pool_budgets); empty = the single sequential scan.
# - mixed: steady-state arrivals are value 2 / map 4 / set 2 / queue 4 /
#   lock 2 / elect 2 per group per round; budgets give ~2x headroom so
#   post-nemesis backlogs drain while cutting each pool's HBM traffic to
#   budget/A of the sequential scan's.
# - lock: full budgets — partitioning still wins 2.3x because the fully
#   unrolled single-pool fold fuses the 16 applies into few HBM passes.
# - counter/election/map: sequential scan measures equal or better
#   (dispatch-bound or single-pool-dominant with value planes tiny).
_full = str(max(4, SUBMIT_SLOTS))  # = applies_per_round, never a throttle
_default_budgets = {"mixed": "4,6,4,6,4,4,4,4",
                    "lock": ",".join([_full] * 8)}.get(SCENARIO, "")
_budgets_env = knobs.get_str("COPYCAT_BENCH_POOL_BUDGETS",
                             default=_default_budgets)
POOL_BUDGETS = (tuple(int(x) for x in _budgets_env.split(","))
                if _budgets_env else None)

# Set to a directory to capture an XLA profiler trace of the first timed
# repetition (open in TensorBoard/XProf, or summarize with
# copycat_tpu.utils.profiling.summarize_trace).
PROFILE_DIR = knobs.get_str("COPYCAT_BENCH_PROFILE")

# COPYCAT_BENCH_TELEMETRY=1: compile the round-8 device telemetry block
# into the measured step (Config(telemetry=True)) — the A/B knob behind
# PERF.md round 8's ≤2% ms/round acceptance bound. run_throughput
# accumulates the telemetry deltas in the scan carry (an unread output
# would be dead-code-eliminated and the A/B would measure nothing) and
# reports the totals; run_host/run_session surface the engine's
# device.* family in the --metrics-json artifact.
TELEMETRY = knobs.get_bool("COPYCAT_BENCH_TELEMETRY")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


#: per-run registry snapshots scenarios contribute to the
#: ``--metrics-json`` artifact (run_spi adds the server's full
#: stats_snapshot + the client registry), keyed by component name.
METRICS_SNAPSHOTS: dict = {}

#: retained ``/series`` windows scenarios contribute to the artifact
#: (docs/OBSERVABILITY.md "Retrospective telemetry") — where an
#: end-of-run snapshot says WHAT the run cost, the series says WHEN:
#: commit-rate ramp, election spikes mid-run, latency onsets. Keyed by
#: component name like METRICS_SNAPSHOTS; empty when the servers ran
#: with COPYCAT_SERIES=0 or the scenario spins no server.
SERIES_WINDOWS: dict = {}


def capture_series(component: str, server_like: object) -> None:
    """Stash ``server_like``'s retained series window (if it keeps one)
    under ``component`` for the ``--metrics-json`` artifact."""
    store = getattr(server_like, "series", None)
    if store is not None:
        SERIES_WINDOWS[component] = store.payload()


def _bench_gc_tune() -> None:
    """GC tuning shared by the SPI-stack scenarios (the production-server
    treatment): a 1k-op burst allocates ~20k short-lived objects (tasks,
    futures, messages); with default thresholds a gen-2 pass lands
    mid-burst and the collector walks the whole live server — 30+ ms, a
    3-4x swing between otherwise identical reps. Freeze the settled heap
    out of collection and raise gen0 so cyclic garbage is still
    collected, just between bursts."""
    import gc

    gc.collect()
    gc.freeze()
    gc.set_threshold(100_000, 50, 100)


async def _close_spi_stack(client, server, transport=None) -> None:
    """Teardown shared by the SPI-stack scenarios: bounded closes (a
    wedged node must not hang the bench), then the transport's own
    shutdown when it runs background machinery (the native epoll pair)."""
    import asyncio

    try:
        await asyncio.wait_for(client.close(), 10)
    except Exception:
        pass
    try:
        await asyncio.wait_for(server.close(), 10)
    except Exception:
        pass
    if transport is not None:
        shutdown = getattr(transport, "shutdown", None)
        if shutdown is not None:
            shutdown()


def percentiles(hist: np.ndarray, qs) -> list[int]:
    """Percentile values from an exact count histogram (index = value)."""
    total = int(hist.sum())
    if total == 0:
        return [0 for _ in qs]
    cum = np.cumsum(hist)
    return [int(np.searchsorted(cum, q * total)) for q in qs]


def zipf_sampler(rng, n_keys: int, s: float):
    """Deterministic zipfian rank draw: inverse-CDF over 1/rank^s on
    the caller's seeded ``rng``. Shared by the hot/cold-keyspace
    scenarios (``sharded``, ``apply``) so their skew semantics cannot
    drift apart; returns a 0-based rank in ``[0, n_keys)``."""
    import bisect

    weights = [1.0 / (r ** s) for r in range(1, n_keys + 1)]
    total_w = sum(weights)
    cdf = []
    acc = 0.0
    for w in weights:
        acc += w / total_w
        cdf.append(acc)

    def draw() -> int:
        return min(bisect.bisect_left(cdf, rng.random()), n_keys - 1)

    return draw


def empty_submits(G: int) -> Submits:
    return make_submits(G, SUBMIT_SLOTS)


def current_leaders(state) -> jnp.ndarray:
    """[G] leader peer index per group, -1 if none."""
    return current_leader(state)[0]


def tile_pattern(pattern, G: int) -> jnp.ndarray:
    """Tile a short per-slot pattern across [G, SUBMIT_SLOTS]."""
    pat = jnp.asarray(pattern, jnp.int32)
    row = pat[jnp.arange(SUBMIT_SLOTS) % pat.size]
    return jnp.broadcast_to(row, (G, SUBMIT_SLOTS))


def counter_submits(G: int) -> Submits:
    ones = jnp.ones((G, SUBMIT_SLOTS), jnp.int32)
    return Submits(opcode=ones * ap.OP_LONG_ADD, a=ones, b=ones * 0,
                   c=ones * 0, tag=ones, valid=ones.astype(bool))


def map_submits(G: int) -> Submits:
    """put/get mix over 10 rotating keys per group (BASELINE config #3:
    "10k keys × 1k groups" = 10 keys/group at G=1000, hashed-keyspace
    kernel)."""
    ones = jnp.ones((G, SUBMIT_SLOTS), jnp.int32)
    opc = [ap.OP_MAP_PUT, ap.OP_MAP_GET] * 5
    keys = [1, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 2, 3, 6, 8, 10]
    return Submits(opcode=tile_pattern(opc, G), a=tile_pattern(keys, G),
                   b=ones * 7, c=ones * 0, tag=ones,
                   valid=ones.astype(bool))


def lock_submits(G: int) -> Submits:
    """acquire(1) → acquire(2, queued) → release(1) [grants 2] → release(2).

    Every round drives the full grant chain including the event-push path.
    """
    ones = jnp.ones((G, SUBMIT_SLOTS), jnp.int32)
    opc = [ap.OP_LOCK_ACQUIRE, ap.OP_LOCK_ACQUIRE,
           ap.OP_LOCK_RELEASE, ap.OP_LOCK_RELEASE]
    who = [1, 2, 1, 2]
    waitflag = [-1, -1, 0, 0]
    return Submits(opcode=tile_pattern(opc, G), a=tile_pattern(who, G),
                   b=tile_pattern(waitflag, G),
                   c=ones * 0, tag=ones, valid=ones.astype(bool))


def mixed_submits(G: int) -> Submits:
    """Every resource kernel in one round (BASELINE config #5): counter,
    map, set, queue, lock grant chain, election listen/resign — so the
    nemesis run exercises all apply paths plus the event outbox."""
    ones = jnp.ones((G, SUBMIT_SLOTS), jnp.int32)
    opc = [ap.OP_LONG_ADD, ap.OP_MAP_PUT, ap.OP_MAP_GET,
           ap.OP_SET_ADD, ap.OP_SET_REMOVE,
           ap.OP_Q_OFFER, ap.OP_Q_POLL,
           ap.OP_LOCK_ACQUIRE, ap.OP_LOCK_RELEASE,
           ap.OP_ELECT_LISTEN, ap.OP_ELECT_RESIGN,
           ap.OP_LONG_ADD, ap.OP_MAP_PUT,
           ap.OP_Q_OFFER, ap.OP_Q_POLL, ap.OP_MAP_GET]
    a = [1, 3, 3, 5, 5, 6, 0, 9, 9, 4, 4, 1, 7, 6, 0, 7]
    b = [0, 5, 0, 0, 0, 0, 0, -1, 0, 0, 0, 0, 8, 0, 0, 0]
    return Submits(opcode=tile_pattern(opc, G), a=tile_pattern(a, G),
                   b=tile_pattern(b, G),
                   c=ones * 0, tag=ones, valid=ones.astype(bool))


SUBMIT_BUILDERS = {
    "counter": counter_submits,
    "map": map_submits,
    "lock": lock_submits,
    "mixed": mixed_submits,
}


def isolation_masks(rounds: int, G: int, P: int, period: int,
                    seed: int) -> jnp.ndarray:
    """Per-round victim peer per group (-1 = no fault), [R, G] int32."""
    rng = np.random.default_rng(seed)
    victims = np.full((rounds, G), -1, np.int32)
    for r in range(0, rounds, period):
        victims[r: r + period // 2] = rng.integers(0, P, G, dtype=np.int32)
    return jnp.asarray(victims)


def victim_deliver(victim: jnp.ndarray, G: int, P: int) -> jnp.ndarray:
    """deliver[G,P,P] isolating ``victim[G]`` (-1 = fully connected)."""
    peers = jnp.arange(P)
    hit = peers[None, :] == victim[:, None]          # [G,P]
    cut = hit[:, :, None] | hit[:, None, :]
    return ~cut | (victim[:, None, None] < 0)


def elect_all(state, jit_step, empty, deliver, key, G):
    t0 = time.perf_counter()
    for r in range(150):
        key, k = jax.random.split(key)
        state, out = jit_step(state, empty, deliver, k)
        if int((np.asarray(out.leader) >= 0).sum()) == G:
            break
    else:
        raise RuntimeError("not all groups elected a leader")
    log(f"bench: all {G} leaders elected in {r + 1} rounds "
        f"({time.perf_counter() - t0:.1f}s incl. compile)")
    return state, key


def run_throughput(scenario: str) -> dict:
    # Mixed (the nemesis config) defaults to tight election timers: the
    # p99 tail IS failover latency — entries appended the round a
    # partition forms wait out lease-drop + step-down + election. With
    # the lease-gated accept, timers 2-5 measured p99 14→7 rounds and
    # p99.9 18→10 at +13% throughput vs the 4-9 default (round-4 A/B);
    # a second A/B tightened to 2-4 (p99 8→7 rounds and +19% ops at
    # 256×3, +4% at 1024×5). 2-3 is over the edge: the randomization
    # range is too narrow to break vote splits and elections thrash.
    # Partition-only nemesis keeps short timers safe here; lossy
    # environments (the verdict runner) keep the roomier engine default.
    t_min = knobs.get_int("COPYCAT_BENCH_TIMER_MIN",
                          default=2 if scenario == "mixed" else 4)
    t_max = knobs.get_int("COPYCAT_BENCH_TIMER_MAX",
                          default=4 if scenario == "mixed" else 9)
    config = Config(use_pallas=use_pallas(),
                    append_window=max(4, SUBMIT_SLOTS),
                    applies_per_round=max(4, SUBMIT_SLOTS),
                    pool_budgets=POOL_BUDGETS,
                    timer_min=t_min, timer_max=t_max,
                    telemetry=TELEMETRY,
                    resource=RESOURCE_CONFIGS.get(scenario, ResourceConfig()))
    key = jax.random.PRNGKey(0)
    key, init_key = jax.random.split(key)
    state = init_state(GROUPS, PEERS, LOG_SLOTS, init_key, config)
    deliver = full_delivery(GROUPS, PEERS)
    submits = SUBMIT_BUILDERS[scenario](GROUPS)
    jit_step = jax.jit(partial(step, config=config))

    log(f"bench[{scenario}]: G={GROUPS} P={PEERS} L={LOG_SLOTS} "
        f"rounds={ROUNDS} device={jax.devices()[0].platform}")
    state, key = elect_all(state, jit_step, empty_submits(GROUPS), deliver,
                           key, GROUPS)

    nemesis = scenario == "mixed"
    victims = (isolation_masks(ROUNDS, GROUPS, PEERS, period=20, seed=1)
               if nemesis else None)

    # Commit latency (BASELINE.md metric). DEFINITION: device-measured
    # rounds from leader log APPEND to state-machine APPLY (+1 for the
    # appending round), converted to ms at the measured round cadence.
    # This is the replication+commit+apply cost; the host-observed
    # submit->harvest latency adds host queueing on top (RaftGroups
    # reports it in metrics "commit_latency_rounds" — see
    # BENCH_SCENARIOS.md for both numbers side by side).
    # Histogrammed on device with exact integer buckets; the histogram's
    # one-hot compare scales with the bucket count, so only nemesis runs
    # (whose entries can wait out isolation windows plus the whole
    # backpressure ring) pay for the wide range. The top bucket is a
    # saturation catch-all (warned about below if hit).
    max_lat = LOG_SLOTS + (200 if nemesis else 34)

    # Telemetry A/B (PERF.md round 8): the deltas must be CONSUMED or
    # XLA dead-code-eliminates the whole block and the A/B measures the
    # pre-change program. Accumulate them in the scan carry (per-group
    # int32 sums — the same amortized-fetch shape the drivers use).
    tel0 = None
    if TELEMETRY:
        from copycat_tpu.ops.apply import NUM_POOLS
        from copycat_tpu.ops.consensus import DeviceTelemetry
        zg = jnp.zeros((GROUPS,), jnp.int32)
        tel0 = DeviceTelemetry(
            elections_started=zg, leader_changes=zg, term_bumps=zg,
            leaderless=zg, commit_advance=zg, commit_max=zg, term_max=zg,
            leader_lane=zg, leader_term=zg,
            applies=jnp.zeros((GROUPS, NUM_POOLS + 1), jnp.int32),
            ring_occ_max=zg, submit_rejections=zg, vote_splits=zg,
            events_drained=zg, events_dropped=zg)

    def run(state, key):
        def body(carry, victim):
            state, key, applied_prev, tel_acc = carry
            key, k = jax.random.split(key)
            dl = (victim_deliver(victim, GROUPS, PEERS) if nemesis
                  else deliver)
            state, out = step(state, submits, dl, k, config=config)
            if TELEMETRY:
                tel_acc = jax.tree.map(lambda a, d: a + d, tel_acc,
                                       out.telemetry)
            if nemesis:
                # Followers that fell beyond the ring window during an
                # isolation can never be served by AppendEntries again;
                # without the snapshot-install path (what RaftGroups does
                # host-side) they accumulate until groups lose quorum and
                # throughput decays run over run. Unconditional masked
                # install fuses into the round; a lax.cond every-k-rounds
                # variant measured 1.8x SLOWER (the cond blocks XLA's
                # in-place aliasing of the full state).
                state = install_snapshots(state, out.stale, out.leader,
                                          config=config)
            lat = jnp.clip(out.out_latency.reshape(-1), 0, max_lat - 1)
            # one-hot select-reduce, NOT .at[].add(): XLA lowers the scatter
            # to an element-at-a-time DMA loop that costs more than the whole
            # consensus step (see PERF.md — same pathology as the engine's
            # round-2 gather/scatter rewrite, rediscovered here by profile)
            hist = jnp.sum(
                (lat[:, None] == jnp.arange(max_lat, dtype=jnp.int32)[None, :])
                & out.out_valid.reshape(-1)[:, None],
                axis=0, dtype=jnp.int32)
            # exact-once committed-op count: global applied high-water delta
            # (out_valid reports are at-least-once across leader changes)
            applied_now = jnp.max(state.applied_index, axis=1)
            n = jnp.sum(applied_now - applied_prev, dtype=jnp.int32)
            return (state, key, applied_now, tel_acc), (n, hist)
        applied0 = jnp.max(state.applied_index, axis=1)
        (state, key, _, tel_acc), (counts, hists) = jax.lax.scan(
            body, (state, key, applied0, tel0), victims,
            length=None if nemesis else ROUNDS)
        return state, key, counts.sum(), hists.sum(axis=0), tel_acc

    run_jit = jax.jit(run)
    state, key, n, hist, tel = run_jit(state, key)
    jax.block_until_ready(n)
    log(f"bench[{scenario}]: warmup committed {int(n)} ops")
    best, best_dt, best_hist = 0.0, 1.0, np.asarray(hist)

    reps = []
    tel_totals: dict = {}
    for rep in range(REPEATS):
        with xla_trace(PROFILE_DIR if rep == 0 else None):
            t0 = time.perf_counter()
            state, key, n, hist, tel = run_jit(state, key)
            n = int(jax.block_until_ready(n))
            dt = time.perf_counter() - t0
        ops = n / dt
        reps.append(ops)
        if ops >= best:
            best, best_dt, best_hist = ops, dt, np.asarray(hist)
        if TELEMETRY:
            for name in ("elections_started", "leader_changes",
                         "leaderless", "commit_advance",
                         "submit_rejections", "vote_splits"):
                tel_totals[name] = tel_totals.get(name, 0) + int(
                    np.asarray(getattr(tel, name), np.int64).sum())
        log(f"bench[{scenario}]: rep {rep}: {n} committed ops in {dt:.3f}s "
            f"-> {ops:,.0f} ops/sec ({dt / ROUNDS * 1e3:.2f} ms/round)")
    if best_hist[-1]:
        log(f"bench[{scenario}]: WARNING: {int(best_hist[-1])} samples "
            f"saturated the top latency bucket (>{max_lat - 1} rounds); "
            f"p99 is a lower bound")

    ms_per_round = best_dt / ROUNDS * 1e3
    # out_latency counts rounds the entry sat in the log before apply; the
    # round that appended+replicated+applied it counts too (+1): an op
    # submitted before round r completes after round r finishes.
    p50_r, p99_r = [p + 1 for p in percentiles(best_hist, (0.50, 0.99))]
    log(f"bench[{scenario}]: commit latency p50={p50_r} rounds "
        f"({p50_r * ms_per_round:.2f} ms)  p99={p99_r} rounds "
        f"({p99_r * ms_per_round:.2f} ms) at {ms_per_round:.2f} ms/round")

    suffix = "" if scenario == "counter" else f"_{scenario}"
    out = {
        "metric": (f"committed_linearizable_ops_per_sec_{GROUPS}_groups"
                   f"{suffix}"),
        "value": round(best, 1),
        "unit": "ops/sec",
        "vs_baseline": round(best / NORTH_STAR_OPS, 4),
        "p50_commit_latency_ms": round(p50_r * ms_per_round, 3),
        "p99_commit_latency_ms": round(p99_r * ms_per_round, 3),
        "p50_commit_latency_rounds": int(p50_r),
        "p99_commit_latency_rounds": int(p99_r),
        **spread(reps),
    }
    if TELEMETRY:
        out["telemetry"] = True
        out["device_telemetry"] = tel_totals
    return out


def run_host() -> dict:
    """Client-visible throughput through the host runtime.

    Default mode ``bulk`` (``COPYCAT_BENCH_HOST_MODE``): the pipelined
    vectorized driver (``models/bulk.py``) — double-buffered rounds,
    zero per-op Python — with ``COPYCAT_BENCH_HOST_BURST`` ops per group
    per burst (default 8 bursts' worth of submit slots). Mode ``queued``
    keeps the round-3 queue-managed path (submit_batch → run_until with
    full exactly-once retry bookkeeping) for comparison; both are
    client-visible numbers. BENCH_SCENARIOS.md documents them side by
    side."""
    from .models import BulkDriver, RaftGroups

    mode = knobs.get_str("COPYCAT_BENCH_HOST_MODE")
    if mode not in ("deep", "deepscan", "bulk", "queued"):
        raise SystemExit(
            f"COPYCAT_BENCH_HOST_MODE={mode!r}: deep|deepscan|bulk|queued")
    rg = RaftGroups(GROUPS, PEERS, log_slots=LOG_SLOTS,
                    submit_slots=SUBMIT_SLOTS,
                    config=Config(use_pallas=use_pallas(),
                                  append_window=max(4, SUBMIT_SLOTS),
                                  applies_per_round=max(4, SUBMIT_SLOTS),
                                  pool_budgets=POOL_BUDGETS,
                                  resource=RESOURCE_CONFIGS["counter"],
                                  telemetry=TELEMETRY,
                                  monotone_tag_accept=(
                                      mode in ("deep", "deepscan"))))
    per_group = knobs.get_int(
        "COPYCAT_BENCH_HOST_BURST",
        default=SUBMIT_SLOTS * (8 if mode != "queued" else 1))
    log(f"bench[host:{mode}]: G={GROUPS} P={PEERS} {per_group} "
        f"ops/group/burst; device={jax.devices()[0].platform}")
    rg.wait_for_leaders()
    groups = np.repeat(np.arange(GROUPS), per_group)
    driver = BulkDriver(rg, deep_scan=(mode == "deepscan"))

    lat_p50 = lat_p99 = 0.0

    def burst() -> tuple[float, dict | None]:
        if mode != "queued":
            res = driver.drive(groups, ap.OP_LONG_ADD, 1)
            return groups.size / res.wall_s, res.latency_percentiles_ms()
        t0 = time.perf_counter()
        tags = rg.submit_batch(groups, ap.OP_LONG_ADD, 1).tolist()
        rg.run_until(tags, max_rounds=120)
        return len(tags) / (time.perf_counter() - t0), None

    burst()  # warm (jit compile + first transfers)
    best = 0.0
    reps = []
    for rep in range(REPEATS):
        with xla_trace(PROFILE_DIR if rep == 0 else None):
            ops, pct = burst()
        if ops >= best and pct is not None:
            lat_p50, lat_p99 = pct["p50"], pct["p99"]  # pair with `value`
        best = max(best, ops)
        reps.append(ops)
        log(f"bench[host:{mode}]: rep {rep}: {ops:,.0f} committed "
            f"ops/sec host-observed")
    out = {
        "metric": (f"host_observed_committed_ops_per_sec_{GROUPS}_groups"
                   + {"deep": "", "deepscan": "_scan", "bulk": "_sync",
                      "queued": "_queued"}[mode]),
        "value": round(best, 1),
        "unit": "ops/sec",
        "vs_baseline": round(best / NORTH_STAR_OPS, 4),
        **spread(reps),
    }
    if mode != "queued":
        # client-observed submit->result latency (ms, best-rep cadence)
        out["p50_latency_ms"] = round(lat_p50, 3)
        out["p99_latency_ms"] = round(lat_p99, 3)
    else:
        lat = rg.metrics.histogram("commit_latency_rounds")
        out["p50_commit_latency_rounds"] = lat.percentile(50)
        out["p99_commit_latency_rounds"] = lat.percentile(99)
    METRICS_SNAPSHOTS["driver"] = rg.metrics.snapshot()
    if rg.telemetry is not None:
        METRICS_SNAPSHOTS["device"] = rg.device_snapshot()
    return out


def run_session() -> dict:
    """Client-visible throughput through the SESSIONED client runtime
    (``models/session_client.BulkSessionClient`` — the unified plane,
    VERDICT r4 #2): ``COPYCAT_BENCH_SESSIONS`` sessions over one client
    share one deep drive per flush; every op carries (session, seq), is
    exactly-once deduplicated, and its result is correlated into the
    session cache. This is the reference-shaped client contract
    (Copycat client runtime, SURVEY.md §2.3) riding the north-star
    plane; round-5 target ≥100k committed ops/s on one chip."""
    from .models import BulkSessionClient, RaftGroups

    n_sessions = knobs.get_int("COPYCAT_BENCH_SESSIONS")
    rg = RaftGroups(GROUPS, PEERS, log_slots=LOG_SLOTS,
                    submit_slots=SUBMIT_SLOTS,
                    config=Config(use_pallas=use_pallas(),
                                  append_window=max(4, SUBMIT_SLOTS),
                                  applies_per_round=max(4, SUBMIT_SLOTS),
                                  pool_budgets=POOL_BUDGETS,
                                  resource=RESOURCE_CONFIGS["counter"],
                                  telemetry=TELEMETRY,
                                  monotone_tag_accept=True))
    per_group = knobs.get_int("COPYCAT_BENCH_HOST_BURST",
                              default=SUBMIT_SLOTS * 8)
    log(f"bench[session]: G={GROUPS} P={PEERS} {n_sessions} sessions, "
        f"{per_group} ops/group/burst; "
        f"device={jax.devices()[0].platform}")
    rg.wait_for_leaders()
    client = BulkSessionClient(
        rg, deep_scan=knobs.get_bool("COPYCAT_BENCH_SESSION_SCAN"))
    sessions = [client.open_session() for _ in range(n_sessions)]
    # each session owns an equal slice of the groups (disjoint groups
    # keep per-session FIFO independent of scheduling order)
    slices = np.array_split(np.arange(GROUPS), n_sessions)

    def burst() -> float:
        t0 = time.perf_counter()
        total = 0
        for s, sl in zip(sessions, slices):
            seqs = s.submit_batch(np.repeat(sl, per_group),
                                  ap.OP_LONG_ADD, 1)
            total += seqs.size
        n = client.flush()
        assert n == total
        return total / (time.perf_counter() - t0)

    burst()  # warm (jit compile + first transfers)
    best = 0.0
    reps = []
    for rep in range(REPEATS):
        with xla_trace(PROFILE_DIR if rep == 0 else None):
            ops = burst()
        best = max(best, ops)
        reps.append(ops)
        log(f"bench[session]: rep {rep}: {ops:,.0f} committed "
            f"session ops/sec client-observed")
    # exactly-once spot check: group 0's counter equals its op count
    s0 = sessions[0]
    q = s0.submit(0, ap.OP_VALUE_GET)
    client.flush()
    expect = per_group * (len(reps) + 1)
    assert s0.result(q) == expect, (s0.result(q), expect)
    METRICS_SNAPSHOTS["driver"] = rg.metrics.snapshot()
    if rg.telemetry is not None:
        METRICS_SNAPSHOTS["device"] = rg.device_snapshot()
    return {
        "metric": f"session_committed_ops_per_sec_{GROUPS}_groups",
        "value": round(best, 1),
        "unit": "ops/sec",
        "vs_baseline": round(best / NORTH_STAR_OPS, 4),
        "sessions": n_sessions,
        **spread(reps),
    }


def spread(reps: list[float]) -> dict:
    """Per-rep min/median/max so regressions are distinguishable from
    tunnel weather (±30% session swings — BENCH_SCENARIOS.md note ¹)."""
    s = sorted(reps)
    return {"reps_min": round(s[0], 1),
            "reps_median": round(s[len(s) // 2], 1),
            "reps_max": round(s[-1], 1),
            "reps_n": len(s)}


def run_spi() -> dict:
    """Manager-level throughput THROUGH the public resource API: N
    device-backed ``DistributedAtomicLong`` instances hosted by an
    ``AtomixServer(executor="tpu")``, pipelined increments from real
    client sessions; measures client-visible committed ops/sec through
    the full stack — session protocol → CPU Raft log → shared-window
    device engine. The reference's public API *is* its data path
    (``Atomix.java:205``); this scenario keeps ours honest about that.
    """
    import asyncio

    from .atomic import DistributedAtomicLong
    from .io.local import LocalServerRegistry, LocalTransport
    from .io.transport import Address
    from .manager.atomix import AtomixClient, AtomixServer
    from .manager.device_executor import DeviceEngineConfig

    instances = knobs.get_int("COPYCAT_BENCH_SPI_INSTANCES")
    bursts = knobs.get_int("COPYCAT_BENCH_SPI_BURSTS")
    # int (default): device-resident counters — the device fast path.
    # str: DistributedMap puts with STRING values, which every device-
    # backed map refuses onto int32 lanes and takes through the host
    # SHADOW instead — this measures the documented K/V degradation
    # cliff (VERDICT r4 missing #4; reference DistributedMap.java:54
    # takes arbitrary K/V, so the cliff must be a number, not a
    # surprise).
    payload = knobs.get_str("COPYCAT_BENCH_SPI_PAYLOAD")
    if payload not in ("int", "str"):
        raise SystemExit(f"COPYCAT_BENCH_SPI_PAYLOAD={payload!r}: int|str")
    # Engine pool provisioning (DeviceEngineConfig.resource): the counter
    # scenario hosts only value registers, and pool state is carried
    # through every engine round — counters-only provisioning measured
    # the loaded round 9.3 -> 5.1 ms at capacity 1024 on CPU. The str
    # (shadow-cliff) scenario needs the map pool live, so it keeps all
    # pools; override with COPYCAT_BENCH_SPI_POOLS=counters|all.
    pools = knobs.get_str("COPYCAT_BENCH_SPI_POOLS",
                          default="counters" if payload == "int" else "all")
    if pools not in ("counters", "all"):
        raise SystemExit(f"COPYCAT_BENCH_SPI_POOLS={pools!r}: counters|all")
    engine_pools = (ResourceConfig.counters_only() if pools == "counters"
                    else None)
    # client pipelining depth: each session keeps WAVES commands in
    # flight per instance (sequential per instance — FIFO preserved).
    # Depth 2 overlaps the client/submit stack with the window pump
    # (~+40% measured on CPU); deeper convoys fragment the window into
    # more partial pump cycles and lose it again.
    waves = knobs.get_int("COPYCAT_BENCH_SPI_WAVES")
    # local (in-memory, default) | tcp (asyncio sockets) | native (C++
    # epoll + C codec): same wire format, so the knob isolates the IO
    # stack's share of the client-visible number
    transport_kind = knobs.get_str("COPYCAT_BENCH_SPI_TRANSPORT")
    capacity = 1 << max(4, (instances - 1).bit_length())  # pow2 >= instances
    # Engine ring: the spi steady state keeps ≤1 in-flight entry per
    # group (one public op per instance per burst), so the 32-slot ring
    # round 5 ran was 2x headroom paid in one-hot pass width every
    # round; 16 measured -0.3 ms/loaded round at G=1024 with identical
    # commit behavior. Override for deeper per-group pipelining.
    log_slots = knobs.get_int("COPYCAT_BENCH_SPI_LOG_SLOTS")
    registry = LocalServerRegistry()  # shared by both ends in local mode

    def make_transport():
        if transport_kind == "local":
            return LocalTransport(registry)
        if transport_kind == "tcp":
            from .io.tcp import TcpTransport
            return TcpTransport()
        if transport_kind == "native":
            from .io.native import NativeTcpTransport, native_available
            if not native_available():
                raise SystemExit("native transport unavailable "
                                 "(make -C native)")
            return NativeTcpTransport()
        raise SystemExit(
            f"COPYCAT_BENCH_SPI_TRANSPORT={transport_kind!r}: "
            "local|tcp|native")

    async def drive() -> dict:
        addr = Address("127.0.0.1", 15999)
        # ONE transport shared by both ends (client()/server() hand out
        # independent endpoints): the native kind owns an epoll thread
        # pair, and a second instance would contend for the single core
        # this scenario documents — shut it down in the finally.
        transport = make_transport()
        server = AtomixServer(
            addr, [addr], transport,
            election_timeout=0.5, heartbeat_interval=0.1,
            session_timeout=60.0, executor="tpu",
            engine_config=DeviceEngineConfig(
                capacity=capacity, num_peers=PEERS, log_slots=log_slots,
                submit_slots=4, resource=engine_pools))
        await server.open()
        client = AtomixClient([addr], transport,
                              session_timeout=60.0)
        await client.open()
        try:
            t0 = time.perf_counter()
            if payload == "str":
                from .collections import DistributedMap
                counters = await asyncio.gather(
                    *(client.get(f"map{i}", DistributedMap)
                      for i in range(instances)))
            else:
                counters = await asyncio.gather(
                    *(client.get(f"ctr{i}", DistributedAtomicLong)
                      for i in range(instances)))
            engine = server.server.state_machine.device_engine
            on_device = engine._next_group
            log(f"bench[spi:{payload}]: {instances} instances created in "
                f"{time.perf_counter() - t0:.1f}s; {on_device} on-device "
                f"(capacity {capacity}); device="
                f"{jax.devices()[0].platform}")
            _bench_gc_tune()

            lats: list[float] = []
            n_op = [0]

            async def one(c) -> None:
                for _ in range(waves):
                    t = time.perf_counter()
                    if payload == "str":
                        # string values refuse the int32 lanes -> host
                        # shadow
                        n_op[0] += 1
                        await c.put("k", f"v{n_op[0]}")
                    else:
                        await c.add_and_get(1)
                    lats.append(time.perf_counter() - t)

            reps = []
            best_lats: list[float] = []
            burst_ops = instances * waves
            for rep in range(bursts):
                lats.clear()
                t0 = time.perf_counter()
                await asyncio.gather(*(one(c) for c in counters))
                dt = time.perf_counter() - t0
                ops = burst_ops / dt
                reps.append(ops)
                if ops >= max(reps):
                    best_lats = list(lats)  # latencies pair with `value`
                log(f"bench[spi]: rep {rep}: {burst_ops} ops in {dt:.3f}s "
                    f"-> {ops:,.0f} client-visible ops/sec")
            lat = np.asarray(sorted(best_lats))
            rounds0 = engine._groups.rounds if engine._groups else 0
            # --metrics-json artifact: every bench run leaves an
            # attributable snapshot (server lanes + transport + client)
            METRICS_SNAPSHOTS["server"] = server.server.stats_snapshot()
            METRICS_SNAPSHOTS["client"] = client.client.metrics.snapshot()
            capture_series("server", server.server)
            return {
                "metric": (f"spi_client_visible_ops_per_sec_{instances}"
                           f"_device_instances"
                           + ("" if transport_kind == "local"
                              else f"_{transport_kind}")
                           + ("" if payload == "int" else "_shadow")
                           + ("" if waves == 1 else f"_w{waves}")),
                "transport": transport_kind,
                "payload": payload,
                "pipeline_depth": waves,
                "value": round(max(reps), 1),
                "unit": "ops/sec",
                "vs_baseline": round(max(reps) / NORTH_STAR_OPS, 4),
                "p50_latency_ms": round(float(lat[len(lat) // 2]) * 1e3, 3),
                "p99_latency_ms": round(
                    float(lat[int(len(lat) * 0.99)]) * 1e3, 3),
                "on_device_instances": int(on_device),
                "engine_rounds": int(rounds0),
                **spread(reps),
            }
        finally:
            await _close_spi_stack(client, server, transport)

    return asyncio.run(drive())


def run_readmix() -> dict:
    """Read-dominated (90/10 read/write) traffic THROUGH the public
    resource API: the readmix production coordination workloads actually
    run. N device-backed ``DistributedAtomicLong`` instances on an
    ``AtomixServer(executor="tpu")``; per burst every instance commits
    ONE increment and serves ``COPYCAT_BENCH_READMIX_READS`` (default 9)
    gets. Reads ride the no-append query lane: client-side they coalesce
    into per-consistency ``QueryBatchRequest``s, server-side the batched
    read pump (``COPYCAT_SERVER_READ_PUMP`` — the A/B knob this
    scenario exists to measure) windows them across sessions, pays the
    consistency gate once per window, and evaluates the device-eligible
    set through one ``query_step`` engine round. Headline value =
    client-visible READS/sec; writes and total ops ride along in the
    artifact. ``COPYCAT_BENCH_READMIX_LEVEL`` picks the facade
    consistency (atomic = lease-gated reads, default; sequential;
    linearizable = quorum-confirmed reads)."""
    import asyncio

    from .atomic import DistributedAtomicLong
    from .io.local import LocalServerRegistry, LocalTransport
    from .io.transport import Address
    from .manager.atomix import AtomixClient, AtomixServer
    from .manager.device_executor import DeviceEngineConfig
    from .resource.consistency import Consistency

    instances = knobs.get_int("COPYCAT_BENCH_SPI_INSTANCES")
    bursts = knobs.get_int("COPYCAT_BENCH_SPI_BURSTS")
    reads_per_write = knobs.get_int("COPYCAT_BENCH_READMIX_READS")
    level = knobs.get_str("COPYCAT_BENCH_READMIX_LEVEL")
    facade_level = {"atomic": Consistency.ATOMIC,
                    "sequential": Consistency.SEQUENTIAL,
                    "none": Consistency.NONE}.get(level)
    if facade_level is None and level != "linearizable":
        raise SystemExit(
            f"COPYCAT_BENCH_READMIX_LEVEL={level!r}: "
            "atomic|sequential|none|linearizable")
    read_pump = knobs.get_bool("COPYCAT_SERVER_READ_PUMP")
    capacity = 1 << max(4, (instances - 1).bit_length())
    log_slots = knobs.get_int("COPYCAT_BENCH_SPI_LOG_SLOTS")
    registry = LocalServerRegistry()

    async def drive() -> dict:
        addr = Address("127.0.0.1", 15998)
        transport = LocalTransport(registry)
        server = AtomixServer(
            addr, [addr], transport,
            election_timeout=0.5, heartbeat_interval=0.1,
            session_timeout=60.0, executor="tpu",
            engine_config=DeviceEngineConfig(
                capacity=capacity, num_peers=PEERS, log_slots=log_slots,
                submit_slots=4,
                resource=ResourceConfig.counters_only()))
        await server.open()
        client = AtomixClient([addr], LocalTransport(registry),
                              session_timeout=60.0)
        await client.open()
        try:
            t0 = time.perf_counter()
            counters = await asyncio.gather(
                *(client.get(f"ctr{i}", DistributedAtomicLong)
                  for i in range(instances)))
            if facade_level is not None:
                for c in counters:
                    c.with_consistency(facade_level)
            else:
                # full quorum-confirmed reads: the facade vocabulary tops
                # out at ATOMIC (bounded); override the read level only
                for c in counters:
                    c._read_cl = "linearizable"
            engine = server.server.state_machine.device_engine
            on_device = engine._next_group
            log(f"bench[readmix:{level}]: {instances} instances in "
                f"{time.perf_counter() - t0:.1f}s; {on_device} on-device; "
                f"read pump {'ON' if read_pump else 'OFF'}; device="
                f"{jax.devices()[0].platform}")
            _bench_gc_tune()

            async def one(c) -> None:
                await c.add_and_get(1)
                for _ in range(reads_per_write):
                    await c.get()

            burst_reads = instances * reads_per_write
            burst_ops = instances * (reads_per_write + 1)
            reps = []
            for rep in range(bursts):
                t0 = time.perf_counter()
                await asyncio.gather(*(one(c) for c in counters))
                dt = time.perf_counter() - t0
                reads_s = burst_reads / dt
                reps.append(reads_s)
                log(f"bench[readmix]: rep {rep}: {burst_reads} reads + "
                    f"{instances} writes in {dt:.3f}s -> "
                    f"{reads_s:,.0f} reads/sec "
                    f"({burst_ops / dt:,.0f} ops/sec)")
            # correctness spot check: every counter saw every increment
            v = await counters[0].get()
            assert v == bursts, (v, bursts)
            METRICS_SNAPSHOTS["server"] = server.server.stats_snapshot()
            METRICS_SNAPSHOTS["client"] = client.client.metrics.snapshot()
            best = max(reps)
            return {
                "metric": (f"readmix_client_visible_reads_per_sec_"
                           f"{instances}_device_instances_{level}"
                           + ("" if read_pump else "_per_op")),
                "value": round(best, 1),
                "unit": "reads/sec",
                "vs_baseline": round(best / NORTH_STAR_OPS, 4),
                "read_pump": read_pump,
                "read_level": level,
                "reads_per_write": reads_per_write,
                "ops_per_sec": round(best * (reads_per_write + 1)
                                     / reads_per_write, 1),
                "on_device_instances": int(on_device),
                **spread(reps),
            }
        finally:
            await _close_spi_stack(client, server)

    return asyncio.run(drive())


def run_fanout() -> dict:
    """Edge read tier bench (docs/EDGE_READS.md): few writers, a sweep
    of reader-session counts, a zipfian key mix — the
    millions-of-readers shape in miniature. With ``COPYCAT_EDGE_READS``
    on (default), each reader's first SEQUENTIAL read per counter
    subscribes and seeds its client-local replica; every later read
    serves from it, so read throughput scales with the reader count
    while the cluster sees only the writers' commits and the
    (reader-count-bounded) seed reads. With the knob off, every read
    pays the server round-trip and reads/s is pinned to the server's
    read-window capacity — the A/B this scenario exists to measure.

    The artifact also carries the trace proof: a cache-served read's
    assembled trace consists solely of client-side spans
    (``client.edge_serve`` — no ``proxy.hop``, no ``quorum.wait``)."""
    import asyncio
    import random as _random

    from .atomic import DistributedAtomicLong
    from .io.local import LocalServerRegistry, LocalTransport
    from .io.transport import Address
    from .manager.atomix import AtomixClient, AtomixServer
    from .resource.consistency import Consistency
    from .utils import tracing
    from .utils.tasks import spawn

    edge_on = knobs.get_bool("COPYCAT_EDGE_READS")
    reader_counts = [int(x) for x in knobs.get_str(
        "COPYCAT_BENCH_FANOUT_READERS").split(",") if x.strip()]
    writers = knobs.get_int("COPYCAT_BENCH_FANOUT_WRITERS")
    n_keys = knobs.get_int("COPYCAT_BENCH_FANOUT_KEYS")
    reads_per_reader = knobs.get_int("COPYCAT_BENCH_FANOUT_READS")
    bursts = knobs.get_int("COPYCAT_BENCH_FANOUT_BURSTS")
    zipf_s = knobs.get_float("COPYCAT_BENCH_FANOUT_ZIPF")
    rng = _random.Random(17)
    draw_rank = zipf_sampler(rng, n_keys, zipf_s)

    async def drive() -> dict:
        registry = LocalServerRegistry()
        addr = Address("127.0.0.1", 15997)
        # the coordination-plane shape: CPU machines, one member — the
        # cluster is deliberately NOT the interesting axis here, the
        # client-side replica is
        server = AtomixServer(addr, [addr], LocalTransport(registry),
                              election_timeout=0.5,
                              heartbeat_interval=0.1,
                              session_timeout=60.0)
        await server.open()
        writer_clients = [AtomixClient([addr], LocalTransport(registry),
                                       session_timeout=60.0)
                          for _ in range(writers)]
        await asyncio.gather(*(c.open() for c in writer_clients))
        readers: list[AtomixClient] = []
        try:
            writer_ctrs = [
                await asyncio.gather(
                    *(c.get(f"ctr{k}", DistributedAtomicLong)
                      for k in range(n_keys)))
                for c in writer_clients]
            log(f"bench[fanout]: edge reads "
                f"{'ON' if edge_on else 'OFF'}; {writers} writers, "
                f"{n_keys} keys, readers sweep {reader_counts}")
            _bench_gc_tune()
            sweep: dict[str, dict] = {}
            reps_largest: list[float] = []
            write_stop = [False]
            writes_done = [0]

            async def write_loop(ctrs) -> None:
                while not write_stop[0]:
                    await ctrs[draw_rank()].add_and_get(1)
                    writes_done[0] += 1

            async def reader_session() -> None:
                c = AtomixClient([addr], LocalTransport(registry),
                                 session_timeout=60.0)
                await c.open()
                readers.append(c)

            def server_reads() -> int:
                snap = server.server.metrics.snapshot()
                return sum(v for k, v in snap.items()
                           if isinstance(v, (int, float))
                           and str(k).startswith("query_reads"))

            for count in reader_counts:
                while len(readers) < count:
                    grow = min(64, count - len(readers))
                    await asyncio.gather(
                        *(reader_session() for _ in range(grow)))
                plans = []
                for c in readers[:count]:
                    keys = [draw_rank() for _ in range(reads_per_reader)]
                    cached = {}
                    for k in set(keys):
                        if k not in cached:
                            h = await c.get(f"ctr{k}",
                                            DistributedAtomicLong)
                            h.with_consistency(Consistency.SEQUENTIAL)
                            cached[k] = h
                    plans.append([cached[k] for k in keys])

                async def read_plan(plan) -> None:
                    for h in plan:
                        await h.get()

                burst_reads = count * reads_per_reader
                reps = []
                for rep in range(bursts):
                    write_stop[0] = False
                    writes_done[0] = 0
                    wtasks = [spawn(write_loop(cs), name="fanout-writer")
                              for cs in writer_ctrs]
                    reads_before = server_reads()
                    t0 = time.perf_counter()
                    await asyncio.gather(*(read_plan(p) for p in plans))
                    dt = time.perf_counter() - t0
                    write_stop[0] = True
                    await asyncio.gather(*wtasks)
                    reads_s = burst_reads / dt
                    reps.append(reads_s)
                    log(f"bench[fanout]: {count} readers rep {rep}: "
                        f"{burst_reads} reads in {dt:.3f}s -> "
                        f"{reads_s:,.0f} reads/s; "
                        f"{writes_done[0] / dt:,.0f} committed writes/s; "
                        f"{server_reads() - reads_before} server reads")
                    if count == reader_counts[-1]:
                        last = (dt, writes_done[0],
                                server_reads() - reads_before)
                sweep[str(count)] = {
                    "reads_per_sec": round(max(reps), 1),
                    "reps": [round(r, 1) for r in reps],
                }
                if count == reader_counts[-1]:
                    reps_largest = reps
                    dt, wd, sr = last
                    sweep[str(count)]["committed_writes_per_sec"] = \
                        round(wd / dt, 1)
                    sweep[str(count)]["server_reads_last_rep"] = sr

            # trace proof: a cache-served read's assembled trace is
            # client-side only (no proxy.hop / quorum.wait / group.*)
            trace_proof = None
            if edge_on:
                tracing.enable()
                try:
                    await plans[0][0].get()  # warmed: serves locally
                    proof_id = next(
                        (tid for tid, spans in tracing.TRACER.traces().items()
                         if any(s.name == "client.edge_serve"
                                for s in spans)), None)
                    if proof_id is not None:
                        spans = tracing.TRACER.spans_for(proof_id)
                        assembly = tracing.assemble_trace(
                            proof_id,
                            {"client": [s.as_dict() for s in spans]})
                        names = sorted({s.name for s in spans})
                        trace_proof = {
                            "spans": names,
                            "members": assembly.get("members", []),
                            "client_only": all(
                                n.startswith("client.") for n in names),
                            "incomplete": assembly.get("incomplete"),
                        }
                finally:
                    tracing.disable()

            # aggregate the reader clients' edge families for the
            # artifact (the CI smoke asserts these keys)
            agg: dict[str, float] = {}
            for c in readers:
                for k, v in c.client.metrics.snapshot().items():
                    if str(k).startswith("edge.") \
                            and isinstance(v, (int, float)):
                        agg[str(k)] = agg.get(str(k), 0) + v
            METRICS_SNAPSHOTS["server"] = server.server.stats_snapshot()
            METRICS_SNAPSHOTS["edge_clients"] = agg
            largest = reader_counts[-1]
            best = max(reps_largest)
            return {
                "metric": (f"fanout_reads_per_sec_{largest}_readers"
                           + ("" if edge_on else "_server")),
                "value": round(best, 1),
                "unit": "reads/sec",
                "vs_baseline": round(best / NORTH_STAR_OPS, 4),
                "edge_reads": edge_on,
                "readers": reader_counts,
                "writers": writers,
                "keys": n_keys,
                "sweep": sweep,
                "trace": trace_proof,
                **spread(reps_largest),
            }
        finally:
            write_stop[0] = True
            for c in readers + writer_clients:
                try:
                    await asyncio.wait_for(c.close(), 5)
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
            await asyncio.wait_for(server.close(), 10)

    return asyncio.run(drive())


def _cluster_machine_types():
    """Op types + counter machine shared by the cluster-shaped scenarios
    (``cluster``/``sharded``/``recovery``/``compartment``). The classes
    live in ``copycat_tpu.testing.counter_machine`` — jax-free, so the
    compartment scenario's spawned member/ingress processes can host the
    same machine (same serialization ids) without importing this module."""
    from .testing.counter_machine import ClusterAdd, ClusterGet, \
        CounterMachine

    return ClusterAdd, ClusterGet, CounterMachine


def _cluster_storage_factory(level_name: str):
    """(build_storage(i), cleanup) for a bench cluster: MEMORY needs no
    directories; MAPPED/DISK get one temp directory per member, removed
    by ``cleanup()``."""
    import shutil
    import tempfile

    from .server.log import Storage, StorageLevel

    level = StorageLevel(level_name)
    if level is StorageLevel.MEMORY:
        return (lambda i: Storage(StorageLevel.MEMORY)), (lambda: None)
    dirs: list[str] = []

    def build(i: int) -> Storage:
        d = tempfile.mkdtemp(prefix=f"copycat-bench-{level.value}-{i}-")
        dirs.append(d)
        return Storage(level, d)

    def cleanup() -> None:
        for d in dirs:
            shutil.rmtree(d, ignore_errors=True)

    return build, cleanup


def run_cluster() -> dict:
    """The first replicated-cluster bench: committed ops/sec through a
    REAL N-member ``RaftServer`` cluster (leader election, pipelined
    AppendEntries streams, quorum commit) on the local transport, writes
    through the public ``RaftClient`` API (micro-batched sessioned
    commands, exactly-once seqs).

    A fixed per-message-leg delay (``COPYCAT_BENCH_CLUSTER_DELAY_MS``,
    default 2.0 ms — a realistic same-region cross-AZ RTT of ~4 ms) is
    injected via the transport nemesis so the leader->follower
    replication stream actually pays wire latency: stop-and-wait
    replication (``COPYCAT_REPL_PIPELINE=0``) is then capped at
    window/RTT entries/s per peer, which is exactly what the pipelined
    plane exists to break. The A/B pair for PERF.md round 10 is this
    scenario run twice, once per lane.

    ``--storage {memory,mapped,disk}`` (env
    ``COPYCAT_BENCH_CLUSTER_STORAGE``, default memory) runs the same
    workload on a durable log level, so the durability A/B cost — fsync
    policy, segment persistence, snapshot cadence — is MEASURED, with
    the level and the ``snap.*`` family recorded in the
    ``--metrics-json`` artifact."""
    import asyncio

    from .client.client import RaftClient
    from .io.local import LocalServerRegistry, LocalTransport
    from .io.transport import Address
    from .server.raft import LEADER, RaftServer

    ClusterAdd, ClusterGet, CounterMachine = _cluster_machine_types()
    storage_level = knobs.get_str("COPYCAT_BENCH_CLUSTER_STORAGE").lower()
    members = knobs.get_int("COPYCAT_BENCH_CLUSTER_MEMBERS")
    n_clients = knobs.get_int("COPYCAT_BENCH_CLUSTER_CLIENTS")
    ops_per_client = knobs.get_int("COPYCAT_BENCH_CLUSTER_OPS")
    bursts = knobs.get_int("COPYCAT_BENCH_CLUSTER_BURSTS")
    delay_ms = knobs.get_float("COPYCAT_BENCH_CLUSTER_DELAY_MS")
    pipelined = knobs.get_bool("COPYCAT_REPL_PIPELINE")

    async def drive() -> dict:
        registry = LocalServerRegistry()
        addrs = [Address("local", 17000 + i) for i in range(members)]
        build_storage, cleanup_storage = _cluster_storage_factory(
            storage_level)
        servers = [
            RaftServer(addr, addrs,
                       LocalTransport(registry, local_address=addr),
                       CounterMachine(),
                       storage=build_storage(i),
                       election_timeout=0.5, heartbeat_interval=0.1,
                       session_timeout=120.0)
            for i, addr in enumerate(addrs)]
        await asyncio.gather(*(s.open() for s in servers))
        deadline = time.perf_counter() + 30
        leader = None
        while time.perf_counter() < deadline:
            leader = next((s for s in servers if s.role == LEADER), None)
            if leader is not None:
                break
            await asyncio.sleep(0.02)
        assert leader is not None, "no leader elected"
        clients = [RaftClient(addrs, LocalTransport(registry),
                              session_timeout=120.0)
                   for _ in range(n_clients)]
        await asyncio.gather(*(c.open() for c in clients))
        # inject wire latency only once the cluster + sessions are up:
        # the measured path is the replicated write plane, not elections
        nem = registry.attach_nemesis()
        nem.set_delay(delay_ms / 1e3)
        log(f"bench[cluster]: {members} members, {n_clients} clients x "
            f"{ops_per_client} ops/burst, {delay_ms} ms/leg, "
            f"storage={storage_level} "
            f"({'pipelined' if pipelined else 'stop-and-wait'} replication, "
            f"window {leader._repl_window}, depth {leader._repl_depth})")
        _bench_gc_tune()
        burst_ops = n_clients * ops_per_client
        try:
            async def one(client: RaftClient, key: str) -> None:
                futs = [client.submit_command_nowait(
                    ClusterAdd(key=key, delta=1))
                    for _ in range(ops_per_client)]
                await asyncio.gather(*futs)

            reps = []
            for rep in range(bursts):
                t0 = time.perf_counter()
                await asyncio.gather(*(one(c, f"k{i}")
                                       for i, c in enumerate(clients)))
                dt = time.perf_counter() - t0
                ops = burst_ops / dt
                reps.append(ops)
                log(f"bench[cluster]: rep {rep}: {burst_ops} committed ops "
                    f"in {dt:.3f}s -> {ops:,.0f} ops/sec")
            # exactly-once spot check THROUGH the public read API: every
            # client's counter saw every increment exactly once
            for i, c in enumerate(clients):
                v = await c.submit(ClusterGet(key=f"k{i}"))
                assert v == bursts * ops_per_client, (i, v)
            # replicated-state spot check: a quorum actually holds the data
            await asyncio.sleep(0.3)
            converged = sum(
                1 for s in servers
                if s.state_machine.data.get("k0") == bursts * ops_per_client)
            assert converged >= len(servers) // 2 + 1, converged
            METRICS_SNAPSHOTS["server"] = leader.stats_snapshot()
            METRICS_SNAPSHOTS["client"] = clients[0].metrics.snapshot()
            capture_series("server", leader)
            best = max(reps)
            ack = leader.metrics.histogram("repl.ack_ms")
            raft_snap = METRICS_SNAPSHOTS["server"]["raft"]
            return {
                "metric": (f"cluster_committed_ops_per_sec_{members}_members"
                           + ("" if storage_level == "memory"
                              else f"_{storage_level}")
                           + ("" if pipelined else "_stop_and_wait")),
                "value": round(best, 1),
                "unit": "ops/sec",
                "vs_baseline": round(best / NORTH_STAR_OPS, 4),
                "repl_pipeline": pipelined,
                "repl_window": leader._repl_window,
                "repl_depth": leader._repl_depth,
                "delay_ms_per_leg": delay_ms,
                "clients": n_clients,
                "storage_level": storage_level,
                "fsync": leader.storage.fsync,
                "snapshots_enabled": bool(
                    leader._snap_enabled and leader._snapshots is not None),
                # the durability A/B rides the artifact: every snap.*
                # series the leader registry holds (zeroes on memory)
                "snap": {k: v for k, v in raft_snap.items()
                         if k.startswith("snap.")},
                "p50_repl_ack_ms": round(ack.percentile(50), 3),
                "p99_repl_ack_ms": round(ack.percentile(99), 3),
                **spread(reps),
            }
        finally:
            nem.heal()
            for c in clients:
                try:
                    await asyncio.wait_for(c.close(), 10)
                except Exception:
                    pass
            for s in servers:
                try:
                    await asyncio.wait_for(s.close(), 10)
                except Exception:
                    pass
            cleanup_storage()

    return asyncio.run(drive())


def run_sharded() -> dict:
    """Multi-raft keyspace sharding bench (docs/SHARDING.md): committed
    ops/sec through a 3-member cluster hosting ``--groups N`` Raft
    groups, many clients, zipfian keys, writes through the public
    ``RaftClient`` API.

    The wire shape is CROSS-REGION: a fixed per-leg nemesis delay
    (``COPYCAT_BENCH_SHARDED_DELAY_MS``, default 100 ms -> 200 ms RTT)
    makes the bounded replication pipeline the binding constraint — a
    single ordered log cannot carry more than
    ``COPYCAT_REPL_MAX_INFLIGHT / RTT`` entries/s no matter how fast the
    leader's core is, because the in-flight cap exists to bound
    slow-follower memory (docs/REPLICATION.md). Sharding multiplies
    that ceiling: G groups = G independent windowed streams, with
    leadership spread so each member sequences ~G/N of them. The A/B
    for PERF.md round 12 is this scenario at ``--groups 4`` vs
    ``--groups 1`` (the single-group plane, which
    ``COPYCAT_MULTI_GROUP=0`` pins bit-identically)."""
    import asyncio
    import random as _random

    from .client.client import RaftClient
    from .io.local import LocalServerRegistry, LocalTransport
    from .io.transport import Address
    from .server.raft import LEADER, RaftServer

    ClusterAdd, ClusterGet, CounterMachine = _cluster_machine_types()
    groups = max(1, knobs.get_int("COPYCAT_BENCH_SHARDED_GROUPS"))
    members = knobs.get_int("COPYCAT_BENCH_CLUSTER_MEMBERS")
    n_clients = knobs.get_int("COPYCAT_BENCH_SHARDED_CLIENTS")
    ops_per_client = knobs.get_int("COPYCAT_BENCH_SHARDED_OPS")
    bursts = knobs.get_int("COPYCAT_BENCH_SHARDED_BURSTS")
    n_keys = knobs.get_int("COPYCAT_BENCH_SHARDED_KEYS")
    zipf_s = knobs.get_float("COPYCAT_BENCH_SHARDED_ZIPF")
    delay_ms = knobs.get_float("COPYCAT_BENCH_SHARDED_DELAY_MS")

    # zipfian key draw, deterministic: inverse-CDF over 1/rank^s
    rng = _random.Random(12)
    draw_rank = zipf_sampler(rng, n_keys, zipf_s)

    def draw_key() -> str:
        return f"user:{draw_rank()}"

    async def drive() -> dict:
        registry = LocalServerRegistry()
        addrs = [Address("local", 17100 + i) for i in range(members)]
        servers = [
            RaftServer(addr, addrs,
                       LocalTransport(registry, local_address=addr),
                       (lambda g: CounterMachine()), groups=groups,
                       election_timeout=0.5, heartbeat_interval=0.1,
                       session_timeout=120.0)
            for addr in addrs]
        await asyncio.gather(*(s.open() for s in servers))
        deadline = time.perf_counter() + 30
        while time.perf_counter() < deadline:
            led = {g.group_id for s in servers for g in s.groups
                   if g.role == LEADER}
            if len(led) == groups:
                break
            await asyncio.sleep(0.02)
        led = {g.group_id for s in servers for g in s.groups
               if g.role == LEADER}
        assert len(led) == groups, \
            f"groups without a leader: {set(range(groups)) - led}"
        clients = [RaftClient(addrs, LocalTransport(registry),
                              session_timeout=120.0)
                   for _ in range(n_clients)]
        await asyncio.gather(*(c.open() for c in clients))
        # inject wire latency only once the cluster + sessions are up
        nem = registry.attach_nemesis()
        nem.set_delay(delay_ms / 1e3)
        groups_led = {str(s.address): sum(1 for g in s.groups
                                          if g.role == LEADER)
                      for s in servers}
        log(f"bench[sharded]: {members} members x {groups} groups "
            f"(led: {groups_led}), {n_clients} clients x "
            f"{ops_per_client} ops/burst, zipf s={zipf_s} over "
            f"{n_keys} keys, {delay_ms} ms/leg")
        _bench_gc_tune()
        burst_ops = n_clients * ops_per_client
        expected: dict[str, int] = {}
        try:
            # streamed micro-batches: each event-loop turn stages one
            # CHUNK-op batch (the client's turn coalescing), many batches
            # in flight per session up to CAP outstanding ops — the
            # pipelined ingress keeps every group's replication window
            # full for the whole burst. A whole-burst gather (or a
            # half-wave gate) serializes on BATCH completion, i.e. on the
            # hottest group's queue, and measures commit latency convoys
            # instead of stream throughput.
            chunk = 64
            cap = max(chunk * 2, 768)

            async def one(client: RaftClient, keys: list) -> None:
                outstanding = 0
                wake = asyncio.Event()
                futs: list = []

                def done(_f) -> None:
                    nonlocal outstanding
                    outstanding -= 1
                    if outstanding <= cap // 2:
                        wake.set()

                i = 0
                while i < len(keys):
                    while outstanding >= cap:
                        wake.clear()
                        await wake.wait()
                    part = keys[i:i + chunk]
                    i += len(part)
                    for k in part:
                        fut = client.submit_command_nowait(
                            ClusterAdd(key=k, delta=1))
                        fut.add_done_callback(done)
                        futs.append(fut)
                    outstanding += len(part)
                    await asyncio.sleep(0)  # turn boundary: one batch
                await asyncio.gather(*futs)

            reps = []
            for rep in range(bursts):
                burst_keys = []
                for _ in range(n_clients):
                    keys = [draw_key() for _ in range(ops_per_client)]
                    for k in keys:
                        expected[k] = expected.get(k, 0) + 1
                    burst_keys.append(keys)
                t0 = time.perf_counter()
                await asyncio.gather(*(one(c, ks) for c, ks
                                       in zip(clients, burst_keys)))
                dt = time.perf_counter() - t0
                ops = burst_ops / dt
                reps.append(ops)
                log(f"bench[sharded]: rep {rep}: {burst_ops} committed "
                    f"ops in {dt:.3f}s -> {ops:,.0f} ops/sec")
            # causal-tracing wave (COPYCAT_BENCH_SHARDED_TRACE=1): one
            # traced micro-batch AFTER the timed bursts (the perf
            # numbers stay untraced) whose keys cover every group in a
            # single event-loop turn — one CommandBatchRequest fanning
            # out across group leaders, assembled into the cross-member
            # waterfall for the --metrics-json artifact.
            trace_section = None
            if knobs.get_bool("COPYCAT_BENCH_SHARDED_TRACE"):
                import zlib

                from .utils import tracing as _tracing

                _tracing.TRACER.clear()
                _tracing.enable()
                try:
                    cover: dict[int, str] = {}
                    i = 0
                    while len(cover) < groups:
                        k = f"trace:{i}"
                        cover.setdefault(zlib.crc32(k.encode()) % groups, k)
                        i += 1
                    tkeys = [cover[g] for g in sorted(cover)]
                    for k in tkeys:
                        expected[k] = expected.get(k, 0) + 1
                    await asyncio.gather(*(
                        clients[0].submit_command_nowait(
                            ClusterAdd(key=k, delta=1)) for k in tkeys))
                finally:
                    _tracing.disable()
                best_asm = None
                for tid, spans in _tracing.TRACER.traces().items():
                    if not any(s.name == "client.submit" for s in spans):
                        continue
                    asm = _tracing.assemble_trace(tid, {"ring": spans})
                    if best_asm is None or (len(asm["members"])
                                            > len(best_asm["members"])):
                        best_asm = asm
                assert best_asm is not None, "traced wave lost its trace"
                trace_section = {
                    "trace_id": best_asm["trace"],
                    "e2e_ms": best_asm["e2e_ms"],
                    "critical_path_ms": best_asm["critical_path_ms"],
                    "incomplete": best_asm["incomplete"],
                    "members": [m for m in best_asm["members"]
                                if m != "client"],
                    "phases": sorted({s["name"]
                                      for s in best_asm["spans"]}),
                    "waterfall": _tracing.render_waterfall(best_asm),
                }
                log("bench[sharded]: traced waterfall\n"
                    + trace_section["waterfall"])
                # the ingress member's snapshot carries the
                # latency.ingress_queue_ms / proxy_hop_ms phases the CI
                # smoke asserts (metrics.server below is member 0, which
                # may not have been the traced client's ingress)
                ingress_addr = clients[0]._connected_to
                ingress = next((s for s in servers
                                if s.address == ingress_addr), servers[0])
                METRICS_SNAPSHOTS["ingress"] = ingress.stats_snapshot()
            # exactly-once spot check THROUGH the public read API:
            # zipfian increments landed exactly once per key
            for k in sorted(expected)[:16]:
                v = await clients[0].submit(ClusterGet(key=k))
                assert v == expected[k], (k, v, expected[k])
            METRICS_SNAPSHOTS["server"] = servers[0].stats_snapshot()
            METRICS_SNAPSHOTS["client"] = clients[0].metrics.snapshot()
            capture_series("server", servers[0])
            best = max(reps)
            # routing mix: commands per owning group, summed over every
            # member's ingress counters
            routing_mix = {str(g): 0 for g in range(groups)}
            if groups > 1:
                for s in servers:
                    for g in range(groups):
                        routing_mix[str(g)] += s._metrics.counter(
                            "shard.routed", group=str(g)).value
            per_group_commit = {
                str(g.group_id): max(s.groups[g.group_id].commit_index
                                     for s in servers)
                for g in servers[0].groups}
            result_extra = ({"trace": trace_section}
                            if trace_section is not None else {})
            return {
                "metric": (f"sharded_committed_ops_per_sec_{members}"
                           f"_members_{groups}_groups"),
                "value": round(best, 1),
                "unit": "ops/sec",
                **result_extra,
                "vs_baseline": round(best / NORTH_STAR_OPS, 4),
                "groups": groups,
                "groups_led": groups_led,
                "per_group_commit": per_group_commit,
                "routing_mix": routing_mix,
                "delay_ms_per_leg": delay_ms,
                "clients": n_clients,
                "zipf_s": zipf_s,
                "keys": n_keys,
                "repl_max_inflight": servers[0]._repl_max_inflight,
                **spread(reps),
            }
        finally:
            nem.heal()
            for c in clients:
                try:
                    await asyncio.wait_for(c.close(), 10)
                except Exception:
                    pass
            for s in servers:
                try:
                    await asyncio.wait_for(s.close(), 10)
                except Exception:
                    pass

    return asyncio.run(drive())


def run_apply() -> dict:
    """Apply-limited bench (docs/SHARDING.md "Apply ordering"):
    committed ops/sec through the public resource API on a single
    member hosting ``--groups N`` Raft groups, many sessions, a
    hot/cold zipfian key mix over device counters, and an INTERLEAVED
    eligible/ineligible op stream — the shape that collapses the
    contiguous vector classifier to the per-entry lane.

    No replication wire, no nemesis delay: commit is immediate, so the
    apply path IS the bottleneck. Eligible sessions stream single-
    command ``get_and_set`` writes (device rows — deliberately NOT the
    ``DistributedAtomicLong`` CAS-retry loop, whose client-side
    contention on hot zipf keys would measure retry storms, not the
    apply plane) against per-session instance handles of a SHARED zipf
    keyspace; a ``COPYCAT_BENCH_APPLY_INELIGIBLE`` fraction of sessions
    streams host-shadow STRING sets instead — every shadow entry is an
    ineligible log entry interleaved between other sessions' device
    rows. The A/B is this scenario with ``COPYCAT_PARALLEL_APPLY=0
    COPYCAT_APPLY_FUSE=0`` (the contiguous/per-group plane): there each
    interleaved ineligible entry CUTS the vector run (toward the
    per-entry lane as the mix rises), while the dependency classifier
    spans them — disjoint keys, disjoint sessions — and the fused lane
    merges all groups' staged runs into ONE ``DeviceEngine.run_vector``
    per server turn (``apply.*`` family in the artifact;
    ``runs_per_dispatch`` ≈ groups is the one-device-round-per-turn
    evidence)."""
    import asyncio
    import random as _random

    from .atomic import DistributedAtomicValue
    from .io.local import LocalServerRegistry, LocalTransport
    from .io.transport import Address
    from .manager.atomix import AtomixClient, AtomixServer
    from .manager.device_executor import DeviceEngineConfig

    groups = max(1, knobs.get_int("COPYCAT_BENCH_APPLY_GROUPS"))
    n_sessions = knobs.get_int("COPYCAT_BENCH_APPLY_SESSIONS")
    ops_per_session = knobs.get_int("COPYCAT_BENCH_APPLY_OPS")
    bursts = knobs.get_int("COPYCAT_BENCH_APPLY_BURSTS")
    n_keys = knobs.get_int("COPYCAT_BENCH_APPLY_KEYS")
    zipf_s = knobs.get_float("COPYCAT_BENCH_APPLY_ZIPF")
    ineligible = knobs.get_float("COPYCAT_BENCH_APPLY_INELIGIBLE")

    # zipfian key draw, deterministic: inverse-CDF over 1/rank^s
    rng = _random.Random(17)
    draw_key = zipf_sampler(rng, n_keys, zipf_s)

    capacity = 1 << max(4, (n_keys + n_sessions - 1).bit_length())

    async def drive() -> dict:
        registry = LocalServerRegistry()
        (addr,) = (Address("local", 17500),)
        server = AtomixServer(
            addr, [addr], LocalTransport(registry),
            election_timeout=0.5, heartbeat_interval=0.1,
            session_timeout=120.0, executor="tpu", groups=groups,
            engine_config=DeviceEngineConfig(
                capacity=capacity, num_peers=3, log_slots=32,
                submit_slots=8,
                resource=ResourceConfig.counters_only()))
        await server.open()
        sessions = [AtomixClient([addr], LocalTransport(registry),
                                 session_timeout=120.0)
                    for _ in range(n_sessions)]
        await asyncio.gather(*(c.open() for c in sessions))
        rs = server.server
        # a positive fraction always yields >= 1 shadow session (the
        # interleave must exist to be measured); exactly 0 yields NONE —
        # the pure-eligible datapoint that isolates fusion gain from
        # spanning gain
        n_shadow = 0 if ineligible <= 0 else min(
            n_sessions - 1, max(1, round(n_sessions * ineligible)))
        n_elig = n_sessions - n_shadow
        try:
            # Per-session instance handles to the SHARED zipf keyspace:
            # instances of one value share a resource (and its device
            # row), so two sessions writing key k are same-key dependent
            # — the hot/cold mix — while every session still submits
            # through its own connection and seq space.
            handles = await asyncio.gather(*(
                asyncio.gather(*(sessions[i].get(
                    f"k{k}", DistributedAtomicValue)
                    for k in range(n_keys)))
                for i in range(n_elig)))
            # Shadow value names brute-forced against the crc32 router
            # so EVERY group's log interleaves ineligible entries —
            # hash-luck leaving a group shadow-free would hand that
            # group contiguous runs even on the knobs-off plane,
            # measuring nothing.
            import zlib as _zlib

            def _shadow_name(j: int) -> str:
                name, t = f"sh{j}", 0
                while _zlib.crc32(name.encode()) % groups != j % groups:
                    t += 1
                    name = f"sh{j}x{t}"
                return name

            shadows = await asyncio.gather(
                *(sessions[n_elig + j].get(
                    _shadow_name(j), DistributedAtomicValue)
                  for j in range(n_shadow)))
            log(f"bench[apply]: 1 member x {groups} groups, "
                f"{n_elig} device + {n_shadow} host-shadow sessions "
                f"x {ops_per_session} ops/burst, zipf s={zipf_s} over "
                f"{n_keys} keys, parallel_apply={rs._parallel_apply} "
                f"fuse={rs._apply_fuse}")
            _bench_gc_tune()

            # Continuous submission under a bounded-in-flight window per
            # session (no chunk barriers): barriers lock every session
            # to the commit-turn cadence, collapsing the applied windows
            # to a couple of entries each — a commit-latency bench, not
            # an apply bench. A standing backlog keeps windows large.
            # The shadow window is SHALLOW (2), deliberately: a
            # contiguous flush of N ineligible entries cuts a
            # contiguous-plane run once, not N times, so deep shadow
            # pipelining hides the interleave the scenario exists to
            # measure.
            # Both lanes scatter each submission a few seeded
            # ready-queue iterations deep before sending: sessions
            # woken by the same ack wave otherwise submit in the ack
            # order of the PREVIOUS window — a self-reinforcing pattern
            # that parks every shadow entry at a window EDGE, where it
            # cuts nothing and the interleave the scenario exists to
            # measure never forms. The yields put shadow entries in the
            # MIDDLE of device runs, log-order-for-real.
            async def one_device(i: int, script: list) -> None:
                h = handles[i]
                sem = asyncio.Semaphore(8)

                async def go(k: int, v: int, yields: int) -> None:
                    async with sem:
                        for _ in range(yields):
                            await asyncio.sleep(0)
                        await h[k].get_and_set(v)
                await asyncio.gather(*(go(k, v, rng.randrange(8))
                                       for k, v in script))

            async def one_shadow(j: int, script: list) -> None:
                sh = shadows[j]
                sem = asyncio.Semaphore(2)

                async def go(s: str, yields: int) -> None:
                    async with sem:
                        for _ in range(yields):
                            await asyncio.sleep(0)
                        await sh.set(s)
                await asyncio.gather(*(go(s, rng.randrange(8))
                                       for s in script))

            # a shadow session's shallow (2-deep) stream covers ~1/4
            # the ops of a pipelined (8-deep) device session in the
            # same wall window — shorter scripts keep the two streams
            # co-terminous, so the interleave lasts the whole burst
            shadow_ops = max(2, ops_per_session // 4)
            burst_ops = n_elig * ops_per_session + n_shadow * shadow_ops

            # warmup wave (untimed, untraced): the first engine round
            # pays jit compilation — hundreds of ms that would otherwise
            # dominate BOTH planes' first rep and the apply-latency p99
            await asyncio.gather(
                *(one_device(i, [(draw_key(), 1)
                                 for _ in range(ops_per_session // 2)])
                  for i in range(n_elig)),
                *(one_shadow(j, [f"w{j}x{t}"
                                 for t in range(shadow_ops // 2)])
                  for j in range(n_shadow)))

            # Trace EVERY timed request (both A/B planes pay the same
            # ≤2% overhead — PERF.md round 13): the latency.apply_ms
            # phase histogram is the scenario's tail-latency judge —
            # commit → commit-future resolved, exactly the window the
            # parallel/fused plane compresses.
            from .utils import tracing as _tracing
            _tracing.TRACER.clear()
            _tracing.enable()  # warmup above ran untraced: the phase
            # histograms hold timed-burst samples only
            reps = []
            seq = 0
            for rep in range(bursts):
                escripts = [[(draw_key(), rng.randrange(1 << 20))
                             for _ in range(ops_per_session)]
                            for _ in range(n_elig)]
                sscripts = []
                for _ in range(n_shadow):
                    script = []
                    for _ in range(shadow_ops):
                        seq += 1
                        script.append(f"s{seq}")
                    sscripts.append(script)
                t0 = time.perf_counter()
                await asyncio.gather(
                    *(one_device(i, s) for i, s in enumerate(escripts)),
                    *(one_shadow(j, s) for j, s in enumerate(sscripts)))
                dt = time.perf_counter() - t0
                ops = burst_ops / dt
                reps.append(ops)
                log(f"bench[apply]: rep {rep}: {burst_ops} committed ops "
                    f"in {dt:.3f}s -> {ops:,.0f} ops/sec")
            METRICS_SNAPSHOTS["server"] = rs.stats_snapshot()
            METRICS_SNAPSHOTS["client"] = sessions[0].client \
                .metrics.snapshot()
            _tracing.disable()
            # apply-phase tail latency (commit -> futures resolved) per
            # group; the headline p99 is the worst group's — commands
            # spread across groups, so one group's stalled apply IS the
            # client-visible tail
            lat = {}
            for grp in rs.groups:
                h = grp.metrics.histogram("latency.apply_ms")
                if h.count:
                    lat[str(grp.group_id)] = round(h.percentile(99), 3)
            fused = rs._metrics.counter("apply.fused_dispatches").value
            fused_rows = rs._metrics.histogram("apply.fused_rows")
            fused_groups = rs._metrics.histogram("apply.fused_groups")
            runs = spans = conflicts = vops = 0
            for grp in rs.groups:
                runs += grp.metrics.counter("vector_runs").value
                vops += grp.metrics.counter("vector_ops").value
                spans += grp.metrics.counter("apply.parallel_spans").value
                conflicts += grp.metrics.counter(
                    "apply.conflict_flushes").value
            best = max(reps)
            return {
                "metric": (f"apply_committed_ops_per_sec_{n_sessions}"
                           f"_sessions_{groups}_groups"),
                "value": round(best, 1),
                "unit": "ops/sec",
                "vs_baseline": round(best / NORTH_STAR_OPS, 4),
                "groups": groups,
                "sessions": n_sessions,
                "keys": n_keys,
                "zipf_s": zipf_s,
                "ineligible_fraction": ineligible,
                "parallel_apply": rs._parallel_apply,
                "apply_fuse": rs._apply_fuse,
                "latency_apply_p99_ms": max(lat.values()) if lat else 0.0,
                "latency_apply_p99_ms_per_group": lat,
                "apply": {
                    "vector_runs": runs,
                    "vector_ops": vops,
                    "parallel_spans": spans,
                    "conflict_flushes": conflicts,
                    "fused_dispatches": fused,
                    "rows_per_dispatch": round(
                        fused_rows.mean, 2) if fused else 0.0,
                    "groups_per_dispatch": round(
                        fused_groups.mean, 2) if fused else 0.0,
                    "runs_per_dispatch": round(
                        runs / fused, 2) if fused else 0.0,
                },
                **spread(reps),
            }
        finally:
            for c in sessions:
                try:
                    await asyncio.wait_for(c.close(), 10)
                except Exception:
                    pass
            try:
                await asyncio.wait_for(server.close(), 10)
            except Exception:
                pass

    return asyncio.run(drive())


def run_recovery() -> dict:
    """Crash-recovery bench (docs/DURABILITY.md): a fresh member catching
    up to a loaded cluster, snapshot-install vs full log replay.

    Two passes over the same workload on a durable storage level:

    1. **snapshot** (COPYCAT_SNAPSHOTS=1): the running members snapshot at
       the configured cadence and prefix-truncate their logs; the joiner
       catches up via snapshot-install streaming + the retained log tail.
    2. **replay** (COPYCAT_SNAPSHOTS=0): the replay-only plane — the
       joiner receives every entry ever committed through the append
       stream.

    Headline value is the speedup (replay catch-up seconds / snapshot
    catch-up seconds); the artifact carries both times, the log shapes,
    and the leader's + joiner's full ``snap.*`` metric families."""
    import asyncio

    from .client.client import RaftClient
    from .io.local import LocalServerRegistry, LocalTransport
    from .io.transport import Address
    from .server.raft import LEADER, RaftServer

    ClusterAdd, ClusterGet, CounterMachine = _cluster_machine_types()
    ops = knobs.get_int("COPYCAT_BENCH_RECOVERY_OPS")
    storage_level = knobs.get_str("COPYCAT_BENCH_RECOVERY_STORAGE").lower()
    snap_entries = str(knobs.get_int("COPYCAT_BENCH_RECOVERY_SNAP_ENTRIES"))
    n_clients = knobs.get_int("COPYCAT_BENCH_RECOVERY_CLIENTS")

    async def one_pass(snapshots_on: bool, port_base: int) -> dict:
        saved = {k: os.environ.get(k) for k in (
            "COPYCAT_SNAPSHOTS", "COPYCAT_SNAPSHOT_ENTRIES",
            "COPYCAT_SNAPSHOT_RETAIN")}
        os.environ["COPYCAT_SNAPSHOTS"] = "1" if snapshots_on else "0"
        os.environ["COPYCAT_SNAPSHOT_ENTRIES"] = snap_entries
        os.environ["COPYCAT_SNAPSHOT_RETAIN"] = "64"
        build_storage, cleanup_storage = _cluster_storage_factory(
            storage_level)
        registry = LocalServerRegistry()
        addrs = [Address("local", port_base + i) for i in range(3)]

        def build(i: int) -> RaftServer:
            return RaftServer(
                addrs[i], addrs,
                LocalTransport(registry, local_address=addrs[i]),
                CounterMachine(), storage=build_storage(i),
                election_timeout=0.5, heartbeat_interval=0.05,
                session_timeout=120.0)

        # seed: 2 of 3 members carry the workload (still a quorum); the
        # third joins only at catch-up time
        servers = [build(0), build(1)]
        clients: list[RaftClient] = []
        joiner = None
        try:
            await asyncio.gather(*(s.open() for s in servers))
            deadline = time.perf_counter() + 30
            leader = None
            while time.perf_counter() < deadline:
                leader = next((s for s in servers if s.role == LEADER), None)
                if leader is not None:
                    break
                await asyncio.sleep(0.02)
            assert leader is not None, "no leader elected"
            clients = [RaftClient(addrs[:2], LocalTransport(registry),
                                  session_timeout=120.0)
                       for _ in range(n_clients)]
            await asyncio.gather(*(c.open() for c in clients))
            per_client = ops // n_clients
            _bench_gc_tune()

            async def pump(client: RaftClient, key: str) -> None:
                futs = [client.submit_command_nowait(
                    ClusterAdd(key=key, delta=1)) for _ in range(per_client)]
                await asyncio.gather(*futs)

            t0 = time.perf_counter()
            await asyncio.gather(*(pump(c, f"k{i}")
                                   for i, c in enumerate(clients)))
            seed_s = time.perf_counter() - t0
            log(f"bench[recovery]: seeded {per_client * n_clients} ops in "
                f"{seed_s:.2f}s ({'snapshots' if snapshots_on else 'replay'}"
                f" pass); leader log [{leader.log.first_index}, "
                f"{leader.log.last_index}], snap_index "
                f"{leader._snap_index}")
            if snapshots_on:
                assert leader.log.prefix_index > 0, \
                    "cadence never truncated the log — raise OPS or " \
                    "lower COPYCAT_BENCH_RECOVERY_SNAP_ENTRIES"

            # catch-up: the fresh third member boots empty and joins
            joiner = build(2)
            t1 = time.perf_counter()
            await joiner.open()
            target = leader.commit_index
            deadline = time.perf_counter() + 120
            while (joiner.last_applied < target
                   and time.perf_counter() < deadline):
                await asyncio.sleep(0.005)
            catchup_s = time.perf_counter() - t1
            assert joiner.last_applied >= target, \
                (joiner.last_applied, target)
            # correctness: the joiner's machine converged to the truth
            assert joiner.state_machine.data.get("k0") == per_client
            log(f"bench[recovery]: joiner caught up {target} entries in "
                f"{catchup_s:.3f}s "
                f"({'install+tail' if snapshots_on else 'full replay'})")
            return {
                "catchup_s": catchup_s,
                "seed_s": seed_s,
                "commit_index": target,
                "leader_first_index": leader.log.first_index,
                "leader_prefix_index": leader.log.prefix_index,
                "installs_sent": leader.metrics.snapshot().get(
                    "snap.installs_sent", 0),
                "leader_stats": leader.stats_snapshot(),
                "joiner_stats": joiner.stats_snapshot(),
            }
        finally:
            for c in clients:
                try:
                    await asyncio.wait_for(c.close(), 10)
                except Exception:
                    pass
            for s in servers + ([joiner] if joiner is not None else []):
                try:
                    await asyncio.wait_for(s.close(), 10)
                except Exception:
                    pass
            cleanup_storage()
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    snap_pass = asyncio.run(one_pass(True, 17100))
    replay_pass = asyncio.run(one_pass(False, 17200))
    assert snap_pass["installs_sent"] >= 1, snap_pass
    speedup = replay_pass["catchup_s"] / max(snap_pass["catchup_s"], 1e-9)
    METRICS_SNAPSHOTS["server"] = snap_pass["leader_stats"]
    METRICS_SNAPSHOTS["joiner"] = snap_pass["joiner_stats"]
    return {
        "metric": f"recovery_catchup_speedup_vs_replay_{storage_level}",
        "value": round(speedup, 3),
        "unit": "x",
        "vs_baseline": round(speedup, 4),
        "storage_level": storage_level,
        "snapshot_entries": int(snap_entries),
        "seeded_ops": ops,
        "catchup_s_snapshot": round(snap_pass["catchup_s"], 4),
        "catchup_s_replay": round(replay_pass["catchup_s"], 4),
        "commit_index": snap_pass["commit_index"],
        "leader_first_index_snapshot": snap_pass["leader_first_index"],
        "installs_sent": snap_pass["installs_sent"],
        "snap": {k: v
                 for k, v in snap_pass["leader_stats"]["raft"].items()
                 if k.startswith("snap.")},
    }


def run_compartment() -> dict:
    """Compartmentalized deployment bench (docs/DEPLOYMENT.md): committed
    ops/sec through a REAL multi-process topology — one OS process per
    Raft member and per standalone ingress proxy, real sockets, real
    fsync — swept across ingress-tier widths
    (``COPYCAT_BENCH_COMPARTMENT_TIERS``, default ``1,2,4``).

    The compartmentalization claim under test (PAPERS.md, "Scaling
    Replicated State Machines with Compartmentalization"): the ingress
    role — client connections, session fan-out, per-group routing, the
    global ingress batching — scales out independently of the write
    quorums it fronts. In-process benches cannot observe this (every
    tier shares one GIL); here each width is a fresh supervised
    topology and the clients pin round-robin across the tier, so adding
    ingress processes adds real CPU parallelism to exactly one role.

    Per-tier attribution rides the artifact from the existing
    ``latency.*`` plane: every ingress process records
    ``latency.ingress_queue_ms`` / ``latency.proxy_hop_ms`` for every
    forward (scraped over its stats port), and the client records
    ``submit_latency_ms`` end-to-end.

    The nemesis phase (``COPYCAT_BENCH_COMPARTMENT_NEMESIS``, on by
    default, widest tier only) SIGKILLs one member AND one ingress proxy
    mid-load through the supervisor: clients re-route within the tier,
    the supervisor restarts the corpses with backoff, and the read-back
    asserts ZERO lost acknowledged writes — every key's replicated
    counter covers every acked increment, and exceeds it only by
    in-doubt (INDETERMINATE) submissions, the exactly-once contract.

    ``COPYCAT_INGRESS_TIER=0`` is the A/B lane: no ingress processes
    deploy and clients dial the members' in-server ingress directly
    (width 0 in the artifact)."""
    import asyncio
    import random as _random

    from .client.client import PinnedConnectionStrategy, RaftClient
    from .deploy.supervisor import Supervisor
    from .deploy.topology import TopologySpec
    from .io.tcp import TcpTransport
    from .io.transport import Address
    from .server.stats import fetch_stats
    from .testing.counter_machine import ClusterAdd, ClusterGet

    members = max(1, knobs.get_int("COPYCAT_BENCH_COMPARTMENT_MEMBERS"))
    groups = max(1, knobs.get_int("COPYCAT_BENCH_COMPARTMENT_GROUPS"))
    n_clients = knobs.get_int("COPYCAT_BENCH_COMPARTMENT_CLIENTS")
    ops_per_client = knobs.get_int("COPYCAT_BENCH_COMPARTMENT_OPS")
    bursts = knobs.get_int("COPYCAT_BENCH_COMPARTMENT_BURSTS")
    n_keys = knobs.get_int("COPYCAT_BENCH_COMPARTMENT_KEYS")
    zipf_s = knobs.get_float("COPYCAT_BENCH_COMPARTMENT_ZIPF")
    storage = knobs.get_str("COPYCAT_BENCH_COMPARTMENT_STORAGE")
    run_nemesis = knobs.get_bool("COPYCAT_BENCH_COMPARTMENT_NEMESIS")
    if knobs.get_bool("COPYCAT_INGRESS_TIER"):
        tiers = [max(1, int(w)) for w in knobs.get_str(
            "COPYCAT_BENCH_COMPARTMENT_TIERS").split(",") if w.strip()]
    else:
        # the A/B lane: no standalone tier, clients dial the members'
        # in-server ingress directly
        tiers = [0]
    machine = "copycat_tpu.testing.counter_machine:counter_machine"

    rng = _random.Random(12)
    draw_rank = zipf_sampler(rng, n_keys, zipf_s)

    def draw_key() -> str:
        return f"user:{draw_rank()}"

    async def load(client: RaftClient, keys: list,
                   acked: dict, indet: dict) -> None:
        """Streamed micro-batch writer (the sharded scenario's shape)
        that CLASSIFIES every outcome: resolved future = acknowledged
        (the server must never lose it), failed future = in-doubt.
        Chunked so a mid-load process kill leaves a bounded in-flight
        window to classify, not a whole burst."""
        chunk, cap = 64, 768
        pending: list = []
        for i in range(0, len(keys), chunk):
            part = keys[i:i + chunk]
            pending.extend(
                (k, client.submit_command_nowait(ClusterAdd(key=k,
                                                            delta=1)))
                for k in part)
            await asyncio.sleep(0)  # turn boundary: one staged batch
            while len(pending) >= cap:
                k, fut = pending.pop(0)
                try:
                    await fut
                    acked[k] = acked.get(k, 0) + 1
                except Exception:
                    indet[k] = indet.get(k, 0) + 1
        for k, fut in pending:
            try:
                await fut
                acked[k] = acked.get(k, 0) + 1
            except Exception:
                indet[k] = indet.get(k, 0) + 1

    async def scrape(spec: TopologySpec, names: list) -> dict:
        """Per-process ``/stats`` scrape -> the per-tier attribution
        block: ingress latency phases + forward counters per ingress
        process (an unreachable stats port records as ``None``, never
        drops the row)."""
        out: dict = {}
        for name in names:
            try:
                snap = json.loads(await fetch_stats(
                    spec.stats_addrs()[name], "/stats", timeout=5.0))
            except (OSError, RuntimeError, ValueError,
                    asyncio.TimeoutError):
                out[name] = None
                continue
            ing = snap.get("ingress", {})
            out[name] = {
                k: ing.get(k) for k in (
                    "latency.ingress_queue_ms", "latency.proxy_hop_ms",
                    "ingress.commands_forwarded", "ingress.sessions",
                    "ingress.proxy_retries", "ingress.reroutes")}
        return out

    async def run_width(width: int) -> dict:
        spec = TopologySpec.local(
            members=members, ingresses=width, groups=groups,
            storage=storage, machine=machine)
        sup = Supervisor(spec)
        await sup.open()
        clients: list[RaftClient] = []
        try:
            await sup.wait_healthy(timeout=180)
            addrs = [Address.parse(a) for a in spec.client_addrs()]
            clients = [
                RaftClient(addrs, TcpTransport(), session_timeout=120.0,
                           connection_strategy=PinnedConnectionStrategy(
                               addrs[i % len(addrs)]))
                for i in range(n_clients)]
            await asyncio.gather(*(c.open() for c in clients))
            # warmup: one committed write per client primes leader
            # views, session replicas and the disk lanes end to end
            await asyncio.gather(*(
                c.submit(ClusterAdd(key=f"warm:{i}", delta=1))
                for i, c in enumerate(clients)))
            log(f"bench[compartment]: width {width}: {members} member + "
                f"{width} ingress process(es), {groups} group(s), "
                f"{n_clients} clients x {ops_per_client} ops/burst, "
                f"zipf s={zipf_s} over {n_keys} keys, storage={storage}")
            _bench_gc_tune()
            burst_ops = n_clients * ops_per_client
            acked: dict[str, int] = {}
            indet: dict[str, int] = {}
            reps = []
            for rep in range(bursts):
                burst_keys = [[draw_key() for _ in range(ops_per_client)]
                              for _ in range(n_clients)]
                t0 = time.perf_counter()
                await asyncio.gather(*(
                    load(c, ks, acked, indet)
                    for c, ks in zip(clients, burst_keys)))
                dt = time.perf_counter() - t0
                ops = burst_ops / dt
                reps.append(ops)
                log(f"bench[compartment]: width {width} rep {rep}: "
                    f"{burst_ops} ops in {dt:.3f}s -> {ops:,.0f} ops/sec")
            attribution = await scrape(
                spec, [i.name for i in spec.ingresses])
            out = {
                "width": width,
                "ops_per_sec": round(max(reps), 1),
                "client_submit_ms": clients[0].metrics.histogram(
                    "submit_latency_ms").percentile(99),
                "ingress_attribution": attribution,
                **spread(reps),
            }
            if run_nemesis and width == max(tiers) and members >= 3:
                out["nemesis"] = await nemesis_phase(
                    sup, spec, clients, width, acked, indet)
            # zero lost acknowledged writes, every width: each touched
            # key's replicated counter covers every acked increment and
            # exceeds it only by in-doubt submissions
            lost = over = 0
            touched = sorted(acked)
            for i in range(0, len(touched), 256):
                part = touched[i:i + 256]
                got = await asyncio.gather(*(
                    clients[j % len(clients)].submit(ClusterGet(key=k))
                    for j, k in enumerate(part)))
                for k, v in zip(part, got):
                    if v < acked[k]:
                        lost += acked[k] - v
                    if v > acked[k] + indet.get(k, 0):
                        over += v - acked[k] - indet.get(k, 0)
            assert lost == 0, f"LOST {lost} acknowledged write(s)"
            assert over == 0, f"{over} duplicate apply(s) (exactly-once)"
            out["acked_ops"] = sum(acked.values())
            out["indeterminate_ops"] = sum(indet.values())
            out["lost_acked_writes"] = lost
            return out
        finally:
            for c in clients:
                try:
                    await asyncio.wait_for(c.close(), 10)
                except Exception:
                    pass
            await sup.close()

    async def nemesis_phase(sup: Supervisor, spec: TopologySpec,
                            clients: list, width: int,
                            acked: dict, indet: dict) -> dict:
        """kill -9 one member AND one ingress proxy mid-load through the
        supervisor (the process-level nemesis): clients re-route within
        the ingress tier, the supervisor restarts the corpses with
        backoff, and the caller's read-back proves zero lost
        acknowledged writes."""
        from .utils.tasks import spawn as spawn_task

        # A SIGKILLed MEMORY-storage member restarts blank — no log, no
        # (term, voted_for) — which violates Raft's persistence
        # assumptions: the blank member can grant a vote that elects a
        # leader missing an acked entry, a TRUE lost write. The member
        # kill therefore requires a durable level; on memory the
        # nemesis kills only the (stateless-by-design) ingress.
        kill_member = storage != "memory" and members >= 3
        log(f"bench[compartment]: nemesis: kill -9"
            + (" member-1" if kill_member else "")
            + (" + ingress-0" if width else "")
            + f" under load (width {width}, storage={storage})")
        keys = [[draw_key() for _ in range(ops_per_client)]
                for _ in range(n_clients)]
        tasks = [spawn_task(load(c, ks, acked, indet),
                            name="compartment-nemesis-load")
                 for c, ks in zip(clients, keys)]
        try:
            await asyncio.sleep(0.15)  # mid-load, not before it
            ok_m, detail_m = (sup.kill("member-1") if kill_member
                              else (False, f"member kill skipped on "
                                           f"{storage} storage"))
            await asyncio.sleep(0.15)
            ok_i, detail_i = (sup.kill("ingress-0") if width
                              else (False, "no ingress tier"))
            await asyncio.gather(*tasks)
        finally:
            for t in tasks:
                t.cancel()
        # both corpses must come back under supervision before teardown
        # (restart-with-backoff is half the nemesis claim)
        deadline = time.monotonic() + 60
        victims = ((["member-1"] if kill_member else [])
                   + (["ingress-0"] if width else []))
        while time.monotonic() < deadline:
            status = sup.status()["children"]
            if all(status[v]["state"] == "running"
                   and status[v]["pid"] for v in victims):
                break
            await asyncio.sleep(0.25)
        status = sup.status()["children"]
        return {
            "killed": {"member": detail_m if ok_m else None,
                       "ingress": detail_i if ok_i else None},
            "restarts": {v: status[v]["restarts"] for v in victims},
            "restored": all(status[v]["state"] == "running"
                            for v in victims),
        }

    async def drive() -> dict:
        widths = []
        for width in tiers:
            widths.append(await run_width(width))
        by_width = {str(w["width"]): w["ops_per_sec"] for w in widths}
        best = max(w["ops_per_sec"] for w in widths)
        base = widths[0]["ops_per_sec"]
        nemesis = next((w.get("nemesis") for w in widths
                        if "nemesis" in w), None)
        METRICS_SNAPSHOTS["compartment"] = {
            str(w["width"]): w["ingress_attribution"] for w in widths}
        return {
            "metric": (f"compartment_committed_ops_per_sec_{members}"
                       f"_members_{groups}_groups"),
            "value": best,
            "unit": "ops/sec",
            "vs_baseline": round(best / NORTH_STAR_OPS, 4),
            "members": members,
            "groups": groups,
            "storage_level": storage,
            "clients": n_clients,
            "zipf_s": zipf_s,
            "keys": n_keys,
            "ingress_tier": knobs.get_bool("COPYCAT_INGRESS_TIER"),
            "tier_widths": tiers,
            "ops_by_width": by_width,
            "scaling_vs_width1": {
                k: round(v / base, 3) for k, v in by_width.items()},
            "widths": widths,
            **({"nemesis": nemesis} if nemesis is not None else {}),
            "lost_acked_writes": sum(w["lost_acked_writes"]
                                     for w in widths),
        }

    return asyncio.run(drive())


def run_election() -> dict:
    """Config #2: forced leader churn; measures elections completed/sec.

    Election timeout knobs (COPYCAT_BENCH_TIMER_MIN/MAX) default to the
    engine's 4-9 here so the number stays comparable across rounds;
    shorter timers complete forced elections proportionally faster."""
    config = Config(use_pallas=use_pallas(),
                    timer_min=knobs.get_int("COPYCAT_BENCH_TIMER_MIN", default=4),
                    timer_max=knobs.get_int("COPYCAT_BENCH_TIMER_MAX", default=9),
                    resource=RESOURCE_CONFIGS["election"])
    key = jax.random.PRNGKey(0)
    key, init_key = jax.random.split(key)
    state = init_state(GROUPS, PEERS, LOG_SLOTS, init_key, config)
    deliver = full_delivery(GROUPS, PEERS)
    empty = empty_submits(GROUPS)
    jit_step = jax.jit(partial(step, config=config))

    log(f"bench[election]: G={GROUPS} P={PEERS} rounds={ROUNDS} "
        f"device={jax.devices()[0].platform}")
    state, key = elect_all(state, jit_step, empty, deliver, key, GROUPS)
    victims = isolation_masks(ROUNDS, GROUPS, PEERS, period=15, seed=2)

    def run(state, key):
        def body(carry, victim):
            state, key, prev = carry
            key, k = jax.random.split(key)
            dl = victim_deliver(victim, GROUPS, PEERS)
            state, out = step(state, empty, dl, k, config=config)
            changed = ((out.leader >= 0) & (out.leader != prev)).sum(
                dtype=jnp.int32)
            return (state, key, out.leader), changed
        # seed prev with the REAL current leaders so settled groups don't
        # count as spurious elections in the first round
        init = (state, key, current_leaders(state))
        (state, key, _), changes = jax.lax.scan(body, init, victims)
        return state, key, changes.sum()

    run_jit = jax.jit(run)
    state, key, n = run_jit(state, key)
    jax.block_until_ready(n)
    log(f"bench[election]: warmup saw {int(n)} leader changes")

    best = 0.0
    reps = []
    for rep in range(REPEATS):
        with xla_trace(PROFILE_DIR if rep == 0 else None):
            t0 = time.perf_counter()
            state, key, n = run_jit(state, key)
            n = int(jax.block_until_ready(n))
            dt = time.perf_counter() - t0
        rate = n / dt
        best = max(best, rate)
        reps.append(rate)
        log(f"bench[election]: rep {rep}: {n} elections in {dt:.3f}s "
            f"-> {rate:,.0f} elections/sec")

    return {
        "metric": f"elections_per_sec_{GROUPS}_groups_under_nemesis",
        "value": round(best, 1),
        "unit": "elections/sec",
        "vs_baseline": round(best / NORTH_STAR_OPS, 4),
        **spread(reps),
    }


def run_map_read() -> dict:
    """Config #3 variant, get-heavy: puts ride the log, gets ride the
    query lane with no log append — SEQUENTIAL (leader-served) by
    default, or lease-gated ATOMIC/BOUNDED_LINEARIZABLE reads with
    ``COPYCAT_BENCH_READ_LEVEL=atomic`` (reference
    ``Consistency.java:157-176``)."""
    read_level = knobs.get_str("COPYCAT_BENCH_READ_LEVEL")
    if read_level not in ("sequential", "atomic"):
        raise SystemExit(
            f"COPYCAT_BENCH_READ_LEVEL={read_level!r}: pick 'sequential' "
            f"or 'atomic' (a typo here would silently mislabel the metric)")
    config = Config(use_pallas=use_pallas(), append_window=max(4, SUBMIT_SLOTS),
                    applies_per_round=max(4, SUBMIT_SLOTS),
                    resource=RESOURCE_CONFIGS["map"])
    key = jax.random.PRNGKey(0)
    key, init_key = jax.random.split(key)
    state = init_state(GROUPS, PEERS, LOG_SLOTS, init_key, config)
    deliver = full_delivery(GROUPS, PEERS)
    ones = jnp.ones((GROUPS, SUBMIT_SLOTS), jnp.int32)
    puts = Submits(opcode=ones * ap.OP_MAP_PUT, a=tile_pattern([1, 2], GROUPS),
                   b=ones * 7, c=ones * 0, tag=ones, valid=ones.astype(bool))
    gets = Submits(opcode=ones * ap.OP_MAP_GET, a=tile_pattern([1, 2], GROUPS),
                   b=ones * 0, c=ones * 0, tag=ones, valid=ones.astype(bool))
    jit_step = jax.jit(partial(step, config=config))

    log(f"bench[map_read]: G={GROUPS} P={PEERS} rounds={ROUNDS} "
        f"{SUBMIT_SLOTS} puts (log) + {SUBMIT_SLOTS} {read_level} gets "
        f"(query lane) per group per round; "
        f"device={jax.devices()[0].platform}")
    state, key = elect_all(state, jit_step, empty_submits(GROUPS), deliver,
                           key, GROUPS)
    atomic = (jnp.ones((GROUPS, SUBMIT_SLOTS), bool)
              if read_level == "atomic" else None)

    def run(state, key):
        def body(carry, _):
            state, key, applied_prev = carry
            key, k = jax.random.split(key)
            state, _ = step(state, puts, deliver, k, config=config)
            _, served = query_step(state, gets, atomic, config=config)
            applied_now = jnp.max(state.applied_index, axis=1)
            n = jnp.sum(applied_now - applied_prev, dtype=jnp.int32) \
                + served.sum(dtype=jnp.int32)
            return (state, key, applied_now), n
        applied0 = jnp.max(state.applied_index, axis=1)
        (state, key, _), counts = jax.lax.scan(
            body, (state, key, applied0), None, length=ROUNDS)
        return state, key, counts.sum()

    run_jit = jax.jit(run)
    state, key, n = run_jit(state, key)
    jax.block_until_ready(n)
    log(f"bench[map_read]: warmup completed {int(n)} ops")

    best = 0.0
    reps = []
    for rep in range(REPEATS):
        with xla_trace(PROFILE_DIR if rep == 0 else None):
            t0 = time.perf_counter()
            state, key, n = run_jit(state, key)
            n = int(jax.block_until_ready(n))
            dt = time.perf_counter() - t0
        ops = n / dt
        best = max(best, ops)
        reps.append(ops)
        log(f"bench[map_read]: rep {rep}: {n} ops in {dt:.3f}s "
            f"-> {ops:,.0f} ops/sec ({dt / ROUNDS * 1e3:.2f} ms/round)")

    return {
        "metric": (f"map_ops_per_sec_{GROUPS}_groups_half_"
                   f"{read_level}_reads"),
        "value": round(best, 1),
        "unit": "ops/sec",
        "vs_baseline": round(best / NORTH_STAR_OPS, 4),
        **spread(reps),
    }


def run_host_read() -> dict:
    """Client-visible READ throughput: ``drive_queries`` bursts through
    the no-append query lane (``COPYCAT_BENCH_READ_LEVEL=atomic`` gates
    each slot on the leader lease — linearizable reads with zero log
    entries; default ``sequential``). The write path warms each group's
    counter first so reads return real state."""
    from .models import BulkDriver, RaftGroups

    read_level = knobs.get_str("COPYCAT_BENCH_READ_LEVEL")
    if read_level not in ("sequential", "atomic"):
        # causal/process serve identically to sequential here — accepting
        # them would mislabel the metric (same guard as run_map_read)
        raise SystemExit(
            f"COPYCAT_BENCH_READ_LEVEL={read_level!r}: pick 'sequential' "
            "or 'atomic'")
    rg = RaftGroups(GROUPS, PEERS, log_slots=LOG_SLOTS,
                    submit_slots=SUBMIT_SLOTS,
                    config=Config(use_pallas=use_pallas(),
                                  append_window=max(4, SUBMIT_SLOTS),
                                  applies_per_round=max(4, SUBMIT_SLOTS),
                                  monotone_tag_accept=True,
                                  resource=RESOURCE_CONFIGS["counter"]))
    per_group = knobs.get_int("COPYCAT_BENCH_HOST_BURST",
                              default=SUBMIT_SLOTS * 8)
    log(f"bench[host_read:{read_level}]: G={GROUPS} P={PEERS} "
        f"{per_group} reads/group/burst; device={jax.devices()[0].platform}")
    rg.wait_for_leaders()
    driver = BulkDriver(rg)
    driver.drive(np.arange(GROUPS), ap.OP_LONG_ADD, 7)  # warm + real state
    reads = np.repeat(np.arange(GROUPS), per_group)
    driver.drive_queries(reads[:GROUPS], ap.OP_VALUE_GET,
                         consistency=read_level)  # compile warm

    best, reps = 0.0, []
    for rep in range(REPEATS):
        t0 = time.perf_counter()
        got = driver.drive_queries(reads, ap.OP_VALUE_GET,
                                   consistency=read_level)
        dt = time.perf_counter() - t0
        if not (got == 7).all():
            raise SystemExit("host_read: wrong read results")
        ops = reads.size / dt
        best = max(best, ops)
        reps.append(ops)
        log(f"bench[host_read:{read_level}]: rep {rep}: {reads.size:,} "
            f"reads in {dt:.3f}s -> {ops:,.0f} reads/sec host-observed")
    return {
        "metric": (f"host_observed_{read_level}_reads_per_sec_"
                   f"{GROUPS}_groups"),
        "value": round(best, 1),
        "unit": "ops/sec",
        "vs_baseline": round(best / NORTH_STAR_OPS, 4),
        **spread(reps),
    }


def _artifact_meta() -> dict:
    """Attribution block for ``--metrics-json`` artifacts (schema in
    docs/OBSERVABILITY.md "Bench artifacts"): the git SHA, the explicit
    knob overrides, and a host fingerprint — without these two artifacts
    are not comparable (a different host or knob set is a different
    experiment, not a regression; the bench-baseline CI gate keys off
    this block when explaining a miss)."""
    import platform

    from .utils.buildinfo import git_sha

    return {
        "git_sha": git_sha(),
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "knobs": knobs.overrides(),
        "host": {
            "hostname": platform.node(),
            "machine": platform.machine(),
            "python": platform.python_version(),
            "cpus": os.cpu_count(),
            "backend": jax.default_backend(),
            "device_count": jax.device_count(),
        },
    }


def main() -> None:
    parser = argparse.ArgumentParser(prog="copycat-bench")
    parser.add_argument(
        "--metrics-json", default=None, metavar="PATH",
        help="write the result plus per-component metrics snapshots "
             "(server/transport/client registries) as one JSON artifact")
    parser.add_argument(
        "--storage", default=None, choices=["memory", "mapped", "disk"],
        help="log storage level for the cluster/recovery scenarios "
             "(envs COPYCAT_BENCH_CLUSTER_STORAGE / "
             "COPYCAT_BENCH_RECOVERY_STORAGE); the durability A/B knob")
    parser.add_argument(
        "--groups", default=None, type=int, metavar="N",
        help="Raft groups for the sharded/apply scenarios (envs "
             "COPYCAT_BENCH_SHARDED_GROUPS / COPYCAT_BENCH_APPLY_GROUPS);"
             " 1 = the single-group baseline, the sharding A/B knob "
             "(docs/SHARDING.md)")
    args, _ = parser.parse_known_args()
    if args.storage:
        os.environ["COPYCAT_BENCH_CLUSTER_STORAGE"] = args.storage
        os.environ["COPYCAT_BENCH_RECOVERY_STORAGE"] = args.storage
        os.environ["COPYCAT_BENCH_COMPARTMENT_STORAGE"] = args.storage
    if args.groups is not None:
        os.environ["COPYCAT_BENCH_SHARDED_GROUPS"] = str(args.groups)
        os.environ["COPYCAT_BENCH_APPLY_GROUPS"] = str(args.groups)
        os.environ["COPYCAT_BENCH_COMPARTMENT_GROUPS"] = str(args.groups)
    # Probe the accelerator before any in-process backend use — a dead
    # tunnel otherwise hangs device enumeration forever. When every
    # probe fails (BENCH_r05: rc=2 after 5 probes, a whole round's
    # artifact zeroed by env drift), fall back to CPU with
    # ``"degraded": true`` stamped in the artifact instead of exiting
    # FATAL: a degraded-but-parseable number keeps the bench trajectory
    # comparable across env weather. COPYCAT_BENCH_NO_CPU_FALLBACK=1
    # restores the hard exit for pipelines that must not record CPU
    # numbers under a TPU label.
    from .utils.platform import enable_compilation_cache, require_devices
    degraded = False
    try:
        require_devices(env="COPYCAT_BENCH_DEVICE_TIMEOUT")
    except SystemExit:
        if knobs.get_bool("COPYCAT_BENCH_NO_CPU_FALLBACK"):
            raise
        log("bench: accelerator unreachable after all probes — "
            "DEGRADED CPU fallback (JAX_PLATFORMS=cpu)")
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
        degraded = True
    enable_compilation_cache()
    # The bench holds its OWN profiler reference for the whole run: the
    # scenario's servers acquire/release around their lifetime, so by
    # artifact-write time their refs are gone and the singleton would
    # be torn down — this ref keeps the sampled window alive for the
    # top-frame summary below. COPYCAT_PROFILE=0 -> None -> no
    # "profile" key in the artifact (A/B).
    from .utils import profiler as _profiler
    bench_profiler = _profiler.acquire()
    if SCENARIO == "election":
        result = run_election()
    elif SCENARIO == "map_read":
        result = run_map_read()
    elif SCENARIO == "host":
        result = run_host()
    elif SCENARIO == "host_read":
        result = run_host_read()
    elif SCENARIO == "spi":
        result = run_spi()
    elif SCENARIO == "readmix":
        result = run_readmix()
    elif SCENARIO == "cluster":
        result = run_cluster()
    elif SCENARIO == "sharded":
        result = run_sharded()
    elif SCENARIO == "apply":
        result = run_apply()
    elif SCENARIO == "recovery":
        result = run_recovery()
    elif SCENARIO == "compartment":
        result = run_compartment()
    elif SCENARIO == "fanout":
        result = run_fanout()
    elif SCENARIO == "session":
        result = run_session()
    elif SCENARIO in SUBMIT_BUILDERS:
        result = run_throughput(SCENARIO)
    else:
        raise SystemExit(
            f"unknown scenario {SCENARIO!r}; pick one of "
            f"{['election', 'map_read', 'host', 'host_read', 'spi', 'readmix', 'cluster', 'sharded', 'apply', 'recovery', 'compartment', 'fanout', 'session', *SUBMIT_BUILDERS]}")
    if degraded:
        result["degraded"] = True
    if args.metrics_json:
        artifact = {**result, "scenario": SCENARIO,
                    "meta": _artifact_meta(),
                    "metrics": METRICS_SNAPSHOTS,
                    # the run's retained /series windows (empty under
                    # COPYCAT_SERIES=0) — the gate reads none of it
                    "series": SERIES_WINDOWS}
        if bench_profiler is not None:
            # where the run's wall time actually went (the continuous
            # profiler's top-frame summary + the plane's own counters);
            # absent under COPYCAT_PROFILE=0 — the gate reads none of it
            artifact["profile"] = bench_profiler.top_summary(top=10)
        with open(args.metrics_json, "w") as f:
            json.dump(artifact, f)
        log(f"bench: metrics snapshot written to {args.metrics_json}")
    _profiler.release(bench_profiler)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
