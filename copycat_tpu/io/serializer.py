"""Object serialization with a type-id registry (Catalyst ``Serializer`` equivalent).

The reference serializes every operation with ``@SerializeWith(id=...)`` classes
implementing ``CatalystSerializable.writeObject/readObject`` (SURVEY.md §2.3;
reference ids: 28-38 infra, 50-55 atomic, 60-105 collections, 85-89 + 110-127
coordination — the same id blocks are reused here for parity auditing).

Design differences from the reference (deliberate):

- Class-by-name serialization exists (``write_class``/``read_class``, used by
  the ``CreateResource`` catalog op per reference ``CreateResource.java:55-66``)
  but is restricted to registered resource/state-machine classes — no arbitrary
  ``Class.forName``.
- No serialized closures: the reference logs ``Runnable`` closures for group
  remote-execution (``MembershipGroupCommands.java:85``); here remote execution
  ships a registered callback id + args instead (see coordination/group.py).
"""

from __future__ import annotations

from typing import Any, Callable, Protocol, Type, runtime_checkable

from .buffer import BufferInput, BufferOutput

# Built-in wire tags for primitives / containers (< 16 reserved).
_T_NULL = 0
_T_TRUE = 1
_T_FALSE = 2
_T_INT = 3
_T_FLOAT = 4
_T_STR = 5
_T_BYTES = 6
_T_LIST = 7
_T_DICT = 8
_T_TUPLE = 9
_T_SET = 10
_T_CLASS = 11  # registered class reference, by serialization id


@runtime_checkable
class CatalystSerializable(Protocol):
    """Objects that write/read themselves through typed buffers."""

    def write_object(self, buffer: BufferOutput, serializer: "Serializer") -> None: ...

    def read_object(self, buffer: BufferInput, serializer: "Serializer") -> None: ...


_TYPE_REGISTRY: dict[int, type] = {}
_ID_BY_TYPE: dict[type, int] = {}
#: type_id -> tuple of field names for classes whose write/read is the
#: GENERIC field-list form (both methods carry the ``_generic_fields``
#: marker set by protocol.messages.Message), else None. The native codec
#: (io/codec.py) serializes generic classes entirely in C; None means it
#: calls back into the class's custom write_object/read_object.
_CODEC_FIELDS: dict[int, tuple | None] = {}
#: type_id -> count of TRAILING fields that are wire-optional (a
#: trailing None run is omitted when writing; a reader at end-of-buffer
#: fills them with None). Mirrors ``Message._optional`` so the C walk
#: and the Python walk stay byte-identical; only meaningful for
#: top-level RPC messages (see protocol/messages.py).
_CODEC_OPTIONAL: dict[int, int] = {}


def _generic_fields(cls: type) -> tuple | None:
    w = getattr(cls, "write_object", None)
    r = getattr(cls, "read_object", None)
    if getattr(w, "_generic_fields", False) \
            and getattr(r, "_generic_fields", False):
        fields = getattr(cls, "_fields", None)
        if fields is not None:
            return tuple(fields)
    return None


def serialize_with(type_id: int) -> Callable[[type], type]:
    """Class decorator registering a serializable type under a stable id.

    Equivalent of the reference's ``@SerializeWith(id=...)`` annotation.
    """

    def register(cls: type) -> type:
        check = _TYPE_REGISTRY.get(type_id)
        if check is not None and check is not cls and check.__qualname__ != cls.__qualname__:
            raise ValueError(f"serialization id {type_id} already bound to {check!r}")
        _TYPE_REGISTRY[type_id] = cls
        _ID_BY_TYPE[cls] = type_id
        fields = _generic_fields(cls)
        _CODEC_FIELDS[type_id] = fields
        _CODEC_OPTIONAL[type_id] = (
            int(getattr(cls, "_optional", 0)) if fields is not None else 0)
        return cls

    return register


def registered_type(type_id: int) -> type | None:
    return _TYPE_REGISTRY.get(type_id)


class SerializationError(Exception):
    pass


def _native() -> Any:
    """Lazy import breaks the codec<->serializer import cycle."""
    from .codec import codec
    return codec()


class Serializer:
    """Writes/reads arbitrary object graphs of primitives + registered types.

    ``write``/``read`` prefer the native codec (io/codec.py, a
    byte-identical C walk of the same format) and fall back to the pure
    Python below on ``Fallback`` (>64-bit ints) or when the extension
    is unavailable. ``write_object``/``read_object`` ARE the format's
    reference implementation — custom-serialized classes re-enter
    through them from the native side too.
    """

    def write(self, obj: Any) -> bytes:
        c = _native()
        if c is not None:
            try:
                return c.encode(obj)
            except c.Fallback:
                pass
        buf = BufferOutput()
        self.write_object(obj, buf)
        return buf.to_bytes()

    def read(self, data: bytes) -> Any:
        c = _native()
        if c is not None:
            try:
                return c.decode(bytes(data))
            except c.Fallback:
                pass
        return self.read_object(BufferInput(data))

    # -- object graph ------------------------------------------------------

    def write_object(self, obj: Any, buf: BufferOutput) -> None:
        if obj is None:
            buf.write_varint(_T_NULL)
        elif obj is True:
            buf.write_varint(_T_TRUE)
        elif obj is False:
            buf.write_varint(_T_FALSE)
        elif isinstance(obj, int):
            buf.write_varint(_T_INT).write_varint(obj)
        elif isinstance(obj, float):
            buf.write_varint(_T_FLOAT).write_f64(obj)
        elif isinstance(obj, str):
            buf.write_varint(_T_STR).write_utf8(obj)
        elif isinstance(obj, (bytes, bytearray)):
            buf.write_varint(_T_BYTES).write_bytes(bytes(obj))
        elif isinstance(obj, list):
            buf.write_varint(_T_LIST).write_varint(len(obj))
            for item in obj:
                self.write_object(item, buf)
        elif isinstance(obj, tuple):
            buf.write_varint(_T_TUPLE).write_varint(len(obj))
            for item in obj:
                self.write_object(item, buf)
        elif isinstance(obj, (set, frozenset)):
            # Order by encoded bytes so the wire format is deterministic even
            # for registered objects (repr would embed memory addresses).
            buf.write_varint(_T_SET).write_varint(len(obj))
            for encoded in sorted(self.write(item) for item in obj):
                buf.write_raw(encoded)
        elif isinstance(obj, dict):
            buf.write_varint(_T_DICT).write_varint(len(obj))
            for key, value in obj.items():
                self.write_object(key, buf)
                self.write_object(value, buf)
        elif isinstance(obj, type):
            self.write_class(obj, buf)
        else:
            type_id = _ID_BY_TYPE.get(type(obj))
            if type_id is None:
                raise SerializationError(
                    f"unregistered type {type(obj).__qualname__}; decorate with @serialize_with(id)"
                )
            buf.write_varint(16 + type_id)
            obj.write_object(buf, self)

    def read_object(self, buf: BufferInput) -> Any:
        tag = buf.read_varint()
        if tag == _T_NULL:
            return None
        if tag == _T_TRUE:
            return True
        if tag == _T_FALSE:
            return False
        if tag == _T_INT:
            return buf.read_varint()
        if tag == _T_FLOAT:
            return buf.read_f64()
        if tag == _T_STR:
            return buf.read_utf8()
        if tag == _T_BYTES:
            return buf.read_bytes()
        if tag == _T_LIST:
            return [self.read_object(buf) for _ in range(buf.read_varint())]
        if tag == _T_TUPLE:
            return tuple(self.read_object(buf) for _ in range(buf.read_varint()))
        if tag == _T_SET:
            return {self.read_object(buf) for _ in range(buf.read_varint())}
        if tag == _T_DICT:
            n = buf.read_varint()
            return {self.read_object(buf): self.read_object(buf) for _ in range(n)}
        if tag == _T_CLASS:
            return self._read_class_body(buf)
        cls = _TYPE_REGISTRY.get(tag - 16)
        if cls is None:
            raise SerializationError(f"unknown serialization id {tag - 16}")
        obj = cls.__new__(cls)
        obj.read_object(buf, self)
        return obj

    # -- class references (for CreateResource-style catalog ops) ----------

    def write_class(self, cls: Type, buf: BufferOutput) -> None:
        type_id = _ID_BY_TYPE.get(cls)
        if type_id is None:
            raise SerializationError(
                f"class {cls.__qualname__} not registered; register with @serialize_with(id)"
            )
        buf.write_varint(_T_CLASS).write_varint(type_id)

    def _read_class_body(self, buf: BufferInput) -> Type:
        type_id = buf.read_varint()
        cls = _TYPE_REGISTRY.get(type_id)
        if cls is None:
            raise SerializationError(f"unknown class id {type_id}")
        return cls

    def clone(self, obj: Any) -> Any:
        """Round-trip an object through the wire format (used by LocalTransport)."""
        return self.read(self.write(obj))
