"""Native codec loader: build-on-demand CPython extension + fallback hooks.

The wire format's reference implementation is the pure-Python
:mod:`serializer`; ``native/copycat_codec.c`` is a byte-identical C
walk of the same object graphs (the reference's serializer ran on the
JVM JIT — this is the equivalent native runtime component, SURVEY.md
§2.3 "serialization"). Loading degrades gracefully: no toolchain (or a
build failure) leaves ``codec()`` returning None and every caller on
the Python path.

The extension sees the LIVE registries from serializer.py (the
``@serialize_with`` decorator mutates them; C reads them per lookup),
plus two Python callbacks for classes with hand-written
write_object/read_object:

- ``encode_body(obj) -> bytes`` — the body after the 16+id tag;
- ``decode_body(cls, data, pos) -> (obj, new_pos)``.

Anything the C path can't express (ints beyond 64 bits, unregistered
types) raises ``Fallback`` and Serializer.write/read re-run pure
Python — the native path is an accelerator, never a semantic fork.
"""

from __future__ import annotations

import importlib.machinery
import importlib.util
import pathlib
import subprocess
from typing import Any

_NATIVE_DIR = pathlib.Path(__file__).resolve().parents[2] / "native"
_SO_PATH = _NATIVE_DIR / "copycat_codec.so"

_codec: Any = None
_codec_err: str | None = None


def _build_and_load() -> Any:
    src = _NATIVE_DIR / "copycat_codec.c"
    if (not _SO_PATH.exists()
            or _SO_PATH.stat().st_mtime < src.stat().st_mtime):
        subprocess.run(["make", "-C", str(_NATIVE_DIR), "copycat_codec.so"],
                       check=True, capture_output=True, timeout=120)
    loader = importlib.machinery.ExtensionFileLoader(
        "copycat_codec", str(_SO_PATH))
    spec = importlib.util.spec_from_loader("copycat_codec", loader)
    mod = importlib.util.module_from_spec(spec)
    loader.exec_module(mod)
    return mod


def _configure(mod: Any) -> None:
    from .buffer import BufferInput, BufferOutput
    from . import serializer as s

    default = s.Serializer()

    def encode_body(obj: Any) -> bytes:
        buf = BufferOutput()
        obj.write_object(buf, default)
        return buf.to_bytes()

    def decode_body(cls: type, data: bytes, pos: int):
        buf = BufferInput(data)
        buf._pos = pos
        obj = cls.__new__(cls)
        obj.read_object(buf, default)
        return obj, buf._pos

    mod.configure(s._ID_BY_TYPE, s._TYPE_REGISTRY, s._CODEC_FIELDS,
                  encode_body, decode_body, s._CODEC_OPTIONAL)


def codec() -> Any:
    """The configured extension module, or None when unavailable."""
    global _codec, _codec_err
    if _codec is not None or _codec_err is not None:
        return _codec
    try:
        mod = _build_and_load()
        _configure(mod)
        _codec = mod
    except Exception as exc:  # toolchain missing — degrade gracefully
        _codec_err = str(exc)
    return _codec


def codec_error() -> str | None:
    return _codec_err
