"""Typed binary buffers (Catalyst ``BufferInput``/``BufferOutput`` equivalent).

Fixed-width big-endian primitives plus varints and length-prefixed UTF-8/bytes.
The serializer (serializer.py) writes object graphs through these primitives so
the wire format is deterministic and transport-independent.
"""

from __future__ import annotations

import struct

_I16 = struct.Struct(">h")
_I32 = struct.Struct(">i")
_I64 = struct.Struct(">q")
_F64 = struct.Struct(">d")
# single-byte interning: most wire integers (type tags, small ids, field
# counts) zigzag-encode to one byte — skip the per-byte encode loop
_ONE = [bytes((v,)) for v in range(256)]


class BufferOutput:
    """Append-only binary writer."""

    def __init__(self) -> None:
        self._parts: list[bytes] = []

    def write_u8(self, value: int) -> "BufferOutput":
        self._parts.append(_ONE[value & 0xFF])
        return self

    def write_bool(self, value: bool) -> "BufferOutput":
        return self.write_u8(1 if value else 0)

    def write_i16(self, value: int) -> "BufferOutput":
        self._parts.append(_I16.pack(value))
        return self

    def write_i32(self, value: int) -> "BufferOutput":
        self._parts.append(_I32.pack(value))
        return self

    def write_i64(self, value: int) -> "BufferOutput":
        self._parts.append(_I64.pack(value))
        return self

    def write_f64(self, value: float) -> "BufferOutput":
        self._parts.append(_F64.pack(value))
        return self

    def write_varint(self, value: int) -> "BufferOutput":
        """ZigZag-encoded LEB128 varint (handles negatives compactly)."""
        zz = ((-value) << 1) - 1 if value < 0 else (value << 1)
        if zz < 0x80:  # one-byte fast path (the overwhelmingly common case)
            self._parts.append(_ONE[zz])
            return self
        out = bytearray()
        while True:
            byte = zz & 0x7F
            zz >>= 7
            if zz:
                out.append(byte | 0x80)
            else:
                out.append(byte)
                break
        self._parts.append(bytes(out))
        return self

    def write_bytes(self, value: bytes) -> "BufferOutput":
        self.write_varint(len(value))
        self._parts.append(value)
        return self

    def write_raw(self, value: bytes) -> "BufferOutput":
        """Append pre-encoded bytes verbatim (no length prefix)."""
        self._parts.append(value)
        return self

    def write_utf8(self, value: str) -> "BufferOutput":
        return self.write_bytes(value.encode("utf-8"))

    def to_bytes(self) -> bytes:
        return b"".join(self._parts)

    def __len__(self) -> int:
        return sum(len(p) for p in self._parts)


class BufferInput:
    """Sequential binary reader over bytes produced by :class:`BufferOutput`."""

    def __init__(self, data: bytes) -> None:
        self._data = data
        self._pos = 0

    def _take(self, n: int) -> bytes:
        if n < 0:
            raise EOFError(f"negative length {n} at {self._pos}")
        if self._pos + n > len(self._data):
            raise EOFError(f"buffer underflow: need {n} bytes at {self._pos}/{len(self._data)}")
        chunk = self._data[self._pos : self._pos + n]
        self._pos += n
        return chunk

    def read_u8(self) -> int:
        return self._take(1)[0]

    def read_bool(self) -> bool:
        return self.read_u8() != 0

    def read_i16(self) -> int:
        return _I16.unpack(self._take(2))[0]

    def read_i32(self) -> int:
        return _I32.unpack(self._take(4))[0]

    def read_i64(self) -> int:
        return _I64.unpack(self._take(8))[0]

    def read_f64(self) -> float:
        return _F64.unpack(self._take(8))[0]

    def read_varint(self) -> int:
        first = self._take(1)[0]
        if not first & 0x80:  # one-byte fast path
            return -((first + 1) >> 1) if first & 1 else first >> 1
        zz = first & 0x7F
        shift = 7
        while True:
            byte = self._take(1)[0]
            zz |= (byte & 0x7F) << shift
            if not byte & 0x80:
                break
            shift += 7
        if zz & 1:
            return -((zz + 1) >> 1)
        return zz >> 1

    def read_bytes(self) -> bytes:
        return self._take(self.read_varint())

    def read_utf8(self) -> str:
        return self.read_bytes().decode("utf-8")

    @property
    def remaining(self) -> int:
        return len(self._data) - self._pos
