"""Async transport SPI (Catalyst ``Transport``/``Client``/``Server``/``Connection``).

The reference's seam (SURVEY.md §5.8): ``Transport{client(), server()}``,
``Client.connect(Address) -> Connection``, ``Server.listen(Address, on_connect)``,
``Connection.send(msg) -> response`` / ``Connection.handler(type, fn)``.
Implementations: :mod:`local` (in-memory, the test substrate) and :mod:`tcp`
(asyncio streams over real sockets — the reference's NettyTransport role).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Any, Awaitable, Callable

from .serializer import serialize_with
from .buffer import BufferInput, BufferOutput


class TransportError(Exception):
    pass


class ConnectionClosedError(TransportError):
    pass


@serialize_with(12)
@dataclass(frozen=True)
class Address:
    """A host:port endpoint (Catalyst ``Address`` equivalent)."""

    host: str
    port: int

    def __str__(self) -> str:
        return f"{self.host}:{self.port}"

    @staticmethod
    def parse(value: str) -> "Address":
        host, _, port = value.rpartition(":")
        return Address(host, int(port))

    def write_object(self, buf: BufferOutput, serializer: Any) -> None:
        buf.write_utf8(self.host)
        buf.write_i32(self.port)

    def read_object(self, buf: BufferInput, serializer: Any) -> None:
        object.__setattr__(self, "host", buf.read_utf8())
        object.__setattr__(self, "port", buf.read_i32())


Handler = Callable[[Any], Awaitable[Any]]


class Connection(abc.ABC):
    """A bidirectional message channel with request/response correlation.

    ``send`` delivers a message to the peer and resolves with the peer handler's
    return value.  A handler exception crosses the transport as
    ``TransportError("Type: message")`` — identically on every transport, so
    code written against LocalTransport behaves the same over TCP.  Handlers are
    registered per message type; dispatch walks the MRO so a handler registered
    on a base class sees subclasses too.
    """

    def __init__(self) -> None:
        self._handlers: dict[type, Handler] = {}
        self._close_listeners: list[Callable[["Connection"], None]] = []
        self.closed = False

    def handler(self, msg_type: type, fn: Handler) -> None:
        self._handlers[msg_type] = fn

    def on_close(self, fn: Callable[["Connection"], None]) -> None:
        self._close_listeners.append(fn)

    def _dispatch_handler(self, message: Any) -> Handler | None:
        for cls in type(message).__mro__:
            fn = self._handlers.get(cls)
            if fn is not None:
                return fn
        return None

    async def _handle(self, message: Any) -> Any:
        fn = self._dispatch_handler(message)
        if fn is None:
            raise TransportError(f"no handler for {type(message).__name__}")
        return await fn(message)

    def _fire_close(self) -> None:
        if not self.closed:
            self.closed = True
            for fn in list(self._close_listeners):
                fn(self)

    @abc.abstractmethod
    async def send(self, message: Any) -> Any: ...

    @abc.abstractmethod
    async def close(self) -> None: ...


class Client(abc.ABC):
    @abc.abstractmethod
    async def connect(self, address: Address) -> Connection: ...

    @abc.abstractmethod
    async def close(self) -> None: ...


class Server(abc.ABC):
    @abc.abstractmethod
    async def listen(self, address: Address, on_connect: Callable[[Connection], None]) -> None: ...

    @abc.abstractmethod
    async def close(self) -> None: ...


class Transport(abc.ABC):
    """Factory for clients and servers sharing one substrate."""

    @abc.abstractmethod
    def client(self) -> Client: ...

    @abc.abstractmethod
    def server(self) -> Server: ...
