"""Native TCP transport: C++ epoll event loop behind the Transport SPI.

The reference's default transport is Netty — a native-backed event-loop
(`AtomixClient.java:136-144` loads it reflectively). Here the equivalent
runtime component is real native code (``native/copycat_native.cpp``): an
epoll thread owns the sockets and parses the shared wire format
``[u32 len][u8 kind][u64 corr][payload]`` — byte-identical to
:mod:`copycat_tpu.io.tcp`, so native and asyncio endpoints interoperate.
Python only exchanges complete frames with the loop via ctypes
(no pybind11 in the image; plain C ABI).

``NativeTcpTransport`` is a drop-in for ``TcpTransport``; if the shared
library can't be built (no toolchain), importing still works and
``native_available()`` returns False — callers fall back to asyncio TCP.
"""

from __future__ import annotations

import asyncio
import ctypes
import logging
import pathlib
import subprocess
import threading
from typing import Any, Callable

from ..utils.metrics import MetricsRegistry
from ..utils.tasks import spawn
from .serializer import Serializer
from .transport import (
    Address,
    Client,
    Connection,
    ConnectionClosedError,
    Server,
    Transport,
    TransportError,
)

logger = logging.getLogger(__name__)

_REQUEST, _RESPONSE, _ERROR = 0, 1, 2
_ETYPE_ACCEPT, _ETYPE_FRAME, _ETYPE_CLOSE, _ETYPE_CONNECT = 1, 2, 3, 4

_NATIVE_DIR = pathlib.Path(__file__).resolve().parents[2] / "native"
_LIB_PATH = _NATIVE_DIR / "libcopycat_native.so"

_lib: ctypes.CDLL | None = None
_lib_err: str | None = None


def _load() -> ctypes.CDLL | None:
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    try:
        src = _NATIVE_DIR / "copycat_native.cpp"
        if (not _LIB_PATH.exists()
                or _LIB_PATH.stat().st_mtime < src.stat().st_mtime):
            subprocess.run(["make", "-C", str(_NATIVE_DIR)], check=True,
                           capture_output=True, timeout=120)
        lib = ctypes.CDLL(str(_LIB_PATH))
        lib.cn_new.restype = ctypes.c_void_p
        lib.cn_start.argtypes = [ctypes.c_void_p]
        lib.cn_listen.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_int]
        lib.cn_connect.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_int]
        lib.cn_send.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                ctypes.c_uint8, ctypes.c_uint64,
                                ctypes.c_char_p, ctypes.c_int]
        lib.cn_poll.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_char_p, ctypes.c_int]
        lib.cn_close_conn.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.cn_shutdown.argtypes = [ctypes.c_void_p]
        _lib = lib
    except Exception as exc:  # toolchain missing — degrade gracefully
        _lib_err = str(exc)
    return _lib


def native_available() -> bool:
    return _load() is not None


class _NativeLoop:
    """Owns one C++ epoll loop + the Python-side poller thread."""

    def __init__(self, metrics: MetricsRegistry | None = None) -> None:
        lib = _load()
        if lib is None:
            raise TransportError(f"native transport unavailable: {_lib_err}")
        m = metrics if metrics is not None else MetricsRegistry()
        self.metrics = m
        self._m_bytes_in = m.counter("bytes_in")
        self._m_bytes_out = m.counter("bytes_out")
        self._m_frames_in = m.counter("frames_in")
        self._m_frames_out = m.counter("frames_out")
        self._m_burst = m.histogram("read_burst_frames")
        self._lib = lib
        self._handle = ctypes.c_void_p(lib.cn_new())
        if lib.cn_start(self._handle) != 0:
            raise TransportError("failed to start native loop thread")
        self._cap = 1 << 20
        self._buf = ctypes.create_string_buffer(self._cap)
        self._routes: dict[int, Callable[[int, int, int, bytes], None]] = {}
        self._accepts: dict[int, Callable[[int], None]] = {}
        self._aio: asyncio.AbstractEventLoop | None = None
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._poller, daemon=True,
                                        name="copycat-native-poll")
        self._thread.start()

    def bind_asyncio(self, loop: asyncio.AbstractEventLoop) -> None:
        self._aio = loop

    #: max events drained per poller wake — bounds the latency one
    #: burst handoff can add to the event at the back of the queue
    BURST_MAX = 256

    def _poller(self) -> None:
        conn = ctypes.c_int()
        etype = ctypes.c_int()
        kind = ctypes.c_uint8()
        corr = ctypes.c_uint64()

        def poll_one(timeout_ms: int):
            """One cn_poll; None when idle, else the event tuple."""
            while True:
                n = self._lib.cn_poll(self._handle, timeout_ms,
                                      ctypes.byref(conn), ctypes.byref(etype),
                                      ctypes.byref(kind), ctypes.byref(corr),
                                      self._buf, self._cap)
                if n == -1:
                    return None
                if n == -2:  # grow and re-poll; the event was kept queued
                    self._cap = max(self._cap * 2, int(corr.value) + 1)
                    self._buf = ctypes.create_string_buffer(self._cap)
                    continue
                payload = self._buf.raw[:n] if n > 0 else b""
                return (conn.value, etype.value, kind.value,
                        int(corr.value), payload)

        while not self._stop.is_set():
            ev = poll_one(100)
            if ev is None:
                continue
            # Burst handoff: drain everything already queued in the C
            # loop (zero-timeout polls) and cross the thread boundary
            # ONCE — one call_soon_threadsafe per burst instead of per
            # frame kept the poller from scheduling N asyncio callbacks
            # for an N-frame read burst.
            burst = [ev]
            while len(burst) < self.BURST_MAX:
                ev = poll_one(0)
                if ev is None:
                    break
                burst.append(ev)
            self._dispatch_burst(burst)

    def _dispatch_burst(self, burst: list) -> None:
        # poller-thread-only counters (the asyncio side owns the _out
        # pair, so no counter is shared across threads)
        frames = [p for _, etype, _, _, p in burst if etype == _ETYPE_FRAME]
        if frames:
            self._m_frames_in.inc(len(frames))
            self._m_burst.record(len(frames))
            self._m_bytes_in.inc(sum(len(p) for p in frames))
        aio = self._aio
        if aio is None or aio.is_closed():
            return
        # Route lookups must happen IN the asyncio thread: an ACCEPT's
        # callback (which registers the route) and the first FRAME arrive
        # back-to-back from the poller, and in-burst order is preserved
        # by delivering the whole burst inside one loop callback.
        def deliver() -> None:
            for conn, etype, kind, corr, payload in burst:
                # per-event isolation: one raising callback must not drop
                # the rest of the burst (the per-frame call_soon design
                # isolated failures for free; the burst handoff must too)
                try:
                    if etype == _ETYPE_ACCEPT:
                        fn = self._accepts.get(corr)  # corr = listener conn
                        if fn is not None:
                            fn(conn)
                        continue
                    route = self._routes.get(conn)
                    if route is not None:
                        route(etype, kind, corr, payload)
                except Exception:
                    logger.exception(
                        "native poller: event callback failed "
                        "(conn=%d etype=%d)", conn, etype)

        try:
            aio.call_soon_threadsafe(deliver)
        except RuntimeError:  # loop shut down mid-poll
            pass

    # thin C wrappers -----------------------------------------------------
    # The ints below are loop-assigned conn ids (generation-safe), not raw
    # fds: the kernel reuses fd numbers, ids are never reused.
    def listen(self, address: Address) -> int:
        conn = self._lib.cn_listen(self._handle, address.host.encode(),
                                   address.port)
        if conn < 0:
            raise TransportError(f"cannot listen on {address}")
        return conn

    def connect(self, address: Address) -> int:
        conn = self._lib.cn_connect(self._handle, address.host.encode(),
                                    address.port)
        if conn < 0:
            raise TransportError(f"cannot connect to {address}")
        return conn

    def send(self, conn: int, kind: int, corr: int, payload: bytes) -> None:
        if self._lib.cn_send(self._handle, conn, kind, corr, payload,
                             len(payload)) != 0:
            raise ConnectionClosedError("connection closed")
        self._m_frames_out.inc()
        self._m_bytes_out.inc(len(payload))

    def close_conn(self, conn: int) -> None:
        self._lib.cn_close_conn(self._handle, conn)

    def shutdown(self) -> None:
        if not self._stop.is_set():
            self._stop.set()
            self._thread.join(timeout=2)
            self._lib.cn_shutdown(self._handle)


class NativeConnection(Connection):
    """Frame-level I/O lives in C++; request/response correlation here."""

    def __init__(self, loop: _NativeLoop, fd: int, serializer: Serializer,
                 awaits_connect: bool = False) -> None:
        super().__init__()
        self._loop = loop
        self._fd = fd
        self._serializer = serializer
        self._next_id = 0
        self._pending: dict[int, asyncio.Future] = {}
        # Client side: connects are nonblocking in C; completion (or
        # refusal) arrives as an event, awaited before connect() returns
        # so the fail-fast contract of TcpTransport is preserved.
        self._ready: asyncio.Future | None = (
            asyncio.get_running_loop().create_future() if awaits_connect
            else None)
        loop._routes[fd] = self._on_event

    def _on_event(self, etype: int, kind: int, corr: int,
                  payload: bytes) -> None:
        if etype == _ETYPE_CONNECT:
            if self._ready is not None and not self._ready.done():
                self._ready.set_result(True)
            return
        if etype == _ETYPE_CLOSE:
            self._abort()
            return
        if kind == _REQUEST:
            spawn(self._serve(corr, payload), name="native-serve")
            return
        future = self._pending.pop(corr, None)
        if future is not None and not future.done():
            if kind == _ERROR:
                future.set_exception(
                    TransportError(self._serializer.read(payload)))
            else:
                future.set_result(self._serializer.read(payload))

    async def _serve(self, corr: int, payload: bytes) -> None:
        try:
            result = await self._handle(self._serializer.read(payload))
            self._loop.send(self._fd, _RESPONSE, corr,
                            self._serializer.write(result))
        except Exception as exc:
            try:
                self._loop.send(self._fd, _ERROR, corr, self._serializer.write(
                    f"{type(exc).__name__}: {exc}"))
            except Exception:
                pass

    async def send(self, message: Any) -> Any:
        if self.closed:
            raise ConnectionClosedError("connection closed")
        self._next_id += 1
        corr = self._next_id
        future: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[corr] = future
        try:
            self._loop.send(self._fd, _REQUEST, corr,
                            self._serializer.write(message))
            return await future
        finally:
            # Same stranded-correlation guard as TcpConnection.send: a
            # cancelled/timed-out send must not leak its slot in
            # _pending until the connection closes.
            self._pending.pop(corr, None)

    def _abort(self) -> None:
        self._loop._routes.pop(self._fd, None)
        if self._ready is not None and not self._ready.done():
            self._ready.set_exception(
                ConnectionClosedError("connect failed"))
        for future in self._pending.values():
            if not future.done():
                future.set_exception(ConnectionClosedError("connection closed"))
        self._pending.clear()
        self._fire_close()

    async def close(self) -> None:
        if not self.closed:
            self._loop.close_conn(self._fd)
            self._abort()


class NativeTcpClient(Client):
    def __init__(self, loop: _NativeLoop) -> None:
        self._loop = loop
        self._connections: list[NativeConnection] = []

    async def connect(self, address: Address) -> Connection:
        aio = asyncio.get_running_loop()
        self._loop.bind_asyncio(aio)
        self._loop.metrics.counter("connects").inc()
        # Resolve on the asyncio resolver (thread pool) so a slow DNS
        # lookup never blocks the event loop; C gets a numeric host.
        import socket
        infos = await aio.getaddrinfo(address.host or "127.0.0.1",
                                      address.port, family=socket.AF_INET,
                                      type=socket.SOCK_STREAM)
        numeric = Address(infos[0][4][0], address.port)
        fd = self._loop.connect(numeric)
        conn = NativeConnection(self._loop, fd, Serializer(),
                                awaits_connect=True)
        try:
            await conn._ready  # fail-fast: refused connects raise here
        except ConnectionClosedError as exc:
            raise TransportError(f"cannot connect to {address}") from exc
        self._connections.append(conn)
        conn.on_close(lambda c: self._connections.remove(c)
                      if c in self._connections else None)
        return conn

    async def close(self) -> None:
        for conn in list(self._connections):
            await conn.close()
        self._connections.clear()


class NativeTcpServer(Server):
    def __init__(self, loop: _NativeLoop) -> None:
        self._loop = loop
        self._listener: int | None = None
        self._connections: list[NativeConnection] = []

    async def listen(self, address: Address,
                     on_connect: Callable[[Connection], None]) -> None:
        aio = asyncio.get_running_loop()
        self._loop.bind_asyncio(aio)
        if address.host:
            import socket
            infos = await aio.getaddrinfo(address.host, address.port,
                                          family=socket.AF_INET,
                                          type=socket.SOCK_STREAM)
            address = Address(infos[0][4][0], address.port)
        self._listener = self._loop.listen(address)

        def accept(fd: int) -> None:
            self._loop.metrics.counter("accepts").inc()
            conn = NativeConnection(self._loop, fd, Serializer())
            self._connections.append(conn)
            conn.on_close(lambda c: self._connections.remove(c)
                          if c in self._connections else None)
            on_connect(conn)

        self._loop._accepts[self._listener] = accept

    async def close(self) -> None:
        for conn in list(self._connections):
            await conn.close()
        self._connections.clear()
        if self._listener is not None:
            self._loop._accepts.pop(self._listener, None)
            self._loop.close_conn(self._listener)
            self._listener = None


class NativeTcpTransport(Transport):
    """Drop-in for ``TcpTransport`` with the I/O path in C++."""

    def __init__(self) -> None:
        self.metrics = MetricsRegistry()
        self._loop = _NativeLoop(self.metrics)

    def client(self) -> Client:
        return NativeTcpClient(self._loop)

    def server(self) -> Server:
        return NativeTcpServer(self._loop)

    def shutdown(self) -> None:
        """Stop the epoll thread (call when done with the transport)."""
        self._loop.shutdown()
