"""I/O substrate (Catalyst equivalent): serialization + pluggable async transport.

Mirrors the consumed Catalyst API surface (SURVEY.md §2.3): ``Serializer`` with
a ``@SerializeWith(id=...)`` type-id registry, ``BufferInput/Output`` typed
binary buffers, ``Transport{client(), server()}`` with async connect/listen/
send/handler, and the in-memory ``LocalTransport``/``LocalServerRegistry`` used
by every reference test.
"""

from .buffer import BufferInput, BufferOutput
from .serializer import Serializer, serialize_with, CatalystSerializable
from .transport import Address, Transport, Client, Server, Connection, TransportError
from .local import LocalTransport, LocalServerRegistry

__all__ = [
    "BufferInput",
    "BufferOutput",
    "Serializer",
    "serialize_with",
    "CatalystSerializable",
    "Address",
    "Transport",
    "Client",
    "Server",
    "Connection",
    "TransportError",
    "LocalTransport",
    "LocalServerRegistry",
]
