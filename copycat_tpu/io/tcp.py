"""TCP transport over asyncio streams (the reference's NettyTransport role).

Frames: ``[u32 length][u8 kind][u64 correlation id][payload]`` where kind is
REQUEST / RESPONSE / ERROR.  Payloads are serialized with the shared type-id
serializer, so anything that crosses LocalTransport crosses TCP identically.
This is the DCN/gRPC-role host-side transport of the TPU design (SURVEY.md
§5.8): client sessions and cross-slice traffic ride here, while intra-step
quorum traffic rides ICI collectives inside the compiled engine.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Any, Callable

from .serializer import Serializer
from .transport import (
    Address,
    Client,
    Connection,
    ConnectionClosedError,
    Server,
    Transport,
    TransportError,
)

_HEADER = struct.Struct(">IBQ")
_REQUEST, _RESPONSE, _ERROR = 0, 1, 2


class TcpConnection(Connection):
    def __init__(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter, serializer: Serializer
    ) -> None:
        super().__init__()
        self._reader = reader
        self._writer = writer
        self._serializer = serializer
        self._next_id = 0
        self._pending: dict[int, asyncio.Future] = {}
        self._reader_task = asyncio.get_running_loop().create_task(self._read_loop())

    async def _read_loop(self) -> None:
        try:
            while True:
                header = await self._reader.readexactly(_HEADER.size)
                length, kind, corr = _HEADER.unpack(header)
                payload = await self._reader.readexactly(length)
                if kind == _REQUEST:
                    asyncio.get_running_loop().create_task(self._serve(corr, payload))
                else:
                    future = self._pending.pop(corr, None)
                    if future is not None and not future.done():
                        if kind == _ERROR:
                            future.set_exception(TransportError(self._serializer.read(payload)))
                        else:
                            future.set_result(self._serializer.read(payload))
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        finally:
            self._abort()

    async def _serve(self, corr: int, payload: bytes) -> None:
        try:
            message = self._serializer.read(payload)
            result = await self._handle(message)
            self._write_frame(_RESPONSE, corr, self._serializer.write(result))
        except Exception as exc:  # marshal handler errors back to the caller
            try:
                self._write_frame(_ERROR, corr, self._serializer.write(f"{type(exc).__name__}: {exc}"))
            except Exception:
                pass

    def _write_frame(self, kind: int, corr: int, payload: bytes) -> None:
        if self.closed:
            raise ConnectionClosedError("connection closed")
        self._writer.write(_HEADER.pack(len(payload), kind, corr) + payload)

    async def send(self, message: Any) -> Any:
        if self.closed:
            raise ConnectionClosedError("connection closed")
        self._next_id += 1
        corr = self._next_id
        future: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[corr] = future
        self._write_frame(_REQUEST, corr, self._serializer.write(message))
        await self._writer.drain()
        return await future

    def _abort(self) -> None:
        for future in self._pending.values():
            if not future.done():
                future.set_exception(ConnectionClosedError("connection closed"))
        self._pending.clear()
        self._fire_close()

    async def close(self) -> None:
        if not self.closed:
            self._fire_close()
            self._reader_task.cancel()
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionResetError, OSError):
                pass
        self._abort()


class TcpClient(Client):
    def __init__(self, serializer_factory: Callable[[], Serializer]) -> None:
        self._serializer_factory = serializer_factory
        self._connections: list[TcpConnection] = []

    async def connect(self, address: Address) -> Connection:
        reader, writer = await asyncio.open_connection(address.host, address.port)
        conn = TcpConnection(reader, writer, self._serializer_factory())
        self._connections.append(conn)
        conn.on_close(lambda c: self._connections.remove(c) if c in self._connections else None)
        return conn

    async def close(self) -> None:
        for conn in list(self._connections):
            await conn.close()
        self._connections.clear()


class TcpServer(Server):
    def __init__(self, serializer_factory: Callable[[], Serializer]) -> None:
        self._serializer_factory = serializer_factory
        self._server: asyncio.AbstractServer | None = None
        self._connections: list[TcpConnection] = []

    async def listen(self, address: Address, on_connect: Callable[[Connection], None]) -> None:
        def accept(reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
            conn = TcpConnection(reader, writer, self._serializer_factory())
            self._connections.append(conn)
            conn.on_close(
                lambda c: self._connections.remove(c) if c in self._connections else None
            )
            on_connect(conn)

        self._server = await asyncio.start_server(accept, address.host, address.port)

    async def close(self) -> None:
        for conn in list(self._connections):
            await conn.close()
        self._connections.clear()
        if self._server is not None:
            self._server.close()
            # Python >=3.12 wait_closed() also waits for client handlers; all
            # connections are already closed above, but guard with a timeout in
            # case a transport lingers in the event loop.
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2.0)
            except (TimeoutError, asyncio.TimeoutError):
                pass


class TcpTransport(Transport):
    """Real-network transport; drop-in for LocalTransport."""

    def __init__(self) -> None:
        self._factory = Serializer

    def client(self) -> Client:
        return TcpClient(self._factory)

    def server(self) -> Server:
        return TcpServer(self._factory)
