"""TCP transport over asyncio streams (the reference's NettyTransport role).

Frames: ``[u32 length][u8 kind][u64 correlation id][payload]`` where kind is
REQUEST / RESPONSE / ERROR.  Payloads are serialized with the shared type-id
serializer, so anything that crosses LocalTransport crosses TCP identically.
This is the DCN/gRPC-role host-side transport of the TPU design (SURVEY.md
§5.8): client sessions and cross-slice traffic ride here, while intra-step
quorum traffic rides ICI collectives inside the compiled engine.

Burst handoff: the read loop drains whole socket reads and walks EVERY
complete frame in one pass — through the native codec's
``decode_frames`` (C: header walk + per-frame payload decode in one
call) when the extension is built, else a Python ``struct`` walk. A
burst of N frames costs one ``read()`` await + one frame walk instead
of 2N ``readexactly`` awaits, which is where the per-message asyncio
scheduling cost of the old loop lived. Handlers still run as
independent tasks (a burst must not serialize request handling — a
blocking command must never delay a keep-alive sharing its connection).
"""

from __future__ import annotations

import asyncio
import struct
from typing import Any, Callable

from ..utils.metrics import MetricsRegistry
from ..utils.tasks import spawn
from .codec import codec
from .serializer import Serializer
from .transport import (
    Address,
    Client,
    Connection,
    ConnectionClosedError,
    Server,
    Transport,
    TransportError,
)

_HEADER = struct.Struct(">IBQ")
_REQUEST, _RESPONSE, _ERROR = 0, 1, 2


class TcpConnection(Connection):
    def __init__(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
        serializer: Serializer, metrics: MetricsRegistry | None = None,
    ) -> None:
        super().__init__()
        self._reader = reader
        self._writer = writer
        self._serializer = serializer
        self._next_id = 0
        self._pending: dict[int, asyncio.Future] = {}
        # Transport-shared registry (TcpTransport.metrics); the counter
        # objects are cached so the read/write loops pay one attr + int
        # add per event, never a registry lookup.
        m = metrics if metrics is not None else MetricsRegistry()
        self._m_bytes_in = m.counter("bytes_in")
        self._m_bytes_out = m.counter("bytes_out")
        self._m_frames_in = m.counter("frames_in")
        self._m_frames_out = m.counter("frames_out")
        self._m_burst = m.histogram("read_burst_frames")
        self._reader_task = spawn(self._read_loop(), name="tcp-read-loop")

    def _walk_frames(self, buf: bytes | bytearray) -> tuple[list, int]:
        """Every complete frame in ``buf`` as ``(kind, corr, message,
        ok)`` records plus the bytes consumed. The C walk handles the
        whole burst in one call; any frame it cannot express (>64-bit
        ints, unregistered types, torn payload) re-runs the burst in
        Python, where per-frame decode errors become error records so
        one bad frame fails one request, not the connection."""
        c = codec()
        if c is not None:
            try:
                frames, consumed = c.decode_frames(buf)
                return [(k, co, m, True) for k, co, m in frames], consumed
            except Exception:
                pass
        frames: list = []
        pos = 0
        n = len(buf)
        while pos + _HEADER.size <= n:
            length, kind, corr = _HEADER.unpack_from(buf, pos)
            end = pos + _HEADER.size + length
            if end > n:
                break
            # bytes() copy: the read loop hands a mutable bytearray, and
            # decoded byte-typed fields must stay `bytes` downstream
            payload = bytes(buf[pos + _HEADER.size:end])
            try:
                frames.append((kind, corr, self._serializer.read(payload),
                               True))
            except Exception as exc:  # noqa: BLE001 — marshalled per frame
                frames.append((kind, corr, exc, False))
            pos = end
        return frames, pos

    async def _read_loop(self) -> None:
        # bytearray accumulation: `+=` is amortized O(n) and `del` of the
        # consumed prefix is linear, so a frame spanning many 64 KiB
        # reads costs one pass — bytes concatenation per chunk re-copied
        # the whole pending frame every read (quadratic in frame size)
        buf = bytearray()
        try:
            while True:
                chunk = await self._reader.read(1 << 16)
                if not chunk:
                    break
                self._m_bytes_in.inc(len(chunk))
                buf += chunk
                frames, consumed = self._walk_frames(buf)
                if consumed:
                    del buf[:consumed]
                if frames:
                    self._m_frames_in.inc(len(frames))
                    self._m_burst.record(len(frames))
                for kind, corr, message, ok in frames:
                    if kind == _REQUEST:
                        if ok:
                            spawn(self._serve(corr, message),
                                  name="tcp-serve")
                        else:  # decode error: fail THIS request only
                            self._write_error(corr, message)
                    else:
                        future = self._pending.pop(corr, None)
                        if future is not None and not future.done():
                            if not ok:
                                future.set_exception(TransportError(
                                    f"{type(message).__name__}: {message}"))
                            elif kind == _ERROR:
                                future.set_exception(TransportError(message))
                            else:
                                future.set_result(message)
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        finally:
            self._abort()

    async def _serve(self, corr: int, message: Any) -> None:
        try:
            result = await self._handle(message)
            self._write_message(_RESPONSE, corr, result)
        except Exception as exc:  # marshal handler errors back to the caller
            self._write_error(corr, exc)

    def _write_error(self, corr: int, exc: Any) -> None:
        try:
            self._write_message(_ERROR, corr,
                                f"{type(exc).__name__}: {exc}")
        except Exception:
            pass

    def _write_frame(self, kind: int, corr: int, payload: bytes) -> None:
        if self.closed:
            raise ConnectionClosedError("connection closed")
        self._m_frames_out.inc()
        self._m_bytes_out.inc(_HEADER.size + len(payload))
        self._writer.write(_HEADER.pack(len(payload), kind, corr) + payload)

    def _write_message(self, kind: int, corr: int, message: Any) -> None:
        """Frame + encode in one C pass when the codec is available (the
        header pack and bytes concat disappear into ``encode_frames``)."""
        if self.closed:
            raise ConnectionClosedError("connection closed")
        c = codec()
        if c is not None:
            try:
                data = c.encode_frames([(kind, corr, message)])
                self._writer.write(data)
                # count AFTER the write: a raising write falls through to
                # the Python path, which counts the frame itself — counting
                # first would tally one logical frame twice
                self._m_frames_out.inc()
                self._m_bytes_out.inc(len(data))
                return
            except Exception:  # Fallback etc. — the Python path decides
                pass
        self._write_frame(kind, corr, self._serializer.write(message))

    async def send(self, message: Any) -> Any:
        if self.closed:
            raise ConnectionClosedError("connection closed")
        self._next_id += 1
        corr = self._next_id
        future: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[corr] = future
        try:
            self._write_message(_REQUEST, corr, message)
            await self._writer.drain()
            return await future
        finally:
            # A caller-side cancellation (asyncio.wait_for timeout around
            # send — the replication and leadership-confirm paths) must
            # not strand the correlation in _pending until the connection
            # closes: pipelined peers issue thousands of correlated sends
            # per connection, and each stranded future is leaked memory
            # plus a slot the late response will never find. After a
            # normal response the read loop already popped corr — no-op.
            self._pending.pop(corr, None)

    def _abort(self) -> None:
        for future in self._pending.values():
            if not future.done():
                future.set_exception(ConnectionClosedError("connection closed"))
        self._pending.clear()
        self._fire_close()

    async def close(self) -> None:
        if not self.closed:
            self._fire_close()
            self._reader_task.cancel()
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionResetError, OSError):
                pass
        self._abort()


class TcpClient(Client):
    def __init__(self, serializer_factory: Callable[[], Serializer],
                 metrics: MetricsRegistry | None = None) -> None:
        self._serializer_factory = serializer_factory
        self._metrics = metrics
        self._connections: list[TcpConnection] = []

    async def connect(self, address: Address) -> Connection:
        reader, writer = await asyncio.open_connection(address.host, address.port)
        if self._metrics is not None:
            self._metrics.counter("connects").inc()
        conn = TcpConnection(reader, writer, self._serializer_factory(),
                             self._metrics)
        self._connections.append(conn)
        conn.on_close(lambda c: self._connections.remove(c) if c in self._connections else None)
        return conn

    async def close(self) -> None:
        for conn in list(self._connections):
            await conn.close()
        self._connections.clear()


class TcpServer(Server):
    def __init__(self, serializer_factory: Callable[[], Serializer],
                 metrics: MetricsRegistry | None = None) -> None:
        self._serializer_factory = serializer_factory
        self._metrics = metrics
        self._server: asyncio.AbstractServer | None = None
        self._connections: list[TcpConnection] = []

    async def listen(self, address: Address, on_connect: Callable[[Connection], None]) -> None:
        def accept(reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
            if self._metrics is not None:
                self._metrics.counter("accepts").inc()
            conn = TcpConnection(reader, writer, self._serializer_factory(),
                                 self._metrics)
            self._connections.append(conn)
            conn.on_close(
                lambda c: self._connections.remove(c) if c in self._connections else None
            )
            on_connect(conn)

        self._server = await asyncio.start_server(accept, address.host, address.port)

    async def close(self) -> None:
        for conn in list(self._connections):
            await conn.close()
        self._connections.clear()
        if self._server is not None:
            self._server.close()
            # Python >=3.12 wait_closed() also waits for client handlers; all
            # connections are already closed above, but guard with a timeout in
            # case a transport lingers in the event loop.
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2.0)
            except (TimeoutError, asyncio.TimeoutError):
                pass


class TcpTransport(Transport):
    """Real-network transport; drop-in for LocalTransport."""

    def __init__(self) -> None:
        self._factory = Serializer
        #: shared by every connection this transport hands out
        #: (bytes/frames in/out, read-burst histogram, connects/accepts)
        self.metrics = MetricsRegistry()

    def client(self) -> Client:
        return TcpClient(self._factory, self.metrics)

    def server(self) -> Server:
        return TcpServer(self._factory, self.metrics)
