"""In-memory transport (Catalyst ``LocalTransport``/``LocalServerRegistry``).

Hosts N logical nodes in one process — the substrate for the entire test
pyramid, exactly as in the reference where every multi-node test runs a real
Raft cluster over ``LocalTransport`` (reference ``AbstractServerTest.java:53-57``,
SURVEY.md §4).  Messages are round-tripped through the serializer on every hop
so wire-format bugs surface in unit tests, not just over TCP.
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable

from .serializer import Serializer
from .transport import (
    Address,
    Client,
    Connection,
    ConnectionClosedError,
    Server,
    Transport,
    TransportError,
)


class LocalServerRegistry:
    """Shared address -> listening-server map (one per simulated network)."""

    def __init__(self) -> None:
        self._servers: dict[Address, "LocalServer"] = {}

    def register(self, address: Address, server: "LocalServer") -> None:
        self._servers[address] = server

    def unregister(self, address: Address) -> None:
        self._servers.pop(address, None)

    def lookup(self, address: Address) -> "LocalServer | None":
        return self._servers.get(address)


class LocalConnection(Connection):
    """One endpoint of an in-memory duplex channel."""

    def __init__(self, serializer: Serializer) -> None:
        super().__init__()
        self._serializer = serializer
        self.peer: "LocalConnection | None" = None

    async def send(self, message: Any) -> Any:
        peer = self.peer
        if self.closed or peer is None or peer.closed:
            raise ConnectionClosedError("connection closed")
        # Round-trip through the wire format for fidelity with real transports.
        wire = self._serializer.write(message)
        delivered = peer._serializer.read(wire)
        try:
            result = await peer._handle(delivered)
        except TransportError:
            raise
        except Exception as exc:
            # Same marshalling contract as TcpConnection: handler errors cross
            # the transport as TransportError("Type: message").
            raise TransportError(f"{type(exc).__name__}: {exc}") from exc
        if result is None:
            return None
        return self._serializer.read(peer._serializer.write(result))

    async def close(self) -> None:
        peer = self.peer
        self._fire_close()
        if peer is not None and not peer.closed:
            peer._fire_close()


class LocalClient(Client):
    def __init__(self, registry: LocalServerRegistry, serializer: Serializer) -> None:
        self._registry = registry
        self._serializer = serializer
        self._connections: list[LocalConnection] = []

    async def connect(self, address: Address) -> Connection:
        server = self._registry.lookup(address)
        if server is None or server.closed:
            raise TransportError(f"no server listening at {address}")
        local = LocalConnection(self._serializer)
        remote = LocalConnection(server._serializer)
        local.peer = remote
        remote.peer = local
        self._connections.append(local)
        local.on_close(lambda c: self._connections.remove(c) if c in self._connections else None)
        # Give the server a chance to register handlers before first send.
        server._accept(remote)
        await asyncio.sleep(0)
        return local

    async def close(self) -> None:
        for conn in list(self._connections):
            await conn.close()
        self._connections.clear()


class LocalServer(Server):
    def __init__(self, registry: LocalServerRegistry, serializer: Serializer) -> None:
        self._registry = registry
        self._serializer = serializer
        self._address: Address | None = None
        self._on_connect: Callable[[Connection], None] | None = None
        self._connections: list[LocalConnection] = []
        self.closed = False

    async def listen(self, address: Address, on_connect: Callable[[Connection], None]) -> None:
        self._address = address
        self._on_connect = on_connect
        self._registry.register(address, self)

    def _accept(self, connection: LocalConnection) -> None:
        assert self._on_connect is not None
        self._connections.append(connection)
        connection.on_close(
            lambda c: self._connections.remove(c) if c in self._connections else None
        )
        self._on_connect(connection)

    async def close(self) -> None:
        self.closed = True
        if self._address is not None:
            self._registry.unregister(self._address)
        for conn in list(self._connections):
            await conn.close()
        self._connections.clear()


class LocalTransport(Transport):
    def __init__(self, registry: LocalServerRegistry, serializer: Serializer | None = None) -> None:
        self._registry = registry
        self._serializer = serializer or Serializer()

    def client(self) -> Client:
        return LocalClient(self._registry, Serializer())

    def server(self) -> Server:
        return LocalServer(self._registry, Serializer())
