"""In-memory transport (Catalyst ``LocalTransport``/``LocalServerRegistry``).

Hosts N logical nodes in one process — the substrate for the entire test
pyramid, exactly as in the reference where every multi-node test runs a real
Raft cluster over ``LocalTransport`` (reference ``AbstractServerTest.java:53-57``,
SURVEY.md §4).  Messages are round-tripped through the serializer on every hop
so wire-format bugs surface in unit tests, not just over TCP.
"""

from __future__ import annotations

import asyncio
import random
from typing import Any, Callable, Iterable

from ..utils.metrics import MetricsRegistry
from .serializer import Serializer
from .transport import (
    Address,
    Client,
    Connection,
    ConnectionClosedError,
    Server,
    Transport,
    TransportError,
)


class NetworkNemesis:
    """Fault plan for a :class:`LocalServerRegistry` network: partitions,
    one-directional blocks, message loss and delay.

    The reference's server tests run real consensus over a fake network
    they control (``AbstractServerTest.java:53-57``) and the project
    claims Jepsen-tested behavior (reference ``README.md:8``); this is
    the control plane that lets the HOST stack (asyncio Raft + SPI) be
    driven through the same fault envelope the device plane's
    ``deliver`` masks provide (SURVEY.md §5.3).

    Semantics (evaluated per message, so live connections are affected):

    - ``partition(sides...)``: only endpoints within the same side can
      exchange messages. Endpoints with no address (anonymous clients)
      or outside every side reach everyone — the Jepsen client model.
    - ``block(src, dst)``: one-directional edge cut (asymmetric
      partitions — the classic stale-leader-lease trap).
    - ``set_loss(request=, response=)``: independent drop probabilities
      for the request leg and the response leg. A dropped RESPONSE means
      the handler RAN but the sender sees a transport error — the
      at-most-once ambiguity exactly-once machinery must survive.
    - ``set_delay(min_s, max_s)``: uniform per-message latency.

    Faults surface to senders as :class:`TransportError` (what a real
    dead/slow link produces through the TCP transport's timeouts).
    """

    def __init__(self, seed: int = 0) -> None:
        self._rng = random.Random(seed)
        self._sides: list[frozenset[Address]] = []
        self._blocked: set[tuple[Address, Address]] = set()
        self._request_loss = 0.0
        self._response_loss = 0.0
        self._delay = (0.0, 0.0)
        #: counters for test assertions / soak reports
        self.delivered = 0
        self.dropped_requests = 0
        self.dropped_responses = 0

    # -- fault plan -------------------------------------------------------

    def partition(self, *sides: Iterable[Address]) -> None:
        """Replace the current partition with ``sides`` (each an iterable
        of addresses); messages flow only within a side."""
        self._sides = [frozenset(s) for s in sides]

    def block(self, src: Address, dst: Address) -> None:
        """Cut the ``src -> dst`` direction only."""
        self._blocked.add((src, dst))

    def set_loss(self, request: float = 0.0, response: float = 0.0) -> None:
        self._request_loss = request
        self._response_loss = response

    def set_delay(self, min_s: float = 0.0, max_s: float | None = None
                  ) -> None:
        """Uniform per-message delay in [min_s, max_s]; ``set_delay(x)``
        means a fixed ``x``-second delay."""
        if max_s is None:
            max_s = min_s
        if min_s < 0 or max_s < min_s:
            raise ValueError(f"bad delay range [{min_s}, {max_s}]")
        self._delay = (min_s, max_s)

    def heal(self) -> None:
        """Clear every fault (partitions, blocks, loss, delay)."""
        self._sides = []
        self._blocked.clear()
        self._request_loss = self._response_loss = 0.0
        self._delay = (0.0, 0.0)

    # -- per-message evaluation ------------------------------------------

    def allowed(self, src: Address | None, dst: Address | None) -> bool:
        if src is not None and dst is not None:
            if (src, dst) in self._blocked:
                return False
            # endpoints listed in some side may only talk within their
            # side; anything unlisted (anonymous clients, unnamed nodes)
            # reaches everyone — the Jepsen client model
            src_side = next((i for i, s in enumerate(self._sides)
                             if src in s), None)
            dst_side = next((i for i, s in enumerate(self._sides)
                             if dst in s), None)
            if src_side is not None and dst_side is not None \
                    and src_side != dst_side:
                return False
        return True

    def delay_s(self) -> float:
        lo, hi = self._delay
        return self._rng.uniform(lo, hi) if hi > 0 else 0.0

    def drop_request(self, src: Address | None, dst: Address | None) -> bool:
        if not self.allowed(src, dst):
            self.dropped_requests += 1
            return True
        if self._request_loss and self._rng.random() < self._request_loss:
            self.dropped_requests += 1
            return True
        return False

    def drop_response(self, src: Address | None, dst: Address | None) -> bool:
        # the response leg travels dst -> src
        if not self.allowed(dst, src):
            self.dropped_responses += 1
            return True
        if self._response_loss and self._rng.random() < self._response_loss:
            self.dropped_responses += 1
            return True
        return False


class LocalServerRegistry:
    """Shared address -> listening-server map (one per simulated network)."""

    def __init__(self) -> None:
        self._servers: dict[Address, "LocalServer"] = {}
        self.nemesis: NetworkNemesis | None = None

    def attach_nemesis(self, nemesis: NetworkNemesis | None = None
                       ) -> NetworkNemesis:
        """Install (and return) a fault plan every connection on this
        network consults per message."""
        self.nemesis = nemesis or NetworkNemesis()
        return self.nemesis

    def register(self, address: Address, server: "LocalServer") -> None:
        self._servers[address] = server

    def unregister(self, address: Address) -> None:
        self._servers.pop(address, None)

    def lookup(self, address: Address) -> "LocalServer | None":
        return self._servers.get(address)


class LocalConnection(Connection):
    """One endpoint of an in-memory duplex channel."""

    def __init__(self, serializer: Serializer,
                 registry: "LocalServerRegistry | None" = None,
                 local_address: Address | None = None,
                 remote_address: Address | None = None,
                 metrics: MetricsRegistry | None = None) -> None:
        super().__init__()
        self._serializer = serializer
        self._registry = registry
        self.local_address = local_address
        self.remote_address = remote_address
        self.peer: "LocalConnection | None" = None
        m = metrics if metrics is not None else MetricsRegistry()
        self._m_bytes_out = m.counter("bytes_out")
        self._m_frames_out = m.counter("frames_out")
        self._m_bytes_in = m.counter("bytes_in")
        self._m_frames_in = m.counter("frames_in")

    async def send(self, message: Any) -> Any:
        peer = self.peer
        if self.closed or peer is None or peer.closed:
            raise ConnectionClosedError("connection closed")
        nem = self._registry.nemesis if self._registry is not None else None
        if nem is not None:
            d = nem.delay_s()
            if d:
                await asyncio.sleep(d)
            if nem.drop_request(self.local_address, self.remote_address):
                raise TransportError(
                    f"nemesis: request {self.local_address} -> "
                    f"{self.remote_address} dropped")
        # Round-trip through the wire format for fidelity with real transports.
        wire = self._serializer.write(message)
        self._m_frames_out.inc()
        self._m_bytes_out.inc(len(wire))
        peer._m_frames_in.inc()
        peer._m_bytes_in.inc(len(wire))
        delivered = peer._serializer.read(wire)
        try:
            result = await peer._handle(delivered)
        except TransportError:
            raise
        except Exception as exc:
            # Same marshalling contract as TcpConnection: handler errors cross
            # the transport as TransportError("Type: message").
            raise TransportError(f"{type(exc).__name__}: {exc}") from exc
        if nem is not None:
            # symmetric per-message delay: the response leg pays the same
            # latency draw as the request leg (a real network delays both
            # directions), and pays it BEFORE the drop evaluation — a
            # dropped response still spent its wire time
            d = nem.delay_s()
            if d:
                await asyncio.sleep(d)
        if nem is not None and nem.drop_response(self.local_address,
                                                 self.remote_address):
            # the handler RAN; only the reply is lost — the sender must
            # treat the op's fate as unknown (at-most-once ambiguity)
            raise TransportError(
                f"nemesis: response {self.remote_address} -> "
                f"{self.local_address} dropped")
        if nem is not None:
            nem.delivered += 1
        if result is None:
            return None
        # response leg: the peer SENDS, we receive — counted like the
        # request leg so cross-transport attribution (local vs tcp in
        # the spi bench) compares like with like
        wire = peer._serializer.write(result)
        peer._m_frames_out.inc()
        peer._m_bytes_out.inc(len(wire))
        self._m_frames_in.inc()
        self._m_bytes_in.inc(len(wire))
        return self._serializer.read(wire)

    async def close(self) -> None:
        peer = self.peer
        self._fire_close()
        if peer is not None and not peer.closed:
            peer._fire_close()


class LocalClient(Client):
    def __init__(self, registry: LocalServerRegistry, serializer: Serializer,
                 local_address: Address | None = None,
                 metrics: MetricsRegistry | None = None) -> None:
        self._registry = registry
        self._serializer = serializer
        self._local_address = local_address
        self._metrics = metrics
        self._connections: list[LocalConnection] = []

    async def connect(self, address: Address) -> Connection:
        server = self._registry.lookup(address)
        if server is None or server.closed:
            raise TransportError(f"no server listening at {address}")
        nem = self._registry.nemesis
        if nem is not None and not nem.allowed(self._local_address, address):
            raise TransportError(
                f"nemesis: dial {self._local_address} -> {address} blocked")
        if self._metrics is not None:
            self._metrics.counter("connects").inc()
        local = LocalConnection(self._serializer, self._registry,
                                self._local_address, address, self._metrics)
        remote = LocalConnection(server._serializer, self._registry,
                                 address, self._local_address,
                                 server._metrics)
        local.peer = remote
        remote.peer = local
        self._connections.append(local)
        local.on_close(lambda c: self._connections.remove(c) if c in self._connections else None)
        # Give the server a chance to register handlers before first send.
        server._accept(remote)
        await asyncio.sleep(0)
        return local

    async def close(self) -> None:
        for conn in list(self._connections):
            await conn.close()
        self._connections.clear()


class LocalServer(Server):
    def __init__(self, registry: LocalServerRegistry, serializer: Serializer,
                 metrics: MetricsRegistry | None = None) -> None:
        self._registry = registry
        self._serializer = serializer
        self._metrics = metrics
        self._address: Address | None = None
        self._on_connect: Callable[[Connection], None] | None = None
        self._connections: list[LocalConnection] = []
        self.closed = False

    async def listen(self, address: Address, on_connect: Callable[[Connection], None]) -> None:
        self._address = address
        self._on_connect = on_connect
        self._registry.register(address, self)

    def _accept(self, connection: LocalConnection) -> None:
        assert self._on_connect is not None
        self._connections.append(connection)
        connection.on_close(
            lambda c: self._connections.remove(c) if c in self._connections else None
        )
        self._on_connect(connection)

    async def close(self) -> None:
        self.closed = True
        if self._address is not None:
            self._registry.unregister(self._address)
        for conn in list(self._connections):
            await conn.close()
        self._connections.clear()


class LocalTransport(Transport):
    def __init__(self, registry: LocalServerRegistry,
                 serializer: Serializer | None = None,
                 local_address: Address | None = None) -> None:
        self._registry = registry
        self._serializer = serializer or Serializer()
        # The identity this node's DIALS carry (partition membership for
        # client-side connections). Servers are identified by the address
        # they listen on; anonymous transports (no local_address) reach
        # every side of a partition — the Jepsen client model.
        self._local_address = local_address
        #: shared by every endpoint this transport hands out
        self.metrics = MetricsRegistry()

    def client(self) -> Client:
        return LocalClient(self._registry, Serializer(),
                           self._local_address, self.metrics)

    def server(self) -> Server:
        return LocalServer(self._registry, Serializer(), self.metrics)
