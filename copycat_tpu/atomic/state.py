"""Server state machine for the atomic value (reference
``AtomicValueState.java:32``): single value + owning commit, TTL expiry via
deterministic log-time timers, "change" events to Listen sessions, careful
clean() of superseded commits."""

from __future__ import annotations

from typing import Any

from ..io.serializer import serialize_with
from ..resource.state_machine import ResourceStateMachine
from ..server.state_machine import Commit
from . import commands


@serialize_with(56)
class AtomicValueState(ResourceStateMachine):
    def __init__(self) -> None:
        super().__init__()
        self.value: Any = None
        self._current: Commit | None = None  # commit owning the live value
        self._timer = None
        self._listeners: dict[int, Commit] = {}  # session id -> Listen commit

    # -- value ops ---------------------------------------------------------

    def get(self, commit: Commit[commands.Get]) -> Any:
        try:
            return self.value
        finally:
            commit.close()

    def set(self, commit: Commit[commands.Set]) -> None:
        self._set_current(commit, commit.operation.value, commit.operation.ttl)

    def get_and_set(self, commit: Commit[commands.GetAndSet]) -> Any:
        previous = self.value
        self._set_current(commit, commit.operation.value, commit.operation.ttl)
        return previous

    def compare_and_set(self, commit: Commit[commands.CompareAndSet]) -> bool:
        op = commit.operation
        if self.value == op.expect:
            self._set_current(commit, op.update, op.ttl)
            return True
        commit.clean()
        return False

    def _set_current(self, commit: Commit, value: Any, ttl: float | None) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if self._current is not None:
            self._current.clean()  # superseded value's commit is reclaimable
        self._current = commit
        changed = value != self.value
        self.value = value
        if ttl:
            def expire() -> None:
                self._expire_value()

            self._timer = self.executor.schedule(ttl, expire)
        if changed:
            self._publish_change(value)

    def _expire_value(self) -> None:
        if self._current is not None:
            self._current.clean()
            self._current = None
        self.value = None
        self._timer = None
        self._publish_change(None)

    # -- change listeners --------------------------------------------------

    def listen(self, commit: Commit[commands.Listen]) -> None:
        session_id = commit.session.id
        previous = self._listeners.get(session_id)
        if previous is not None:
            previous.clean()
        self._listeners[session_id] = commit

    def unlisten(self, commit: Commit[commands.Unlisten]) -> None:
        previous = self._listeners.pop(commit.session.id, None)
        if previous is not None:
            previous.clean()
        commit.clean()

    def _publish_change(self, value: Any) -> None:
        for listen_commit in list(self._listeners.values()):
            session = listen_commit.session
            if session.is_open:
                session.publish("change", value)

    # -- snapshot hooks (crash-recovery plane, docs/DURABILITY.md) --------
    # The plain register states snapshot as one value. States holding
    # commit references that cannot round-trip — an armed TTL timer or
    # live change listeners — opt out (NotImplemented), keeping the whole
    # server on replay-only recovery instead of a lossy image.

    def edge_state(self) -> Any:
        # the whole register IS the state: one tagged value per delta
        # (docs/EDGE_READS.md) — `Get` evaluates client-side as identity.
        # An armed TTL expires via an executor timer OUTSIDE any command
        # apply, where the delta plane's dirty marking cannot see it —
        # refresh records would certify the expired value indefinitely —
        # so TTL'd state opts out (subscribers are retired), the same
        # rule snapshot_state applies.
        if self._timer is not None:
            return NotImplemented
        return ("val", self.value)

    def snapshot_state(self) -> Any:
        if self._timer is not None or self._listeners:
            return NotImplemented
        return {"value": self.value}

    def restore_state(self, data: Any, sessions: dict) -> None:
        self.value = data["value"]
        if self.value is not None:
            # the owning commit is behind the snapshot boundary (entry
            # already released): a log-less stand-in keeps the
            # retained-commit discipline (clean() is a no-op)
            self._current = Commit(0, None, 0.0, None, None)

    # -- lifecycle ---------------------------------------------------------

    def close(self, session: Any) -> None:
        listen_commit = self._listeners.pop(session.id, None)
        if listen_commit is not None:
            listen_commit.clean()

    def delete(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if self._current is not None:
            self._current.clean()
            self._current = None
        for listen_commit in self._listeners.values():
            listen_commit.clean()
        self._listeners.clear()
        self.value = None
