"""Client API for the linearizable register (reference
``DistributedAtomicValue.java:38``): get/set/get_and_set/compare_and_set with
optional TTLs, plus ``on_change`` listeners fed by "change" session events
(first local listener submits Listen; last close submits Unlisten)."""

from __future__ import annotations

from typing import Any, Callable

from ..resource.resource import AbstractResource, resource_info
from ..utils.listeners import Listener, Listeners
from . import commands
from .state import AtomicValueState


@resource_info(state_machine=AtomicValueState)
class DistributedAtomicValue(AbstractResource):
    def __init__(self, client: Any) -> None:
        super().__init__(client)
        self._change_listeners = Listeners()
        self._listen_state: dict = {}
        self.session().on_event("change", self._on_change)

    async def get(self) -> Any:
        return await self.submit(commands.Get())

    async def set(self, value: Any, ttl: float | None = None) -> None:
        await self.submit_command(commands.Set(value=value, ttl=ttl))

    async def get_and_set(self, value: Any, ttl: float | None = None) -> Any:
        return await self.submit_command(
            commands.GetAndSet(value=value, ttl=ttl))

    async def compare_and_set(self, expect: Any, update: Any,
                              ttl: float | None = None) -> bool:
        return bool(await self.submit_command(
            commands.CompareAndSet(expect=expect, update=update, ttl=ttl)))

    async def on_change(self, callback: Callable[[Any], Any]) -> Listener:
        """Register a change listener; the first one registers server-side."""
        return await self._tracked_listener(
            self._change_listeners, callback, self._listen_state,
            commands.Listen(), commands.Unlisten)

    def _on_change(self, value: Any) -> None:
        self._change_listeners.accept(value)
