"""Distributed counter (reference ``DistributedAtomicLong.java:29``).

Arithmetic is implemented CLIENT-SIDE as an optimistic compare-and-set retry
loop over the underlying atomic value — exactly the reference's ``updateValue``
recursion — exercising the linearizable CAS path under contention (this is
BASELINE config #1)."""

from __future__ import annotations

from typing import Any

from ..resource.resource import resource_info
from . import commands
from .state import AtomicValueState
from .value import DistributedAtomicValue


@resource_info(state_machine=AtomicValueState)
class DistributedAtomicLong(DistributedAtomicValue):
    _UNSET = object()

    def __init__(self, client: Any) -> None:
        super().__init__(client)
        self._raw: Any = self._UNSET  # last observed raw value (None = unset register)

    async def get(self) -> int:
        self._raw = await super().get()
        return int(self._raw) if self._raw is not None else 0

    async def set(self, value: int, ttl: float | None = None) -> None:
        await super().set(int(value), ttl)
        self._raw = int(value)

    async def _update(self, delta: int) -> tuple[int, int]:
        """CAS-retry loop; returns (old, new).  CAS runs against the RAW
        register value so the unset (None) register reads as 0 but still
        compare-and-sets correctly. Submits the CAS directly through the
        flattened facade lane (one coroutine frame fewer per op than
        going through :meth:`compare_and_set` — this loop IS the spi
        bench's hot path)."""
        if self._raw is self._UNSET:
            await self.get()
        while True:
            expect_raw = self._raw
            old = int(expect_raw) if expect_raw is not None else 0
            update = old + delta
            if await self.submit_command(
                    commands.CompareAndSet(expect_raw, update, None)):
                self._raw = update
                return old, update
            await self.get()  # refresh and retry

    async def add_and_get(self, delta: int) -> int:
        return (await self._update(delta))[1]

    async def get_and_add(self, delta: int) -> int:
        return (await self._update(delta))[0]

    async def increment_and_get(self) -> int:
        return await self.add_and_get(1)

    async def decrement_and_get(self) -> int:
        return await self.add_and_get(-1)

    async def get_and_increment(self) -> int:
        return await self.get_and_add(1)

    async def get_and_decrement(self) -> int:
        return await self.get_and_add(-1)
