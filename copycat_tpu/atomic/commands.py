"""Atomic value operation catalog (reference ``AtomicValueCommands.java``,
serializer ids 50-55).  ``ValueCommand.persistence()`` is PERSISTENT iff a TTL
is set, EPHEMERAL otherwise — TTL-less writes are droppable once superseded."""

from __future__ import annotations

from ..io.serializer import serialize_with
from ..protocol.messages import Message
from ..protocol.operations import Command, Persistence, Query


class ValueCommand(Message, Command):
    def persistence(self) -> Persistence:
        return Persistence.PERSISTENT if getattr(self, "ttl", None) else Persistence.EPHEMERAL


@serialize_with(50)
class Get(Message, Query):
    _fields = ()


@serialize_with(51)
class Set(ValueCommand):
    _fields = ("value", "ttl")


@serialize_with(52)
class CompareAndSet(ValueCommand):
    _fields = ("expect", "update", "ttl")


@serialize_with(53)
class GetAndSet(ValueCommand):
    _fields = ("value", "ttl")


@serialize_with(54)
class Listen(Message, Command):
    _fields = ()


@serialize_with(55)
class Unlisten(Message, Command):
    _fields = ()
