"""Atomic resources (reference ``atomic/`` module, SURVEY.md §2.1):
``DistributedAtomicValue`` (linearizable register with TTL + change events) and
``DistributedAtomicLong`` (client-side CAS-retry counter on top of it)."""

from .value import DistributedAtomicValue
from .long import DistributedAtomicLong
from .state import AtomicValueState

__all__ = ["DistributedAtomicValue", "DistributedAtomicLong", "AtomicValueState"]
