"""asyncio hygiene rules: ``loop-blocking`` and ``orphan-task``.

``loop-blocking``: a curated blocklist of calls that stall the event
loop when made from an ``async def`` body. One stalled turn holds every
staged read window and replication ack behind it — the latency hazard
is measured, not theoretical (the read pump coalesces per event-loop
turn, PERF.md round 9). Nested *sync* defs are skipped: blocking there
is judged at the call site.

``orphan-task``: ``loop.create_task`` / ``asyncio.ensure_future``
anywhere but ``utils/tasks.py``. The loop holds only a weak reference to
tasks — a fire-and-forget task can be garbage-collected mid-flight, and
an unobserved exception vanishes. ``utils/tasks.spawn`` is the one
blessed spawn point (strong ref until done + error logging), so every
background task in the tree shares its lifecycle guarantees.
"""

from __future__ import annotations

import ast

from .astutil import (
    body_nodes_excluding_nested_defs,
    dotted_name,
    enclosing_symbol,
    iter_async_functions,
)
from .findings import Finding

# Call chains that block the calling thread. Receiver-qualified names
# match exactly ("time.sleep" does not match "asyncio.time.sleep" — the
# chain is rendered from the AST, so aliasing hides from us; the rule is
# a tripwire, not a sandbox).
BLOCKING_CALLS = {
    "time.sleep": "blocks the event loop; use `await asyncio.sleep(...)`",
    "os.fsync": "synchronous disk flush on the loop thread",
    "os.fdatasync": "synchronous disk flush on the loop thread",
    "os.replace": "synchronous rename on the loop thread",
    "subprocess.run": "blocking subprocess wait",
    "subprocess.call": "blocking subprocess wait",
    "subprocess.check_call": "blocking subprocess wait",
    "subprocess.check_output": "blocking subprocess wait",
    "shutil.rmtree": "synchronous recursive delete on the loop thread",
    "shutil.copyfile": "synchronous file copy on the loop thread",
    "shutil.copytree": "synchronous tree copy on the loop thread",
    "jax.device_get": "synchronous device fetch on the loop thread",
    "jax.block_until_ready": "synchronous device sync on the loop thread",
}

# Method names that block regardless of receiver.
BLOCKING_METHODS = {
    "block_until_ready": "synchronous device sync on the loop thread",
}

# The builtin ``open``: sync file I/O from a coroutine.
BLOCKING_BUILTINS = {
    "open": "synchronous file open/IO on the loop thread",
}

SPAWN_CALLS = ("create_task", "ensure_future")


def check_loop_blocking(tree: ast.Module, path: str) -> list[Finding]:
    findings: list[Finding] = []
    for fn, qual in iter_async_functions(tree):
        for node in body_nodes_excluding_nested_defs(fn):
            if not isinstance(node, ast.Call):
                continue
            why = None
            name = dotted_name(node.func)
            if name in BLOCKING_CALLS:
                why = f"`{name}(...)` — {BLOCKING_CALLS[name]}"
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr in BLOCKING_METHODS):
                why = (f"`.{node.func.attr}(...)` — "
                       f"{BLOCKING_METHODS[node.func.attr]}")
            elif (isinstance(node.func, ast.Name)
                  and node.func.id in BLOCKING_BUILTINS):
                why = (f"`{node.func.id}(...)` — "
                       f"{BLOCKING_BUILTINS[node.func.id]}")
            if why:
                findings.append(Finding(
                    rule="loop-blocking", path=path, line=node.lineno,
                    message=f"blocking call in async def: {why}",
                    symbol=qual))
    return findings


def check_orphan_task(tree: ast.Module, path: str) -> list[Finding]:
    if path.endswith("utils/tasks.py"):
        return []  # the blessed spawn point itself
    findings: list[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        is_spawn = (
            (isinstance(func, ast.Attribute) and func.attr in SPAWN_CALLS)
            or (isinstance(func, ast.Name) and func.id in SPAWN_CALLS))
        if not is_spawn:
            continue
        findings.append(Finding(
            rule="orphan-task", path=path, line=node.lineno,
            message=("raw task spawn — the loop keeps only a weak ref and "
                     "exceptions vanish; route through `utils/tasks.spawn` "
                     "(returns the task, logs failures, holds a strong ref)"),
            symbol=enclosing_symbol(tree, node.lineno)))
    return findings
