"""asyncio hygiene rules: ``loop-blocking`` and ``orphan-task``.

``loop-blocking``: a curated blocklist of calls that stall the event
loop when made from an ``async def`` body. One stalled turn holds every
staged read window and replication ack behind it — the latency hazard
is measured, not theoretical (the read pump coalesces per event-loop
turn, PERF.md round 9). Since copycheck v2 the rule is
**interprocedural**: a blocking call inside a SYNC helper is flagged
when the package call graph (:mod:`callgraph`) proves the helper
reachable from an ``async def`` through resolved sync calls — running a
sync helper inline IS running its blocking call on the loop thread. The
finding lands on the blocking call and carries the call chain from the
async root in its message (and ``via`` metadata). Nested sync defs
inside an async def are still skipped lexically: they are judged where
they're reachable from, not where they're written.

``orphan-task``: ``loop.create_task`` / ``asyncio.ensure_future``
anywhere but ``utils/tasks.py``. The loop holds only a weak reference to
tasks — a fire-and-forget task can be garbage-collected mid-flight, and
an unobserved exception vanishes. ``utils/tasks.spawn`` is the one
blessed spawn point (strong ref until done + error logging), so every
background task in the tree shares its lifecycle guarantees.
"""

from __future__ import annotations

import ast

from .astutil import (
    body_nodes_excluding_nested_defs,
    dotted_name,
    enclosing_symbol,
    iter_async_functions,
)
from .callgraph import CallGraph, awaited_call_nodes, own_body
from .findings import Finding

# Call chains that block the calling thread. Receiver-qualified names
# match exactly ("time.sleep" does not match "asyncio.time.sleep" — the
# chain is rendered from the AST, so aliasing hides from us; the rule is
# a tripwire, not a sandbox).
BLOCKING_CALLS = {
    "time.sleep": "blocks the event loop; use `await asyncio.sleep(...)`",
    "os.fsync": "synchronous disk flush on the loop thread",
    "os.fdatasync": "synchronous disk flush on the loop thread",
    "os.replace": "synchronous rename on the loop thread",
    "os.waitpid": "blocking child-process wait on the loop thread",
    "subprocess.run": "blocking subprocess wait",
    "subprocess.call": "blocking subprocess wait",
    "subprocess.check_call": "blocking subprocess wait",
    "subprocess.check_output": "blocking subprocess wait",
    "socket.create_connection": "blocking connect on the loop thread; "
                                "use the loop/transport APIs",
    "shutil.rmtree": "synchronous recursive delete on the loop thread",
    "shutil.copyfile": "synchronous file copy on the loop thread",
    "shutil.copytree": "synchronous tree copy on the loop thread",
    "shutil.copyfileobj": "synchronous stream copy on the loop thread",
    "jax.device_get": "synchronous device fetch on the loop thread",
    "jax.block_until_ready": "synchronous device sync on the loop thread",
}

# Method names that block regardless of receiver.
BLOCKING_METHODS = {
    "block_until_ready": "synchronous device sync on the loop thread",
}

# Method names that block UNLESS the call sits under an ``await``
# (``proc.wait()`` from subprocess.Popen blocks; ``await proc.wait()``
# and ``await wait_for(proc.wait(), t)`` are the asyncio coroutine).
BLOCKING_METHODS_UNLESS_AWAITED = {
    "wait": "blocking wait (Popen.wait / Event.wait) on the loop thread; "
            "await the asyncio form instead",
}

# The builtin ``open``: sync file I/O from a coroutine.
BLOCKING_BUILTINS = {
    "open": "synchronous file open/IO on the loop thread",
}

SPAWN_CALLS = ("create_task", "ensure_future")


def _blocking_reason(node: ast.Call,
                     awaited: set[int] | None = None) -> tuple | None:
    """``(culprit, why)`` when this call matches the blocklist."""
    name = dotted_name(node.func)
    if name in BLOCKING_CALLS:
        return f"`{name}(...)`", BLOCKING_CALLS[name]
    if isinstance(node.func, ast.Attribute):
        attr = node.func.attr
        if attr in BLOCKING_METHODS:
            return f"`.{attr}(...)`", BLOCKING_METHODS[attr]
        if attr in BLOCKING_METHODS_UNLESS_AWAITED \
                and not (awaited and id(node) in awaited):
            return (f"`.{attr}(...)`",
                    BLOCKING_METHODS_UNLESS_AWAITED[attr])
    if isinstance(node.func, ast.Name) and node.func.id in BLOCKING_BUILTINS:
        return f"`{node.func.id}(...)`", BLOCKING_BUILTINS[node.func.id]
    return None


def check_loop_blocking(tree: ast.Module, path: str,
                        graph: CallGraph | None = None) -> list[Finding]:
    findings: list[Finding] = []
    for fn, qual in iter_async_functions(tree):
        awaited = awaited_call_nodes(fn)
        for node in body_nodes_excluding_nested_defs(fn):
            if not isinstance(node, ast.Call):
                continue
            hit = _blocking_reason(node, awaited)
            if hit:
                culprit, why = hit
                findings.append(Finding(
                    rule="loop-blocking", path=path, line=node.lineno,
                    message=f"blocking call in async def: {culprit} — {why}",
                    symbol=qual))
    if graph is not None:
        findings += _check_reachable_blocking(tree, path, graph)
    return findings


def _check_reachable_blocking(tree: ast.Module, path: str,
                              graph: CallGraph) -> list[Finding]:
    """Interprocedural half: blocking calls inside SYNC functions of
    this file that the graph proves reachable from an async def."""
    findings: list[Finding] = []
    for (fpath, qual), chain in sorted(graph.async_reachable.items()):
        if fpath != path:
            continue
        info = graph.info_for(fpath, qual)
        if info is None or info.node is None:
            continue
        awaited = awaited_call_nodes(info.node)
        for node in own_body(info.node):
            if not isinstance(node, ast.Call):
                continue
            hit = _blocking_reason(node, awaited)
            if hit:
                culprit, why = hit
                # the example chain rides `via` metadata, NOT the
                # message: finding identity (baseline matching) must not
                # churn when an unrelated edit reroutes the shortest
                # discovered path
                findings.append(Finding(
                    rule="loop-blocking", path=path, line=node.lineno,
                    message=(f"blocking call in a sync helper reachable "
                             f"from an async def: {culprit} — {why}"),
                    symbol=qual, via=list(chain)))
    return findings


def check_orphan_task(tree: ast.Module, path: str) -> list[Finding]:
    if path.endswith("utils/tasks.py"):
        return []  # the blessed spawn point itself
    findings: list[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        is_spawn = (
            (isinstance(func, ast.Attribute) and func.attr in SPAWN_CALLS)
            or (isinstance(func, ast.Name) and func.id in SPAWN_CALLS))
        if not is_spawn:
            continue
        findings.append(Finding(
            rule="orphan-task", path=path, line=node.lineno,
            message=("raw task spawn — the loop keeps only a weak ref and "
                     "exceptions vanish; route through `utils/tasks.spawn` "
                     "(returns the task, logs failures, holds a strong ref)"),
            symbol=enclosing_symbol(tree, node.lineno)))
    return findings
