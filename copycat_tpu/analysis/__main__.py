"""``python -m copycat_tpu.analysis`` — same surface as ``copycat-tpu
lint`` (jax-free; see docs/ANALYSIS.md)."""

import sys

from .engine import main

if __name__ == "__main__":
    sys.exit(main())
