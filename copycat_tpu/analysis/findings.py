"""Findings model: one dataclass, inline suppressions, the baseline file.

A finding's *identity* for baseline matching is ``(rule, path, symbol,
message)`` — deliberately line-free, so unrelated edits above a
baselined site don't resurrect it. ``symbol`` is the enclosing
``Class.method`` (or ``<module>``), which keeps identities stable when a
function moves wholesale.

Suppressions are line-scoped: ``# copycheck: ignore[rule]`` (or
``ignore[rule-a,rule-b]``) on the finding's line or the line directly
above it. The baseline file (``.copycheck-baseline.json``) carries the
*intentionally kept* findings, each with a one-line ``justification`` —
``copycat-tpu lint --write-baseline`` generates entries, the reviewer
fills the why. CI (``--strict``) fails on any finding that is neither
suppressed nor baselined.
"""

from __future__ import annotations

import json
import re
from dataclasses import asdict, dataclass, field

SUPPRESS_RE = re.compile(r"#\s*copycheck:\s*ignore\[([a-z0-9_,\- *]+)\]")


@dataclass
class Finding:
    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str
    symbol: str = "<module>"
    #: optional call-chain metadata for interprocedural findings (the
    #: labels from the async root to the flagged site). Deliberately
    #: NOT part of identity(): the example chain may reroute under
    #: unrelated edits, and a baselined finding must not resurrect.
    via: list | None = None

    def identity(self) -> tuple[str, str, str, str]:
        return (self.rule, self.path, self.symbol, self.message)

    def render(self) -> str:
        out = f"{self.path}:{self.line}: {self.rule}: {self.message} [{self.symbol}]"
        if self.via:
            out += "\n    via " + " -> ".join(self.via)
        return out

    def to_json(self) -> dict:
        d = asdict(self)
        if d.get("via") is None:
            del d["via"]
        return d


def scan_suppressions(source: str) -> dict[int, set[str]]:
    """Map line number -> set of suppressed rule names (``*`` = all).

    A pragma suppresses its own line and the line below it, so both
    styles read naturally::

        loop.create_task(coro)  # copycheck: ignore[orphan-task] why
        # copycheck: ignore[loop-blocking] shutdown path, loop is done
        shutil.rmtree(tmp)
    """
    out: dict[int, set[str]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        out.setdefault(i, set()).update(rules)
        out.setdefault(i + 1, set()).update(rules)
    return out


def is_suppressed(finding: Finding, suppressions: dict[int, set[str]]) -> bool:
    rules = suppressions.get(finding.line)
    if not rules:
        return False
    return "*" in rules or finding.rule in rules


@dataclass
class Baseline:
    """The accepted-findings file: identity -> justification."""

    entries: dict[tuple[str, str, str, str], str] = field(default_factory=dict)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        try:
            with open(path, encoding="utf-8") as f:
                raw = json.load(f)
        except FileNotFoundError:
            return cls()
        entries = {}
        for item in raw.get("findings", []):
            key = (item["rule"], item["path"], item.get("symbol", "<module>"),
                   item["message"])
            entries[key] = item.get("justification", "")
        return cls(entries)

    def save(self, path: str) -> None:
        findings = [
            {"rule": rule, "path": p, "symbol": symbol, "message": message,
             "justification": just or "TODO: justify or fix"}
            for (rule, p, symbol, message), just in sorted(self.entries.items())
        ]
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"version": 1, "findings": findings}, f, indent=2,
                      sort_keys=False)
            f.write("\n")

    def match(self, finding: Finding) -> bool:
        return finding.identity() in self.entries

    def stale(self, findings: list[Finding]) -> list[tuple]:
        """Baseline identities that no current finding matches — they
        were fixed (or moved); prune them so the file can't rot."""
        live = {f.identity() for f in findings}
        return [key for key in self.entries if key not in live]
