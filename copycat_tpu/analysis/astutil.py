"""Tiny shared AST helpers for the copycheck rules (stdlib only)."""

from __future__ import annotations

import ast
from typing import Iterator


def qualname_map(tree: ast.Module) -> dict[ast.AST, str]:
    """Map every function/class node to its dotted qualname."""
    out: dict[ast.AST, str] = {}

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                out[child] = qual
                visit(child, qual)
            else:
                visit(child, prefix)

    visit(tree, "")
    return out


def enclosing_symbol(tree: ast.Module, lineno: int) -> str:
    """The qualname of the innermost def/class spanning ``lineno``."""
    best = "<module>"
    best_span = None
    for node, qual in qualname_map(tree).items():
        end = getattr(node, "end_lineno", node.lineno)
        if node.lineno <= lineno <= end:
            span = end - node.lineno
            if best_span is None or span <= best_span:
                best, best_span = qual, span
        # decorated defs report their body lineno; include decorators
        for deco in getattr(node, "decorator_list", []):
            if deco.lineno <= lineno <= getattr(deco, "end_lineno",
                                                deco.lineno):
                return qual
    return best


def iter_async_functions(
        tree: ast.Module) -> Iterator[tuple[ast.AsyncFunctionDef, str]]:
    quals = qualname_map(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.AsyncFunctionDef):
            yield node, quals.get(node, node.name)


def body_nodes_excluding_nested_defs(fn: ast.AST) -> Iterator[ast.AST]:
    """Every node lexically inside ``fn``'s own body, *not* descending
    into nested function definitions (a nested sync helper is its own
    execution context — blocking there is the call site's problem)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def dotted_name(node: ast.AST) -> str | None:
    """Render ``a.b.c`` attribute/name chains, else ``None``."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
