"""``jit-purity``: no host effects reachable inside jitted step code.

The ``[G×P]`` consensus step compiles to one XLA program. Anything
impure a traced function touches — wall clocks, Python RNG, env reads,
host callbacks — either silently bakes a trace-time constant into every
execution (``time.time()`` at trace time is *one* number forever) or
drags a host round-trip into the hot loop. The op-definition census
(PERF.md round 8, ``parallel/scaling.py``) checks the *compiled* program
for stray collectives at runtime; this rule is its static complement —
the impurity never lands on a branch CI didn't trace.

Mechanics: a pre-pass over the whole package collects jit *roots* —
function names appearing in ``jax.jit(f)``, ``jax.jit(partial(f, ...))``
or under a ``@jax.jit`` / ``@functools.partial(jax.jit, ...)``
decorator. Within each ``ops/`` module, the rule walks the local
name-level call graph from those roots and flags forbidden calls in any
reachable function. Name-level reachability is deliberately
over-approximate for helpers shared with host-side code — a helper that
must stay host-impure belongs outside ``ops/``.
"""

from __future__ import annotations

import ast

from .astutil import dotted_name
from .callgraph import callee_names, local_functions
from .findings import Finding

FORBIDDEN_CALLS = {
    "time.time": "wall clock freezes to a trace-time constant",
    "time.monotonic": "wall clock freezes to a trace-time constant",
    "time.perf_counter": "wall clock freezes to a trace-time constant",
    "time.sleep": "host sleep inside a traced function",
    "random.random": "Python RNG is trace-time-frozen; use jax.random",
    "random.randint": "Python RNG is trace-time-frozen; use jax.random",
    "random.choice": "Python RNG is trace-time-frozen; use jax.random",
    "os.getenv": "env read freezes to a trace-time constant",
    "os.environ.get": "env read freezes to a trace-time constant",
    "jax.debug.callback": "host callback in the step's hot loop",
    "jax.pure_callback": "host callback in the step's hot loop",
    "jax.experimental.io_callback": "host callback in the step's hot loop",
    "io_callback": "host callback in the step's hot loop",
    "np.random.seed": "host RNG state mutation at trace time",
}

FORBIDDEN_PREFIXES = {
    "np.random.": "host-side numpy RNG is trace-time-frozen; use jax.random",
    "numpy.random.": "host-side numpy RNG is trace-time-frozen; use "
                     "jax.random",
}

FORBIDDEN_SUBSCRIPTS = {
    "os.environ": "env read freezes to a trace-time constant",
}


def collect_jit_roots(trees: dict[str, ast.Module]) -> set[str]:
    """Function names jitted anywhere in the scanned tree."""
    roots: set[str] = set()

    def jitted_arg(call: ast.Call) -> None:
        for arg in call.args:
            if isinstance(arg, ast.Name):
                roots.add(arg.id)
            elif isinstance(arg, ast.Attribute):
                roots.add(arg.attr)
            elif isinstance(arg, ast.Call):
                # jax.jit(partial(step, ...)) / jax.jit(functools.partial(...))
                inner = dotted_name(arg.func) or ""
                if inner.rsplit(".", 1)[-1] == "partial" and arg.args:
                    first = arg.args[0]
                    if isinstance(first, ast.Name):
                        roots.add(first.id)
                    elif isinstance(first, ast.Attribute):
                        roots.add(first.attr)

    for tree in trees.values():
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func) or ""
                if name.rsplit(".", 1)[-1] == "jit":
                    jitted_arg(node)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    deco_name = dotted_name(
                        deco.func if isinstance(deco, ast.Call) else deco) or ""
                    tail = deco_name.rsplit(".", 1)[-1]
                    if tail == "jit":
                        roots.add(node.name)
                    elif (tail == "partial" and isinstance(deco, ast.Call)
                          and deco.args):
                        inner = dotted_name(deco.args[0]) or ""
                        if inner.rsplit(".", 1)[-1] == "jit":
                            roots.add(node.name)
    return roots


# The name-level resolution machinery this rule pioneered now lives in
# callgraph.py (local_functions / callee_names), where the package-wide
# async call graph builds on the same over-approximation.


def check_jit_purity(tree: ast.Module, path: str,
                     jit_roots: set[str]) -> list[Finding]:
    if "/ops/" not in f"/{path}":
        return []
    local = local_functions(tree)
    reachable: set[str] = set()
    frontier = [name for name in local if name in jit_roots]
    while frontier:
        name = frontier.pop()
        if name in reachable:
            continue
        reachable.add(name)
        frontier.extend(c for c in callee_names(local[name])
                        if c in local and c not in reachable)
    findings: list[Finding] = []
    for name in sorted(reachable):
        fn = local[name]
        for node in ast.walk(fn):
            why = None
            culprit = None
            if isinstance(node, ast.Call):
                called = dotted_name(node.func) or ""
                if called in FORBIDDEN_CALLS:
                    why, culprit = FORBIDDEN_CALLS[called], called
                else:
                    for prefix, reason in FORBIDDEN_PREFIXES.items():
                        if called.startswith(prefix):
                            why, culprit = reason, called
            elif isinstance(node, ast.Subscript):
                sub = dotted_name(node.value) or ""
                if sub in FORBIDDEN_SUBSCRIPTS:
                    why, culprit = FORBIDDEN_SUBSCRIPTS[sub], sub
            if why:
                findings.append(Finding(
                    rule="jit-purity", path=path, line=node.lineno,
                    message=(f"`{culprit}` reachable from jitted step "
                             f"function `{name}` — {why}"),
                    symbol=name))
    return findings
