"""``wire-schema``: protocol/messages.py frozen against a golden file.

The wire format is *positional*: ``Message.write_object`` emits fields
in ``_fields`` order under a ``@serialize_with(id)`` type id, and the C
codec walks the same order. Reordering a tuple, renaming a field, or
recycling an id is an on-the-wire corruption that every transport and
the native codec will happily ship — the PR 6 torn-write findings showed
what silently-misparsed frames cost. This rule makes any schema drift a
CI failure instead:

- type ids must be unique across the module;
- every concrete ``Message`` subclass must carry a type id;
- the extracted schema ``{id: [class, [fields...]]}`` must equal the
  committed golden snapshot ``tests/golden/wire_schema.json``.

An *intentional* schema change regenerates the golden in the same PR::

    copycat-tpu lint --update-golden

which rewrites the snapshot from the current source; the diff then
shows the schema change explicitly to reviewers.
"""

from __future__ import annotations

import ast
import json

from .astutil import const_str
from .findings import Finding

GOLDEN_PATH = "tests/golden/wire_schema.json"
REGEN_HINT = ("if the schema change is intentional, regenerate with "
              "`copycat-tpu lint --update-golden` and commit the diff")


def extract_schema(tree: ast.Module) -> tuple[dict[int, tuple[str, list[str]]],
                                              list[Finding]]:
    """``{type_id: (class_name, fields)}`` plus structural findings
    (duplicate ids, concrete messages without an id)."""
    schema: dict[int, tuple[str, list[str]]] = {}
    problems: list[tuple[int, str]] = []
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        type_id = None
        for deco in node.decorator_list:
            if (isinstance(deco, ast.Call)
                    and isinstance(deco.func, ast.Name)
                    and deco.func.id == "serialize_with" and deco.args
                    and isinstance(deco.args[0], ast.Constant)
                    and isinstance(deco.args[0].value, int)):
                type_id = deco.args[0].value
        fields: list[str] | None = None
        for stmt in node.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "_fields"):
                value = stmt.value
                if isinstance(value, (ast.Tuple, ast.List)):
                    fields = [const_str(e) or "?" for e in value.elts]
        if type_id is None:
            if fields is not None and node.name not in (
                    "Message", "Response"):
                problems.append((
                    node.lineno,
                    f"`{node.name}` declares `_fields` but no "
                    f"`@serialize_with(id)` — it cannot cross the wire"))
            continue
        if type_id in schema:
            problems.append((
                node.lineno,
                f"type id {type_id} reused by `{node.name}` (already "
                f"`{schema[type_id][0]}`) — ids are forever"))
            continue
        schema[type_id] = (node.name, fields or [])
    findings = [Finding(rule="wire-schema", path="", line=line,
                        message=message, symbol="<module>")
                for line, message in problems]
    return schema, findings


def check_wire_schema(tree: ast.Module, path: str,
                      golden: dict | None) -> list[Finding]:
    if not path.endswith("protocol/messages.py"):
        return []
    schema, findings = extract_schema(tree)
    for f in findings:
        f.path = path
    if golden is None:
        findings.append(Finding(
            rule="wire-schema", path=path, line=1,
            message=(f"golden snapshot {GOLDEN_PATH} is missing — "
                     f"{REGEN_HINT}"),
            symbol="<module>"))
        return findings
    current = {str(i): [cls, fields] for i, (cls, fields) in schema.items()}
    for type_id in sorted(set(golden) | set(current), key=int):
        got, want = current.get(type_id), golden.get(type_id)
        if got == want:
            continue
        if want is None:
            msg = (f"type id {type_id} (`{got[0]}`) is new and not in the "
                   f"golden snapshot — {REGEN_HINT}")
        elif got is None:
            msg = (f"type id {type_id} (`{want[0]}`) disappeared from "
                   f"messages.py but is in the golden snapshot — removing "
                   f"a wire message breaks rolling upgrades; {REGEN_HINT}")
        else:
            msg = (f"type id {type_id} drifted from the golden snapshot: "
                   f"golden `{want[0]}{want[1]}` vs source "
                   f"`{got[0]}{got[1]}` — a reorder/rename corrupts the "
                   f"positional wire format; {REGEN_HINT}")
        findings.append(Finding(rule="wire-schema", path=path, line=1,
                                message=msg, symbol="<module>"))
    return findings


def render_golden(tree: ast.Module) -> str:
    schema, _ = extract_schema(tree)
    payload = {str(i): [cls, fields]
               for i, (cls, fields) in sorted(schema.items())}
    return json.dumps(payload, indent=2, sort_keys=False) + "\n"
