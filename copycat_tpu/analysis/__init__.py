"""copycheck — project-native static analysis (docs/ANALYSIS.md).

Seven AST-based rules, each grounded in a hazard this codebase has
actually hit (flight-recorder findings, the PR 6 torn-write post-mortem,
the ``utils/tasks.py`` weakref note):

- ``loop-blocking`` — event-loop-blocking calls inside ``async def``
  bodies (latency hazards for the repl/read pumps);
- ``orphan-task`` — raw ``create_task``/``ensure_future`` outside
  ``utils/tasks.spawn`` (the fire-and-forget weakref-GC hazard);
- ``await-tear`` — an ``await`` between a read and an unguarded write of
  protected Raft state in ``server/raft.py`` (the asyncio analogue of a
  race detector);
- ``knob-registry`` — every ``COPYCAT_*`` env read goes through
  ``utils/knobs.py``; every knob named is registered;
- ``metric-registry`` — every metric call site uses a name from the
  ``docs/OBSERVABILITY.md`` catalog;
- ``wire-schema`` — ``protocol/messages.py`` type ids unique and
  ``_fields`` orders frozen against ``tests/golden/wire_schema.json``;
- ``jit-purity`` — no ``time``/``random``/``os.environ``/host callbacks
  reachable inside the jitted ``ops/`` step functions.

Run with ``copycat-tpu lint`` (or ``python -m copycat_tpu.analysis``);
``--strict`` is the CI gate. Findings are suppressed inline with
``# copycheck: ignore[rule]`` or carried (with a justification) in
``.copycheck-baseline.json``. Pure stdlib + AST: linting never imports
jax or the modules it checks.
"""

from .engine import LintContext, run_lint  # noqa: F401
from .findings import Finding  # noqa: F401

ALL_RULES = (
    "loop-blocking",
    "orphan-task",
    "await-tear",
    "knob-registry",
    "metric-registry",
    "wire-schema",
    "jit-purity",
)
