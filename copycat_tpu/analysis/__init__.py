"""copycheck — project-native static analysis (docs/ANALYSIS.md).

Ten AST-based rules over a package-wide async call graph
(:mod:`callgraph`), each grounded in a hazard this codebase has
actually hit (flight-recorder findings, the PR 6 torn-write post-mortem,
the ``utils/tasks.py`` weakref note, the PR 12 exit-code contract):

- ``loop-blocking`` — event-loop-blocking calls inside ``async def``
  bodies AND inside sync helpers the call graph proves reachable from
  one (latency hazards for the repl/read pumps);
- ``orphan-task`` — raw ``create_task``/``ensure_future`` outside
  ``utils/tasks.spawn`` (the fire-and-forget weakref-GC hazard);
- ``await-tear`` — an unguarded protected-state write after a
  suspension point across the server+deploy plane, interprocedurally:
  awaits of never-suspending helpers don't count, ``async with``/
  ``async for`` and writes hidden in called helpers do (the asyncio
  analogue of a race detector);
- ``durability-order`` — inside ``RaftGroup``, no commit/command future
  resolve or success append ack unless dominated by the commit-boundary
  ``_sync_log`` (the "fsync before ack" guarantee, statically);
- ``span-pairing`` — span-record call sites use vocabulary names from
  ``docs/OBSERVABILITY.md``, never ``with`` over the completed-span
  API, never an unentered ``.timer(...)``;
- ``exit-code`` — deploy-plane mains exit only with the documented
  0/1/2 contract the supervisor's restart policy keys off;
- ``knob-registry`` — every ``COPYCAT_*`` env read goes through
  ``utils/knobs.py``; every knob named is registered;
- ``metric-registry`` — every metric call site uses a name from the
  ``docs/OBSERVABILITY.md`` catalog;
- ``wire-schema`` — ``protocol/messages.py`` type ids unique and
  ``_fields`` orders frozen against ``tests/golden/wire_schema.json``;
- ``jit-purity`` — no ``time``/``random``/``os.environ``/host callbacks
  reachable inside the jitted ``ops/`` step functions.

Run with ``copycat-tpu lint`` (or ``python -m copycat_tpu.analysis``);
``--strict`` is the CI gate, ``--format sarif`` the code-scanning
emitter, ``--changed BASE`` the diff mode. Findings are suppressed
inline with ``# copycheck: ignore[rule]`` or carried (with a
justification) in ``.copycheck-baseline.json``. Pure stdlib + AST:
linting never imports jax or the modules it checks.
"""

from .engine import LintContext, run_lint  # noqa: F401
from .findings import Finding  # noqa: F401

ALL_RULES = (
    "loop-blocking",
    "orphan-task",
    "await-tear",
    "durability-order",
    "span-pairing",
    "exit-code",
    "knob-registry",
    "metric-registry",
    "wire-schema",
    "jit-purity",
)
