"""``await-tear``: unguarded protected-state writes across suspension
points — interprocedural since copycheck v2.

The asyncio analogue of a race detector, scoped to the whole
server+deploy plane: the Raft transition cores (``server/raft.py``,
``server/raft_group.py`` — any basename mentioning ``raft``) plus the
compartmentalized tiers that now run the same ordering contracts in
their own processes (``deploy/ingress.py``, ``deploy/supervisor.py``).
Single-threaded asyncio removes data races but not *interleavings*:
every true yield point is a window where another coroutine can run a
whole election, append, or snapshot install. A method that (1) reads
protected state, (2) suspends, then (3) writes that state from the
stale read has torn the transition — the leadership-epoch bug class "On
the parallels between Paxos and Raft" catalogs as quorum-era confusion.

Protected state is keyed ``(base, field)`` exactly as the lexical-era
rule established (docs/SHARDING.md): ``term``, ``voted_for``,
``commit_index``, ``last_applied``, and the log tail, on ``self`` or on
any group alias (``grp = self.groups[k]``) — a guard only discharges a
write on the SAME base.

What the call graph adds (:mod:`callgraph`):

- **Suspension precision, both directions.** An ``await`` of a local
  coroutine the graph classifies never-suspends is NOT an interleaving
  point (no false tear); an ``async for``/``async with`` — a suspension
  the lexical rule was blind to, e.g. an async lock acquire hiding in a
  helper-built context manager — IS one (no false clean). Awaits of
  anything unresolvable stay conservatively suspending.
- **Helper effect summaries.** A call to a same-class sync helper
  inlines the helper's protected reads/writes/guards at the call line,
  mapped onto the call-site base — ``self._commit_term(t)`` after an
  await is a write to ``self.term`` even though no attribute store is
  lexically visible, and ``grp._helper()`` tracks under ``grp``.
  Summaries close transitively through sync same-class helpers (depth
  capped); helpers with their own suspension points contribute their
  effects too (the effects still happen — on the far side of THEIR
  awaits, which the call site's await already models conservatively).

The blessed pattern is unchanged — re-validate after the suspension::

    term = self.term
    responses = await gather(...)          # interleaving point
    if self.role != CANDIDATE or self.term != term:
        return                             # epoch guard re-reads state
    self.commit_index = ...                # now safe

The check stays lexical in ORDER (source order, not CFG paths),
deliberately: a guard that only covers one branch still re-reads the
state, and a method complex enough to defeat the lexical view belongs
in the baseline with a justification, not silently passed.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .astutil import iter_async_functions, qualname_map
from .callgraph import CallGraph, FunctionInfo, own_body
from .findings import Finding

PROTECTED_FIELDS = ("term", "voted_for", "commit_index", "last_applied")
LOG_WRITE_METHODS = ("append", "append_replicated_block", "truncate",
                     "truncate_prefix", "reset_to", "compact", "set_commit")
GUARD_FIELDS = PROTECTED_FIELDS + ("role", "log")

#: basenames beyond the raft cores in scope since the deploy plane runs
#: its own ordering contracts cross-process (docs/DEPLOYMENT.md)
DEPLOY_BASENAMES = ("ingress.py", "supervisor.py")

_SUMMARY_DEPTH = 3


def in_scope(path: str) -> bool:
    basename = path.rsplit("/", 1)[-1]
    return "raft" in basename or basename in DEPLOY_BASENAMES


def _base_attr(node: ast.AST) -> tuple[str, str] | None:
    """``<name>.X`` -> ``(name, X)`` for any simple-name base (``self``,
    a group alias like ``grp``/``g0``, ...)."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)):
        return node.value.id, node.attr
    return None


@dataclass
class Effects:
    """Protected-state touches of one function body on ``self``,
    line-erased (used as a summary inlined at call sites)."""

    reads: set[str] = field(default_factory=set)
    writes: set[str] = field(default_factory=set)
    guards: set[str] = field(default_factory=set)

    def merge(self, other: "Effects") -> None:
        self.reads |= other.reads
        self.writes |= other.writes
        self.guards |= other.guards

    def __bool__(self) -> bool:
        return bool(self.reads or self.writes or self.guards)


def _direct_effects(fn: ast.AST) -> Effects:
    """One function's own protected touches on ``self`` (no nested
    defs, no transitive calls)."""
    eff = Effects()
    for node in own_body(fn):
        if isinstance(node, ast.Attribute):
            rec = _base_attr(node)
            if rec is not None and rec[0] == "self" \
                    and rec[1] in PROTECTED_FIELDS:
                if isinstance(node.ctx, (ast.Store, ast.Del)):
                    eff.writes.add(rec[1])
                else:
                    eff.reads.add(rec[1])
            else:
                inner = _base_attr(node.value) \
                    if isinstance(node, ast.Attribute) else None
                if inner == ("self", "log") and isinstance(node.ctx,
                                                           ast.Load):
                    eff.reads.add("log")
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute):
            inner = _base_attr(node.func.value)
            if inner == ("self", "log"):
                if node.func.attr in LOG_WRITE_METHODS:
                    eff.writes.add("log")
                else:
                    eff.reads.add("log")
        elif isinstance(node, (ast.If, ast.While, ast.Assert)):
            for sub in ast.walk(node.test):
                rec = _base_attr(sub)
                if rec is not None and rec[0] == "self" \
                        and rec[1] in PROTECTED_FIELDS + ("role",):
                    eff.guards.add(rec[1])
    return eff


class _SummaryTable:
    """Transitive per-function effect summaries over same-class sync
    calls (depth-capped, cycle-safe)."""

    def __init__(self, graph: CallGraph | None) -> None:
        self.graph = graph
        self._cache: dict[tuple[str, str], Effects] = {}

    def effects(self, info: FunctionInfo, depth: int = 0,
                seen: frozenset = frozenset()) -> Effects:
        if info.key in self._cache:
            return self._cache[info.key]
        eff = _direct_effects(info.node)
        if self.graph is not None and depth < _SUMMARY_DEPTH:
            for node in own_body(info.node):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"):
                    continue
                callee = self.graph.resolve_call(info.path, info, node)
                if callee is None or callee.key in seen \
                        or callee.key == info.key:
                    continue
                eff.merge(self.effects(callee, depth + 1,
                                       seen | {info.key}))
        if depth == 0:
            # only COMPLETE summaries are memoized: a summary computed
            # mid-traversal was truncated by the depth cap / cycle set
            # of its caller's frame, and caching it would hide deeper
            # effects from later top-level queries
            self._cache[info.key] = eff
        return eff


class _Events(ast.NodeVisitor):
    """Collect (line-ordered) reads, writes, suspensions and guard tests
    for one async function body, without descending into nested defs.
    Events are keyed ``(base, field)`` so group-state aliases track
    independently of ``self`` and of each other."""

    def __init__(self, path: str, info: FunctionInfo | None,
                 graph: CallGraph | None,
                 summaries: _SummaryTable) -> None:
        self.path = path
        self.info = info
        self.graph = graph
        self.summaries = summaries
        self.reads: list[tuple[int, tuple[str, str]]] = []
        #: writes carry the via label of the helper that hid them (or None)
        self.writes: list[tuple[int, tuple[str, str], str | None]] = []
        self.suspensions: list[int] = []
        self.guards: list[tuple[int, tuple[str, str]]] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass  # nested sync def: its own context

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        pass  # nested coroutine: analyzed on its own

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass

    def _call_suspends(self, call: ast.Call) -> bool:
        if self.graph is None:
            return True  # no graph: every await is an interleaving point
        return self.graph.suspends(self.path, self.info, call)

    def visit_Await(self, node: ast.Await) -> None:
        if not isinstance(node.value, ast.Call) \
                or self._call_suspends(node.value):
            self.suspensions.append(node.lineno)
        self.generic_visit(node)

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        # an async context manager acquires on entry — a suspension the
        # lexical rule could not see (no Await node)
        self.suspensions.append(node.lineno)
        self.generic_visit(node)

    def visit_AsyncFor(self, node: ast.AsyncFor) -> None:
        self.suspensions.append(node.lineno)
        self.generic_visit(node)

    def _note_test(self, test: ast.AST) -> None:
        for sub in ast.walk(test):
            rec = _base_attr(sub)
            if rec is not None and rec[1] in PROTECTED_FIELDS + ("role",):
                self.guards.append((test.lineno, rec))
            elif isinstance(sub, ast.Attribute):
                inner = _base_attr(sub.value)
                if inner is not None and inner[1] == "log":
                    self.guards.append((test.lineno, (inner[0], "log")))

    def visit_If(self, node: ast.If) -> None:
        self._note_test(node.test)
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._note_test(node.test)
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._note_test(node.test)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        rec = _base_attr(node)
        if rec is not None and rec[1] in PROTECTED_FIELDS:
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                self.writes.append((node.lineno, rec, None))
            else:
                self.reads.append((node.lineno, rec))
        else:
            inner = _base_attr(node.value)
            if inner is not None and inner[1] == "log" \
                    and isinstance(node.ctx, ast.Load):
                # <base>.log.last_index / .term_at — a log-tail read
                # (write methods are classified in visit_Call; an extra
                # read note on the same line is harmless)
                self.reads.append((node.lineno, (inner[0], "log")))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        # <base>.log.append(...) and friends: log-tail writes; any other
        # <base>.log.X(...) counts as a log read (term_at, last_index...).
        func = node.func
        if isinstance(func, ast.Attribute):
            inner = _base_attr(func.value)
            if inner is not None and inner[1] == "log":
                key = (inner[0], "log")
                if func.attr in LOG_WRITE_METHODS:
                    self.writes.append((node.lineno, key, None))
                else:
                    self.reads.append((node.lineno, key))
            else:
                self._inline_summary(node, func)
        self.generic_visit(node)

    def _inline_summary(self, node: ast.Call, func: ast.Attribute) -> None:
        """``<base>.helper(...)``: inline the helper's protected effect
        summary at the call line, keyed on the call-site base — the
        write ``self._commit_term()`` hides is a write HERE."""
        if self.graph is None or self.info is None:
            return
        rec = _base_attr(func)
        if rec is None:
            return
        base = rec[0]
        # resolve through the method table of the CALLER's class: group
        # aliases (`grp._helper()`) carry RaftGroup methods in the same
        # file, `self._helper()` the enclosing class's — both resolve
        # name-level within the file, which is the honest boundary
        callee = self.graph.resolve_call(
            self.path, self.info, node) if base == "self" else \
            self._resolve_alias_method(func.attr)
        if callee is None:
            return
        eff = self.summaries.effects(callee)
        if not eff:
            return
        via = callee.label
        for f in sorted(eff.reads):
            self.reads.append((node.lineno, (base, f)))
        for f in sorted(eff.writes):
            self.writes.append((node.lineno, (base, f), via))
        for f in sorted(eff.guards):
            self.guards.append((node.lineno, (base, f)))

    def _resolve_alias_method(self, attr: str) -> FunctionInfo | None:
        """A method called through a non-self base (`grp._helper()`):
        resolve by name against ANY class in the same file — the alias
        model the (base,field) tracking already commits to."""
        for info in self.graph.functions.values():
            if info.path == self.path and info.name == attr \
                    and info.class_name is not None:
                return info
        return None


def check_await_tear(tree: ast.Module, path: str,
                     graph: CallGraph | None = None) -> list[Finding]:
    if not in_scope(path):
        return []
    findings: list[Finding] = []
    summaries = _SummaryTable(graph)
    quals = qualname_map(tree)
    for fn, qual in iter_async_functions(tree):
        info = graph.info_for(path, quals.get(fn, fn.name)) \
            if graph is not None else None
        events = _Events(path, info, graph, summaries)
        for stmt in fn.body:
            events.visit(stmt)
        if not events.suspensions:
            continue
        for wline, (base, fld), via in events.writes:
            suspensions_before = [a for a in events.suspensions if a < wline]
            if not suspensions_before:
                continue
            last_suspension = max(suspensions_before)
            stale_read = any(r < last_suspension and key == (base, fld)
                             for r, key in events.reads)
            if not stale_read:
                continue
            guarded = any(last_suspension < g <= wline
                          and gb == base and gf in (fld, "role")
                          for g, (gb, gf) in events.guards)
            if guarded:
                continue
            hidden = f" (write hidden in `{via}`)" if via else ""
            findings.append(Finding(
                rule="await-tear", path=path, line=wline,
                message=(f"write to protected `{base}.{fld}` after a "
                         f"suspension point with no re-validation of "
                         f"`{fld}`/`role` on `{base}` between the "
                         f"interleaving point and the write{hidden} — "
                         f"re-check the epoch before committing the "
                         f"transition"),
                symbol=qual, via=[via] if via else None))
    return findings
