"""``await-tear``: unguarded protected-state writes after an ``await``.

The asyncio analogue of a race detector, specialized to the Raft
server's transition methods (``server/raft.py`` + the multi-group
``server/raft_group.py`` it was refactored into). Single-threaded
asyncio removes data races but not *interleavings*: every ``await`` is a
point where another coroutine can run a whole election, append, or
snapshot install. A method that (1) reads protected Raft state, (2)
awaits, then (3) writes that state based on the stale read has torn the
transition — exactly the bug class "On the parallels between Paxos and
Raft" catalogs as quorum-era confusion, and the one the flight recorder
only catches after the fact, on device.

Protected state lives on the GROUP-STATE object since the multi-raft
refactor (docs/SHARDING.md): ``term``, ``voted_for``, ``commit_index``,
``last_applied``, and the log tail (writes via
``<state>.log.append/append_replicated_block/truncate/truncate_prefix/
reset_to/compact``, reads via any other ``<state>.log.*`` use). The
rule is base-aware rather than hard-coded to ``self``: inside
``RaftGroup`` methods the base is ``self``; server-level code reaching
through a group alias (``grp = self.groups[k]; ... grp.term = x``) is
tracked under that alias, and a read/guard only discharges a write on
the SAME base — re-validating ``other.term`` does not bless a write to
``grp.term``.

The blessed pattern re-validates after the await — the epoch guard the
election path already uses::

    term = self.term
    responses = await gather(...)          # interleaving point
    if self.role != CANDIDATE or self.term != term:
        return                             # epoch guard re-reads state
    self.commit_index = ...                # now safe

Concretely: a write to a protected field is flagged when (a) at least
one ``await`` precedes it in the method, (b) the same field was read
*on the same base* before that await (the decision input), and (c) no
``if``/``while``/``assert`` test between the last preceding await and
the write re-reads that field or ``role`` on that base. The rule is
lexical (source order, not CFG paths) — deliberately so: a guard that
only covers one branch still re-reads the state, and a method complex
enough to defeat the lexical view belongs in the baseline with a
justification, not silently passed.
"""

from __future__ import annotations

import ast

from .astutil import iter_async_functions
from .findings import Finding

PROTECTED_FIELDS = ("term", "voted_for", "commit_index", "last_applied")
LOG_WRITE_METHODS = ("append", "append_replicated_block", "truncate",
                     "truncate_prefix", "reset_to", "compact", "set_commit")
GUARD_FIELDS = PROTECTED_FIELDS + ("role", "log")


def _base_attr(node: ast.AST) -> tuple[str, str] | None:
    """``<name>.X`` -> ``(name, X)`` for any simple-name base (``self``,
    a group alias like ``grp``/``g0``, ...)."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)):
        return node.value.id, node.attr
    return None


class _Events(ast.NodeVisitor):
    """Collect (line-ordered) reads, writes, awaits and guard tests for
    one async function body, without descending into nested defs.
    Events are keyed ``(base, field)`` so group-state aliases track
    independently of ``self`` and of each other."""

    def __init__(self) -> None:
        self.reads: list[tuple[int, tuple[str, str]]] = []
        self.writes: list[tuple[int, tuple[str, str]]] = []
        self.awaits: list[int] = []
        self.guards: list[tuple[int, tuple[str, str]]] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass  # nested sync def: its own context

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        pass  # nested coroutine: analyzed on its own

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass

    def visit_Await(self, node: ast.Await) -> None:
        self.awaits.append(node.lineno)
        self.generic_visit(node)

    def _note_test(self, test: ast.AST) -> None:
        for sub in ast.walk(test):
            rec = _base_attr(sub)
            if rec is not None and rec[1] in PROTECTED_FIELDS + ("role",):
                self.guards.append((test.lineno, rec))
            elif isinstance(sub, ast.Attribute):
                inner = _base_attr(sub.value)
                if inner is not None and inner[1] == "log":
                    self.guards.append((test.lineno, (inner[0], "log")))

    def visit_If(self, node: ast.If) -> None:
        self._note_test(node.test)
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._note_test(node.test)
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._note_test(node.test)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        rec = _base_attr(node)
        if rec is not None and rec[1] in PROTECTED_FIELDS:
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                self.writes.append((node.lineno, rec))
            else:
                self.reads.append((node.lineno, rec))
        else:
            inner = _base_attr(node.value)
            if inner is not None and inner[1] == "log" \
                    and isinstance(node.ctx, ast.Load):
                # <base>.log.last_index / .term_at — a log-tail read
                # (write methods are classified in visit_Call; an extra
                # read note on the same line is harmless)
                self.reads.append((node.lineno, (inner[0], "log")))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        # <base>.log.append(...) and friends: log-tail writes; any other
        # <base>.log.X(...) counts as a log read (term_at, last_index...).
        func = node.func
        if isinstance(func, ast.Attribute):
            inner = _base_attr(func.value)
            if inner is not None and inner[1] == "log":
                key = (inner[0], "log")
                if func.attr in LOG_WRITE_METHODS:
                    self.writes.append((node.lineno, key))
                else:
                    self.reads.append((node.lineno, key))
        self.generic_visit(node)


def check_await_tear(tree: ast.Module, path: str) -> list[Finding]:
    # Specialized to the Raft server plane: server/raft.py AND the
    # per-group core server/raft_group.py (fixture tests hand in any
    # path whose basename mentions raft).
    if "raft" not in path.rsplit("/", 1)[-1]:
        return []
    findings: list[Finding] = []
    for fn, qual in iter_async_functions(tree):
        events = _Events()
        for stmt in fn.body:
            events.visit(stmt)
        if not events.awaits:
            continue
        for wline, (base, field) in events.writes:
            awaits_before = [a for a in events.awaits if a < wline]
            if not awaits_before:
                continue
            last_await = max(awaits_before)
            stale_read = any(r < last_await and key == (base, field)
                             for r, key in events.reads)
            if not stale_read:
                continue
            guarded = any(last_await < g <= wline
                          and gb == base and gf in (field, "role")
                          for g, (gb, gf) in events.guards)
            if guarded:
                continue
            findings.append(Finding(
                rule="await-tear", path=path, line=wline,
                message=(f"write to protected `{base}.{field}` after an "
                         f"await with no re-validation of `{field}`/"
                         f"`role` on `{base}` between the interleaving "
                         f"point and the write — re-check the epoch "
                         f"before committing the transition"),
                symbol=qual))
    return findings
