"""Registry rules: ``knob-registry`` and ``metric-registry``.

Both rules close the same loop: a name used in code must exist in its
documented registry, so the docs can be *asserted* in sync instead of
hand-maintained.

``knob-registry``:
- any direct ``os.environ.get("COPYCAT_X")`` / ``os.getenv`` /
  ``os.environ["COPYCAT_X"]`` *read* outside ``utils/knobs.py`` is
  flagged — typed access goes through the registry (env *writes* are
  fine: benches stage knobs for servers they build);
- any ``knobs.get_*("COPYCAT_X")`` naming an unregistered knob is
  flagged. The registered set is parsed from ``utils/knobs.py``'s AST
  (the ``_knob("NAME", ...)`` declarations) — linting never imports the
  package.

``metric-registry``: every ``.counter(name) / .gauge(name) /
.histogram(name) / .timer(name)`` call site whose name is a string
literal must use a name from the machine-readable catalog at the bottom
of ``docs/OBSERVABILITY.md``; label kwargs must match the catalog
entry's declared label keys (``query_reads{consistency}``). Dynamic
(non-literal) names can't be checked — they're flagged too, so each one
is either rewritten to a literal or carries an inline suppression
explaining where its names come from.
"""

from __future__ import annotations

import ast
import re

from .astutil import const_str, dotted_name, enclosing_symbol
from .findings import Finding

KNOB_PREFIX = "COPYCAT_"
KNOB_GETTERS = ("get_raw", "get_str", "get_int", "get_float", "get_bool")
METRIC_METHODS = ("counter", "gauge", "histogram", "timer")

# Catalog entries line-match `name` or `name{label,label2}` cells in the
# OBSERVABILITY.md machine catalog table.
CATALOG_ENTRY_RE = re.compile(r"^\|\s*`([A-Za-z0-9_.]+)(\{([A-Za-z0-9_,]+)\})?`\s*\|")
CATALOG_HEADING = "## Metric name catalog"


def parse_knob_registry(knobs_source: str) -> set[str]:
    """Registered knob names from ``utils/knobs.py``'s AST."""
    tree = ast.parse(knobs_source)
    names: set[str] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "_knob" and node.args):
            name = const_str(node.args[0])
            if name:
                names.add(name)
    return names


def parse_metric_catalog(observability_md: str) -> dict[str, set[str]] | None:
    """``{metric name: {label keys}}`` from the OBSERVABILITY.md machine
    catalog section, or ``None`` when the section is missing."""
    idx = observability_md.find(CATALOG_HEADING)
    if idx < 0:
        return None
    catalog: dict[str, set[str]] = {}
    for line in observability_md[idx:].splitlines():
        m = CATALOG_ENTRY_RE.match(line.strip())
        if m:
            labels = set((m.group(3) or "").split(",")) - {""}
            catalog[m.group(1)] = labels
    return catalog


def check_knob_registry(tree: ast.Module, path: str,
                        registered: set[str]) -> list[Finding]:
    if path.endswith("utils/knobs.py"):
        return []
    findings: list[Finding] = []

    def flag(line: int, message: str) -> None:
        findings.append(Finding(
            rule="knob-registry", path=path, line=line, message=message,
            symbol=enclosing_symbol(tree, line)))

    for node in ast.walk(tree):
        # os.environ["COPYCAT_X"] reads (subscript loads)
        if (isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)
                and dotted_name(node.value) in ("os.environ", "environ")):
            name = const_str(node.slice)
            if name and name.startswith(KNOB_PREFIX):
                flag(node.lineno,
                     f"direct env read of `{name}` — go through "
                     f"`utils/knobs.py` (`knobs.get_*`)")
        if not isinstance(node, ast.Call):
            continue
        func_name = dotted_name(node.func) or ""
        # os.environ.get("COPYCAT_X", ...) / os.getenv("COPYCAT_X", ...)
        if func_name.endswith("environ.get") or func_name in (
                "os.getenv", "getenv"):
            name = const_str(node.args[0]) if node.args else None
            if name and name.startswith(KNOB_PREFIX):
                flag(node.lineno,
                     f"direct env read of `{name}` — go through "
                     f"`utils/knobs.py` (`knobs.get_*`)")
        # knobs.get_*("COPYCAT_X"): name must be registered
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in KNOB_GETTERS and node.args):
            name = const_str(node.args[0])
            if (name and name.startswith(KNOB_PREFIX)
                    and name not in registered):
                flag(node.lineno,
                     f"`{name}` is not registered in `utils/knobs.py` — "
                     f"declare it (typed default + one-line doc) so the "
                     f"README table stays generated")
    return findings


def check_metric_registry(tree: ast.Module, path: str,
                          catalog: dict[str, set[str]]) -> list[Finding]:
    if path.endswith("utils/metrics.py"):
        return []  # the substrate itself (merge/snapshot plumbing)
    findings: list[Finding] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in METRIC_METHODS
                and (node.args or node.keywords)):
            continue
        if not node.args:
            continue
        symbol = enclosing_symbol(tree, node.lineno)
        first = node.args[0]
        # `"a" if cond else "b"` picks between two literal names — check
        # both branches instead of flagging the site as dynamic.
        if (isinstance(first, ast.IfExp)
                and const_str(first.body) is not None
                and const_str(first.orelse) is not None):
            candidates = [const_str(first.body), const_str(first.orelse)]
        else:
            candidates = [const_str(first)]
        name = candidates[0]
        if name is None:
            # a non-string constant first arg (e.g. `.timer(3)` on some
            # unrelated object) is not a metric call we can judge
            if not isinstance(node.args[0], ast.Constant):
                findings.append(Finding(
                    rule="metric-registry", path=path, line=node.lineno,
                    message=(f"dynamic metric name passed to "
                             f"`.{node.func.attr}(...)` — use a literal "
                             f"from the docs/OBSERVABILITY.md catalog, or "
                             f"suppress with the source of the names"),
                    symbol=symbol))
            continue
        labels = {kw.arg for kw in node.keywords if kw.arg is not None}
        for name in candidates:
            entry = catalog.get(name)
            if entry is None:
                findings.append(Finding(
                    rule="metric-registry", path=path, line=node.lineno,
                    message=(f"metric `{name}` is not in the "
                             f"docs/OBSERVABILITY.md catalog — document it "
                             f"(name, kind, meaning) before recording it"),
                    symbol=symbol))
                continue
            if labels != entry:
                want = ("{" + ",".join(sorted(entry)) + "}" if entry
                        else "none")
                got = ("{" + ",".join(sorted(labels)) + "}" if labels
                       else "none")
                findings.append(Finding(
                    rule="metric-registry", path=path, line=node.lineno,
                    message=(f"metric `{name}` labels {got} do not match "
                             f"the catalog's {want}"),
                    symbol=symbol))
    return findings
