"""Package-wide async call graph + suspension classification.

The v2 substrate under the concurrency rules (docs/ANALYSIS.md): one
graph over every function in the scanned tree, built from the same
name-level resolution machinery the jit-purity walker proved out —
deliberately over-approximate where Python's dynamism hides the callee,
and honest about the boundary:

- a bare ``helper(...)`` resolves to a module-level ``def helper`` in
  the SAME file;
- ``self.helper(...)`` resolves to a method of the enclosing class in
  the same file;
- ``mod.helper(...)`` resolves to module-level ``helper`` in the file a
  ``import ... as mod`` / ``from pkg import mod`` binding names;
- anything else (cross-object attributes, callables in variables,
  dynamic dispatch) does NOT resolve, and every consumer treats an
  unresolved callee conservatively for its own rule (a suspension for
  await-tear, an analysis frontier for loop-blocking reachability).

On top of the graph, two classifications every concurrency rule
consumes:

- **may-suspend**: an ``async def`` may suspend iff its own body holds
  a true yield point — ``async for``/``async with``, ``yield``, an
  ``await`` of anything unresolvable, or an ``await`` of a local
  coroutine that itself may suspend (computed to a fixed point). An
  async def whose every await lands on a never-suspending local helper
  CANNOT interleave — the await-tear rule uses that for precision, both
  ways.
- **async-reachable**: the set of SYNC functions reachable from any
  ``async def`` body through resolved sync calls, each with one example
  call chain. A blocking call inside such a helper stalls the event
  loop exactly like one written inline — the interprocedural
  loop-blocking rule's frontier.

Pure stdlib + ``ast``; the graph never imports the modules it models.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .astutil import qualname_map


def local_functions(tree: ast.Module) -> dict[str, ast.AST]:
    """Module-level function defs by name (the jit walker's view)."""
    return {node.name: node for node in tree.body
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))}


def callee_names(fn: ast.AST) -> set[str]:
    """Names a function's body could call (name-level, jit-purity's
    over-approximation: plain names count too, for functions passed as
    values like ``lax.scan(body, ...)``)."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Name):
                out.add(node.func.id)
            elif isinstance(node.func, ast.Attribute):
                out.add(node.func.attr)
        elif isinstance(node, ast.Name):
            out.add(node.id)
    return out


#: awaits of these dotted tails are ALWAYS suspension points even if a
#: same-named local exists (asyncio primitives shadowed locally would be
#: perverse, but the conservative direction costs nothing)
_ALWAYS_SUSPENDS = ("sleep", "gather", "wait", "wait_for", "shield")


@dataclass
class FunctionInfo:
    path: str               # repo-relative file
    qual: str               # dotted qualname within the file
    name: str               # bare name
    class_name: str | None  # enclosing class (innermost), if a method
    is_async: bool
    node: ast.AST = field(repr=False, default=None)

    @property
    def key(self) -> tuple[str, str]:
        return (self.path, self.qual)

    @property
    def label(self) -> str:
        return f"{self.path}::{self.qual}"


def _module_imports(tree: ast.Module) -> dict[str, str]:
    """``alias -> module basename`` for every import binding in a file
    (``import a.b.c as m`` -> m: c; ``from pkg import mod`` -> mod: mod)."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                base = a.name.rsplit(".", 1)[-1]
                out[a.asname or a.name.split(".", 1)[0]] = (
                    base if a.asname else a.name.split(".", 1)[0])
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                out[a.asname or a.name] = a.name
    return out


def own_body(fn: ast.AST):
    """Every node lexically inside ``fn``, not descending into nested
    defs/lambdas (a nested function is its own execution context)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def awaited_call_nodes(fn: ast.AST) -> set[int]:
    """ids of every Call node lexically under an Await expression in
    ``fn``'s own body — ``await x.wait()`` and
    ``await wait_for(p.wait(), t)`` both cover the inner call, so
    blocking-method heuristics keyed on ambiguous names (``wait``) can
    skip coroutine plumbing."""
    out: set[int] = set()
    for node in own_body(fn):
        if isinstance(node, ast.Await):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    out.add(id(sub))
    return out


class CallGraph:
    """The package-wide graph; build once per lint run over all trees."""

    def __init__(self) -> None:
        self.functions: dict[tuple[str, str], FunctionInfo] = {}
        #: (path, class_name, method name) -> key
        self._methods: dict[tuple[str, str | None, str],
                            tuple[str, str]] = {}
        #: (path, bare name) -> key for module-level defs
        self._module_level: dict[tuple[str, str], tuple[str, str]] = {}
        #: path -> {alias: module basename}
        self._imports: dict[str, dict[str, str]] = {}
        #: module basename -> [paths defining it]
        self._basename_paths: dict[str, list[str]] = {}
        #: attr names called on a NON-self receiver anywhere in the tree
        #: (the durability rule treats such methods as externally
        #: entered — their call sites can't be proven dominated)
        self.external_attr_calls: set[str] = set()
        self.may_suspend: dict[tuple[str, str], bool] = {}
        #: sync fn key -> example chain of labels from an async def
        self.async_reachable: dict[tuple[str, str], list[str]] = {}

    # -- construction ------------------------------------------------------

    @classmethod
    def build(cls, trees: dict[str, ast.Module]) -> "CallGraph":
        g = cls()
        for path, tree in trees.items():
            g._imports[path] = _module_imports(tree)
            base = path.rsplit("/", 1)[-1].removesuffix(".py")
            g._basename_paths.setdefault(base, []).append(path)
            quals = qualname_map(tree)
            classes: dict[ast.AST, str] = {
                n: q for n, q in quals.items() if isinstance(n, ast.ClassDef)}
            for node, qual in quals.items():
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                parts = qual.split(".")
                class_name = None
                if len(parts) > 1:
                    # innermost enclosing class, when the parent qual
                    # names one (methods of nested classes resolve to
                    # the nearest class)
                    parent_qual = ".".join(parts[:-1])
                    for cnode, cqual in classes.items():
                        if cqual == parent_qual:
                            class_name = cnode.name
                            break
                info = FunctionInfo(path=path, qual=qual, name=node.name,
                                    class_name=class_name,
                                    is_async=isinstance(
                                        node, ast.AsyncFunctionDef),
                                    node=node)
                g.functions[info.key] = info
                g._methods.setdefault(
                    (path, class_name, node.name), info.key)
                if len(parts) == 1:
                    g._module_level[(path, node.name)] = info.key
            for node in ast.walk(tree):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and not (isinstance(node.func.value, ast.Name)
                                 and node.func.value.id == "self")):
                    g.external_attr_calls.add(node.func.attr)
        g._classify_suspension()
        g._classify_async_reachability()
        return g

    # -- resolution --------------------------------------------------------

    def resolve_call(self, path: str, caller: FunctionInfo | None,
                     call: ast.Call) -> FunctionInfo | None:
        """Resolve one call site to a FunctionInfo, or ``None`` when the
        callee hides from name-level analysis."""
        func = call.func
        if isinstance(func, ast.Name):
            key = self._module_level.get((path, func.id))
            return self.functions.get(key) if key else None
        if isinstance(func, ast.Attribute) and isinstance(func.value,
                                                          ast.Name):
            recv = func.value.id
            if recv == "self" and caller is not None \
                    and caller.class_name is not None:
                key = self._methods.get(
                    (path, caller.class_name, func.attr))
                return self.functions.get(key) if key else None
            mod = self._imports.get(path, {}).get(recv)
            if mod is not None:
                base = mod.rsplit(".", 1)[-1]
                # basename-level resolution must stay UNIQUE to stay
                # honest: the tree has homonymous modules (state.py,
                # commands.py in several packages), and guessing the
                # wrong one could classify a real suspension point as
                # never-suspending — ambiguity resolves to None, which
                # every consumer treats conservatively
                hits = [key for target in self._basename_paths.get(base, ())
                        if (key := self._module_level.get(
                            (target, func.attr)))]
                if len(hits) == 1:
                    return self.functions.get(hits[0])
        return None

    def info_for(self, path: str, qual: str) -> FunctionInfo | None:
        return self.functions.get((path, qual))

    # -- suspension classification ----------------------------------------

    def _primitive_suspension(self, info: FunctionInfo) -> bool:
        """True yield points that need no graph: async for/with, yield,
        and awaits of anything we can't resolve to a local coroutine."""
        for node in own_body(info.node):
            if isinstance(node, (ast.AsyncFor, ast.AsyncWith, ast.Yield,
                                 ast.YieldFrom)):
                return True
            if isinstance(node, ast.Await):
                if not isinstance(node.value, ast.Call):
                    return True  # awaiting a future/task/variable
                callee = self.resolve_call(info.path, info, node.value)
                if callee is None or not callee.is_async:
                    return True
                tail = (node.value.func.attr
                        if isinstance(node.value.func, ast.Attribute)
                        else getattr(node.value.func, "id", ""))
                if tail in _ALWAYS_SUSPENDS:
                    return True
        return False

    def _classify_suspension(self) -> None:
        suspend = {key: False for key, info in self.functions.items()}
        # reverse awaited-call edges among resolved async defs
        rev: dict[tuple[str, str], set[tuple[str, str]]] = {}
        for key, info in self.functions.items():
            if not info.is_async:
                continue
            if self._primitive_suspension(info):
                suspend[key] = True
                continue
            for node in own_body(info.node):
                if isinstance(node, ast.Await) \
                        and isinstance(node.value, ast.Call):
                    callee = self.resolve_call(info.path, info, node.value)
                    if callee is not None and callee.is_async:
                        rev.setdefault(callee.key, set()).add(key)
        frontier = [k for k, v in suspend.items() if v]
        while frontier:
            key = frontier.pop()
            for caller in rev.get(key, ()):
                if not suspend[caller]:
                    suspend[caller] = True
                    frontier.append(caller)
        self.may_suspend = suspend

    def suspends(self, path: str, caller: FunctionInfo | None,
                 call: ast.Call) -> bool:
        """Would ``await <call>`` yield to the event loop? Unresolved or
        non-async callees: conservatively yes."""
        callee = self.resolve_call(path, caller, call)
        if callee is None or not callee.is_async:
            return True
        tail = (call.func.attr if isinstance(call.func, ast.Attribute)
                else getattr(call.func, "id", ""))
        if tail in _ALWAYS_SUSPENDS:
            return True
        return self.may_suspend.get(callee.key, True)

    # -- async reachability (interprocedural loop-blocking) ----------------

    _REACH_DEPTH = 6

    def _classify_async_reachability(self) -> None:
        reach: dict[tuple[str, str], list[str]] = {}
        frontier: list[tuple[tuple[str, str], list[str], int]] = []
        for key, info in self.functions.items():
            if not info.is_async:
                continue
            for node in own_body(info.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = self.resolve_call(info.path, info, node)
                if callee is not None and not callee.is_async \
                        and callee.key not in reach:
                    chain = [info.label, callee.label]
                    reach[callee.key] = chain
                    frontier.append((callee.key, chain, 1))
        while frontier:
            key, chain, depth = frontier.pop()
            if depth >= self._REACH_DEPTH:
                continue
            info = self.functions[key]
            # own_body here too: a nested def inside a sync helper is a
            # callback, not inline code — it is judged where something
            # actually calls it, exactly like nested defs in async defs
            for node in own_body(info.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = self.resolve_call(info.path, info, node)
                if callee is not None and not callee.is_async \
                        and callee.key not in reach:
                    sub = chain + [callee.label]
                    reach[callee.key] = sub
                    frontier.append((callee.key, sub, depth + 1))
        self.async_reachable = reach
