"""The copycheck engine: discovery, caching, suppressions, baseline, CLI.

Pure stdlib — parsing is ``ast``, project context (knob registry, metric
catalog, wire golden) is read as *text*, never imported, so ``copycat-tpu
lint`` runs in a venv with no jax and touches nothing it checks.

Per-file caching: findings are memoized in ``.copycheck-cache.json``
keyed by the file's content digest plus a config digest covering the
analysis package itself and the cross-file inputs (catalog, golden,
knob registry). Editing any rule or registry invalidates everything;
editing one source file re-lints just that file. The cache stores RAW
findings — suppressions and the baseline are applied after lookup, so
editing the baseline never needs a re-lint.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
from dataclasses import dataclass, field

from .findings import Baseline, Finding, is_suppressed, scan_suppressions
from .rules_asyncio import check_loop_blocking, check_orphan_task
from .rules_await_tear import check_await_tear
from .rules_jit import check_jit_purity, collect_jit_roots
from .rules_registries import (
    check_knob_registry,
    check_metric_registry,
    parse_knob_registry,
    parse_metric_catalog,
)
from .rules_wire import GOLDEN_PATH, check_wire_schema, render_golden

CACHE_FILE = ".copycheck-cache.json"
BASELINE_FILE = ".copycheck-baseline.json"

#: Scanned by default (repo-root-relative). Tests are exercised by
#: pytest, not linted — their fixtures *seed* violations on purpose.
DEFAULT_ROOTS = ("copycat_tpu", "bench.py", "__graft_entry__.py", "examples")



def _repo_root() -> str:
    # copycat_tpu/analysis/engine.py -> repo root two levels up from the
    # package directory; fall back to cwd for installed trees.
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if os.path.isdir(os.path.join(here, "copycat_tpu")):
        return here
    return os.getcwd()


def _read(path: str) -> str | None:
    try:
        with open(path, encoding="utf-8") as f:
            return f.read()
    except OSError:
        return None


@dataclass
class LintContext:
    root: str
    knob_names: set[str] = field(default_factory=set)
    metric_catalog: dict[str, set[str]] | None = None
    wire_golden: dict | None = None
    jit_roots: set[str] = field(default_factory=set)
    config_digest: str = ""

    @classmethod
    def build(cls, root: str, trees: dict[str, ast.Module]) -> "LintContext":
        ctx = cls(root=root)
        knobs_src = _read(os.path.join(root, "copycat_tpu", "utils",
                                       "knobs.py"))
        if knobs_src:
            ctx.knob_names = parse_knob_registry(knobs_src)
        observability = _read(os.path.join(root, "docs", "OBSERVABILITY.md"))
        if observability:
            ctx.metric_catalog = parse_metric_catalog(observability)
        golden_src = _read(os.path.join(root, GOLDEN_PATH))
        if golden_src:
            try:
                ctx.wire_golden = json.loads(golden_src)
            except ValueError:
                ctx.wire_golden = None
        ctx.jit_roots = collect_jit_roots(trees)
        digest = hashlib.sha256()
        for part in (knobs_src or "", observability or "", golden_src or "",
                     "|".join(sorted(ctx.jit_roots))):
            digest.update(part.encode())
            digest.update(b"\x00")
        for mod in sorted(os.listdir(os.path.dirname(__file__))):
            if mod.endswith(".py"):
                digest.update(
                    _read(os.path.join(os.path.dirname(__file__),
                                       mod)).encode())
        ctx.config_digest = digest.hexdigest()
        return ctx


def lint_file(path: str, source: str, tree: ast.Module,
              ctx: LintContext) -> list[Finding]:
    """All raw findings for one file (suppressions/baseline NOT applied)."""
    findings: list[Finding] = []
    findings += check_loop_blocking(tree, path)
    findings += check_orphan_task(tree, path)
    findings += check_await_tear(tree, path)
    findings += check_knob_registry(tree, path, ctx.knob_names)
    # metric-registry is package-scoped: benches/examples at the repo
    # root stage env for servers they build, not metric planes
    if (ctx.metric_catalog is not None
            and path.startswith("copycat_tpu/")):
        findings += check_metric_registry(tree, path, ctx.metric_catalog)
    findings += check_wire_schema(tree, path, ctx.wire_golden)
    findings += check_jit_purity(tree, path, ctx.jit_roots)
    return findings


def discover(root: str, paths: list[str] | None = None) -> list[str]:
    """Repo-relative .py files to lint, sorted."""
    roots = paths or [os.path.join(root, p) for p in DEFAULT_ROOTS]
    out: set[str] = set()
    for entry in roots:
        if os.path.isfile(entry) and entry.endswith(".py"):
            out.add(os.path.relpath(entry, root))
        elif os.path.isdir(entry):
            for dirpath, dirnames, filenames in os.walk(entry):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for name in filenames:
                    if name.endswith(".py"):
                        out.add(os.path.relpath(
                            os.path.join(dirpath, name), root))
    return sorted(p.replace(os.sep, "/") for p in out)


class _Cache:
    def __init__(self, path: str, enabled: bool) -> None:
        self.path = path
        self.enabled = enabled
        self.dirty = False
        self.data: dict = {}
        if enabled:
            try:
                with open(path, encoding="utf-8") as f:
                    self.data = json.load(f).get("files", {})
            except (OSError, ValueError):
                self.data = {}

    def get(self, rel: str, digest: str, config: str) -> list[Finding] | None:
        entry = self.data.get(rel)
        if (not self.enabled or entry is None or entry.get("digest") != digest
                or entry.get("config") != config):
            return None
        return [Finding(**f) for f in entry.get("findings", [])]

    def put(self, rel: str, digest: str, config: str,
            findings: list[Finding]) -> None:
        if not self.enabled:
            return
        self.data[rel] = {"digest": digest, "config": config,
                          "findings": [f.to_json() for f in findings]}
        self.dirty = True

    def save(self) -> None:
        if not (self.enabled and self.dirty):
            return
        try:
            with open(self.path, "w", encoding="utf-8") as f:
                json.dump({"version": 1, "files": self.data}, f)
        except OSError:
            pass  # a read-only checkout just goes uncached


@dataclass
class LintResult:
    findings: list[Finding]          # actionable (not suppressed/baselined)
    baselined: list[Finding]
    suppressed: list[Finding]
    stale_baseline: list[tuple]
    files: int = 0
    parse_errors: list[str] = field(default_factory=list)


def run_lint(root: str | None = None, paths: list[str] | None = None,
             baseline_path: str | None = None,
             use_cache: bool = True) -> LintResult:
    root = root or _repo_root()
    rels = discover(root, paths)
    sources: dict[str, str] = {}
    trees: dict[str, ast.Module] = {}
    parse_errors: list[str] = []
    for rel in rels:
        src = _read(os.path.join(root, rel))
        if src is None:
            continue
        try:
            trees[rel] = ast.parse(src)
            sources[rel] = src
        except SyntaxError as e:
            parse_errors.append(f"{rel}: {e}")
    ctx = LintContext.build(root, trees)
    cache = _Cache(os.path.join(root, CACHE_FILE), use_cache)
    raw: list[Finding] = []
    for rel, tree in trees.items():
        digest = hashlib.sha256(sources[rel].encode()).hexdigest()
        cached = cache.get(rel, digest, ctx.config_digest)
        if cached is None:
            cached = lint_file(rel, sources[rel], tree, ctx)
            cache.put(rel, digest, ctx.config_digest, cached)
        raw.extend(cached)
    cache.save()

    baseline = Baseline.load(
        baseline_path or os.path.join(root, BASELINE_FILE))
    actionable: list[Finding] = []
    suppressed: list[Finding] = []
    baselined: list[Finding] = []
    suppressions_by_path: dict[str, dict[int, set[str]]] = {}
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        file_suppressions = suppressions_by_path.get(f.path)
        if file_suppressions is None:
            file_suppressions = scan_suppressions(sources.get(f.path, ""))
            suppressions_by_path[f.path] = file_suppressions
        if is_suppressed(f, file_suppressions):
            suppressed.append(f)
        elif baseline.match(f):
            baselined.append(f)
        else:
            actionable.append(f)
    return LintResult(
        findings=actionable, baselined=baselined, suppressed=suppressed,
        stale_baseline=baseline.stale(baselined + actionable),
        files=len(trees), parse_errors=parse_errors)


def write_baseline(result: LintResult, root: str | None = None,
                   baseline_path: str | None = None) -> str:
    root = root or _repo_root()
    path = baseline_path or os.path.join(root, BASELINE_FILE)
    existing = Baseline.load(path)
    merged = Baseline()
    for f in result.baselined:
        merged.entries[f.identity()] = existing.entries.get(f.identity(), "")
    for f in result.findings:
        merged.entries[f.identity()] = ""
    merged.save(path)
    return path


def update_wire_golden(root: str | None = None) -> str:
    root = root or _repo_root()
    src = _read(os.path.join(root, "copycat_tpu", "protocol", "messages.py"))
    if src is None:
        raise SystemExit("copycat_tpu/protocol/messages.py not found")
    golden = render_golden(ast.parse(src))
    path = os.path.join(root, GOLDEN_PATH)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write(golden)
    return path


def render_text(result: LintResult, strict: bool) -> str:
    lines: list[str] = []
    for f in result.findings:
        lines.append(f.render())
    for err in result.parse_errors:
        lines.append(f"PARSE ERROR: {err}")
    if result.stale_baseline:
        lines.append("")
        lines.append("stale baseline entries (fixed findings — prune them "
                     "from .copycheck-baseline.json):")
        for rule, path, symbol, message in result.stale_baseline:
            lines.append(f"  {path} [{symbol}] {rule}: {message[:60]}")
    failed = bool(result.findings or result.parse_errors
                  or (strict and result.stale_baseline))
    status = "FAIL" if failed else "ok"
    lines.append("")
    lines.append(
        f"copycheck: {status} — {result.files} files, "
        f"{len(result.findings)} finding(s), "
        f"{len(result.baselined)} baselined, "
        f"{len(result.suppressed)} suppressed"
        + (f", {len(result.stale_baseline)} stale baseline entr(ies)"
           if result.stale_baseline else ""))
    return "\n".join(lines)


def render_json(result: LintResult) -> str:
    return json.dumps({
        "findings": [f.to_json() for f in result.findings],
        "baselined": [f.to_json() for f in result.baselined],
        "suppressed": [f.to_json() for f in result.suppressed],
        "stale_baseline": [list(k) for k in result.stale_baseline],
        "files": result.files,
        "parse_errors": result.parse_errors,
    }, indent=2)


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="copycat-tpu lint",
        description="copycheck: project-native static analysis "
                    "(docs/ANALYSIS.md)")
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to lint (default: the product "
                             "tree)")
    parser.add_argument("--strict", action="store_true",
                        help="exit 1 on any unsuppressed, unbaselined "
                             "finding AND on stale baseline entries (the "
                             "CI gate)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--no-cache", action="store_true",
                        help="ignore + don't write .copycheck-cache.json")
    parser.add_argument("--baseline", default=None, metavar="PATH",
                        help="baseline file (default "
                             ".copycheck-baseline.json at the repo root)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="accept current findings into the baseline "
                             "(fill in the justifications!)")
    parser.add_argument("--update-golden", action="store_true",
                        help="regenerate tests/golden/wire_schema.json "
                             "from protocol/messages.py")
    args = parser.parse_args(argv)

    if args.update_golden:
        path = update_wire_golden()
        print(f"wire-schema golden regenerated: {path}")
        return 0

    result = run_lint(paths=args.paths or None,
                      baseline_path=args.baseline,
                      use_cache=not args.no_cache)
    if args.write_baseline:
        path = write_baseline(result, baseline_path=args.baseline)
        print(f"baseline written: {path} "
              f"({len(result.findings) + len(result.baselined)} entries)")
        return 0
    if args.format == "json":
        print(render_json(result))
    else:
        print(render_text(result, args.strict))
    if result.findings or result.parse_errors:
        return 1
    if args.strict and result.stale_baseline:
        return 1
    return 0
