"""The copycheck engine: discovery, caching, suppressions, baseline, CLI.

Pure stdlib — parsing is ``ast``, project context (knob registry, metric
catalog, wire golden, span vocabulary, exit-code table) is read as
*text*, never imported, so ``copycat-tpu lint`` runs in a venv with no
jax and touches nothing it checks.

Caching is **per (file, rule group)** since copycheck v2: findings are
memoized in ``.copycheck-cache.json`` keyed by the file's content
digest plus one config digest *per rule group* covering exactly that
group's inputs — the rule module sources it runs from, the shared
analysis substrate, and the cross-file inputs it reads (catalog,
golden, knob registry, span vocabulary, the package call graph).
Editing one rule file re-lints that group only; editing a source file
re-lints that file lexically AND the interprocedural groups everywhere
(their results legitimately depend on every file's code — the call
graph is a cross-file input, and the digest says so honestly). The
cache stores RAW findings — suppressions and the baseline are applied
after lookup, so editing the baseline never needs a re-lint.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import subprocess
from dataclasses import dataclass, field
from typing import Callable

from .callgraph import CallGraph
from .findings import Baseline, Finding, is_suppressed, scan_suppressions
from .rules_asyncio import check_loop_blocking, check_orphan_task
from .rules_await_tear import check_await_tear
from .rules_contracts import (
    check_durability_order,
    check_exit_contract,
    check_span_contract,
    parse_exit_codes,
    parse_span_catalog,
)
from .rules_jit import check_jit_purity, collect_jit_roots
from .rules_registries import (
    check_knob_registry,
    check_metric_registry,
    parse_knob_registry,
    parse_metric_catalog,
)
from .rules_wire import GOLDEN_PATH, check_wire_schema, render_golden

CACHE_FILE = ".copycheck-cache.json"
CACHE_VERSION = 2
BASELINE_FILE = ".copycheck-baseline.json"

#: Scanned by default (repo-root-relative). Tests are exercised by
#: pytest, not linted — their fixtures *seed* violations on purpose.
DEFAULT_ROOTS = ("copycat_tpu", "bench.py", "__graft_entry__.py", "examples")



def _repo_root() -> str:
    # copycat_tpu/analysis/engine.py -> repo root two levels up from the
    # package directory; fall back to cwd for installed trees.
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if os.path.isdir(os.path.join(here, "copycat_tpu")):
        return here
    return os.getcwd()


def _read(path: str) -> str | None:
    try:
        with open(path, encoding="utf-8") as f:
            return f.read()
    except OSError:
        return None


def _analysis_source(module: str) -> str:
    return _read(os.path.join(os.path.dirname(__file__), module)) or ""


@dataclass
class LintContext:
    root: str
    knob_names: set[str] = field(default_factory=set)
    metric_catalog: dict[str, set[str]] | None = None
    wire_golden: dict | None = None
    jit_roots: set[str] = field(default_factory=set)
    span_catalog: set[str] | None = None
    exit_codes: set[int] | None = None
    graph: CallGraph | None = None
    tree_digest: str = ""
    #: per-rule-group config digests (cache keys); the legacy
    #: all-covering digest stays for compatibility with older callers
    group_digests: dict[str, str] = field(default_factory=dict)
    config_digest: str = ""

    @classmethod
    def build(cls, root: str, trees: dict[str, ast.Module],
              sources: dict[str, str] | None = None) -> "LintContext":
        ctx = cls(root=root)
        knobs_src = _read(os.path.join(root, "copycat_tpu", "utils",
                                       "knobs.py"))
        if knobs_src:
            ctx.knob_names = parse_knob_registry(knobs_src)
        observability = _read(os.path.join(root, "docs", "OBSERVABILITY.md"))
        if observability:
            ctx.metric_catalog = parse_metric_catalog(observability)
            ctx.span_catalog = parse_span_catalog(observability)
        deployment = _read(os.path.join(root, "docs", "DEPLOYMENT.md"))
        if deployment:
            ctx.exit_codes = parse_exit_codes(deployment)
        golden_src = _read(os.path.join(root, GOLDEN_PATH))
        if golden_src:
            try:
                ctx.wire_golden = json.loads(golden_src)
            except ValueError:
                ctx.wire_golden = None
        ctx.jit_roots = collect_jit_roots(trees)
        ctx.graph = CallGraph.build(trees)
        # the interprocedural groups' cross-file input: every scanned
        # file's content (helper summaries/reachability can shift on any
        # edit — the honest invalidation boundary)
        tree_h = hashlib.sha256()
        for rel in sorted(trees):
            src = (sources or {}).get(rel)
            body = src if src is not None else ast.dump(trees[rel])
            tree_h.update(rel.encode())
            tree_h.update(hashlib.sha256(body.encode()).digest())
        ctx.tree_digest = tree_h.hexdigest()
        for spec in RULE_GROUPS:
            h = hashlib.sha256()
            # engine.py is in every group's key: the RuleGroup wiring
            # (scoping lambdas, argument plumbing) lives here, and an
            # edit to it must not reuse findings the old wiring cached
            for module in ("astutil.py", "findings.py",
                           "engine.py") + spec.modules:
                h.update(_analysis_source(module).encode())
                h.update(b"\x00")
            h.update(spec.inputs(ctx).encode())
            ctx.group_digests[spec.key] = h.hexdigest()
        legacy = hashlib.sha256()
        for key in sorted(ctx.group_digests):
            legacy.update(ctx.group_digests[key].encode())
        ctx.config_digest = legacy.hexdigest()
        return ctx


@dataclass
class RuleGroup:
    """One cache bucket: the rule functions that share sources + inputs."""

    key: str
    rules: tuple[str, ...]
    modules: tuple[str, ...]
    run: Callable[[str, str, ast.Module, LintContext], list]
    inputs: Callable[[LintContext], str] = lambda ctx: ""


def _digest_of(value) -> str:
    return hashlib.sha256(repr(sorted(value) if isinstance(value, (set,))
                               else value).encode()).hexdigest()


RULE_GROUPS: tuple[RuleGroup, ...] = (
    RuleGroup(
        key="asyncio",
        rules=("loop-blocking", "orphan-task"),
        modules=("rules_asyncio.py", "callgraph.py"),
        run=lambda path, src, tree, ctx: (
            check_loop_blocking(tree, path, ctx.graph)
            + check_orphan_task(tree, path)),
        # the interprocedural half reads the whole tree's call graph
        inputs=lambda ctx: ctx.tree_digest),
    RuleGroup(
        key="await_tear",
        rules=("await-tear",),
        modules=("rules_await_tear.py", "callgraph.py"),
        run=lambda path, src, tree, ctx: check_await_tear(
            tree, path, ctx.graph),
        inputs=lambda ctx: ctx.tree_digest),
    RuleGroup(
        key="registries",
        rules=("knob-registry", "metric-registry"),
        modules=("rules_registries.py",),
        run=lambda path, src, tree, ctx: (
            check_knob_registry(tree, path, ctx.knob_names)
            # metric-registry is package-scoped: benches/examples at
            # the repo root stage env for servers they build, not
            # metric planes
            + (check_metric_registry(tree, path, ctx.metric_catalog)
               if (ctx.metric_catalog is not None
                   and path.startswith("copycat_tpu/")) else [])),
        inputs=lambda ctx: (_digest_of(ctx.knob_names)
                            + _digest_of(sorted(
                                (k, tuple(sorted(v)))
                                for k, v in
                                (ctx.metric_catalog or {}).items())))),
    RuleGroup(
        key="wire",
        rules=("wire-schema",),
        modules=("rules_wire.py",),
        run=lambda path, src, tree, ctx: check_wire_schema(
            tree, path, ctx.wire_golden),
        inputs=lambda ctx: json.dumps(ctx.wire_golden, sort_keys=True)),
    RuleGroup(
        key="jit",
        rules=("jit-purity",),
        modules=("rules_jit.py", "callgraph.py"),
        run=lambda path, src, tree, ctx: check_jit_purity(
            tree, path, ctx.jit_roots),
        inputs=lambda ctx: "|".join(sorted(ctx.jit_roots))),
    RuleGroup(
        key="contracts",
        rules=("durability-order", "span-pairing", "exit-code"),
        modules=("rules_contracts.py", "callgraph.py"),
        run=lambda path, src, tree, ctx: (
            check_durability_order(
                tree, path,
                ctx.graph.external_attr_calls if ctx.graph else None)
            + check_span_contract(tree, path, ctx.span_catalog)
            + check_exit_contract(tree, path, ctx.exit_codes)),
        inputs=lambda ctx: (_digest_of(ctx.span_catalog or set())
                            + _digest_of(ctx.exit_codes or set())
                            + ctx.tree_digest)),
)


def lint_file(path: str, source: str, tree: ast.Module,
              ctx: LintContext) -> list[Finding]:
    """All raw findings for one file (suppressions/baseline NOT applied)."""
    findings: list[Finding] = []
    for spec in RULE_GROUPS:
        findings += spec.run(path, source, tree, ctx)
    return findings


def discover(root: str, paths: list[str] | None = None) -> list[str]:
    """Repo-relative .py files to lint, sorted."""
    roots = paths or [os.path.join(root, p) for p in DEFAULT_ROOTS]
    out: set[str] = set()
    for entry in roots:
        if os.path.isfile(entry) and entry.endswith(".py"):
            out.add(os.path.relpath(entry, root))
        elif os.path.isdir(entry):
            for dirpath, dirnames, filenames in os.walk(entry):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for name in filenames:
                    if name.endswith(".py"):
                        out.add(os.path.relpath(
                            os.path.join(dirpath, name), root))
    return sorted(p.replace(os.sep, "/") for p in out)


class _Cache:
    """v2 layout: per file, per rule group —
    ``files[rel] = {digest, groups: {key: {config, findings}}}``."""

    def __init__(self, path: str, enabled: bool) -> None:
        self.path = path
        self.enabled = enabled
        self.dirty = False
        self.data: dict = {}
        if enabled:
            try:
                with open(path, encoding="utf-8") as f:
                    raw = json.load(f)
                if raw.get("version") == CACHE_VERSION:
                    self.data = raw.get("files", {})
            except (OSError, ValueError):
                self.data = {}

    def get(self, rel: str, digest: str, key: str,
            config: str) -> list[Finding] | None:
        entry = self.data.get(rel)
        if not self.enabled or entry is None \
                or entry.get("digest") != digest:
            return None
        group = entry.get("groups", {}).get(key)
        if group is None or group.get("config") != config:
            return None
        return [Finding(**f) for f in group.get("findings", [])]

    def put(self, rel: str, digest: str, key: str, config: str,
            findings: list[Finding]) -> None:
        if not self.enabled:
            return
        entry = self.data.get(rel)
        if entry is None or entry.get("digest") != digest:
            entry = self.data[rel] = {"digest": digest, "groups": {}}
        entry.setdefault("groups", {})[key] = {
            "config": config,
            "findings": [f.to_json() for f in findings]}
        self.dirty = True

    def save(self) -> None:
        if not (self.enabled and self.dirty):
            return
        try:
            with open(self.path, "w", encoding="utf-8") as f:
                json.dump({"version": CACHE_VERSION, "files": self.data}, f)
        except OSError:
            pass  # a read-only checkout just goes uncached


@dataclass
class LintResult:
    findings: list[Finding]          # actionable (not suppressed/baselined)
    baselined: list[Finding]
    suppressed: list[Finding]
    stale_baseline: list[tuple]
    files: int = 0
    parse_errors: list[str] = field(default_factory=list)
    #: set when --changed BASE filtered the report to touched files
    changed_files: list[str] | None = None


def changed_files_since(root: str, base: str) -> list[str]:
    """Repo-relative .py files touched since ``base``: commits since
    the merge-base (three-dot ``BASE...`` — a branch BEHIND base must
    not inherit files only base's own history changed), staged and
    unstaged edits, and untracked files (a brand-new module must not
    dodge the diff gate)."""
    out: set[str] = set()
    for argv in (["git", "diff", "--name-only", f"{base}...", "--",
                  "*.py"],
                 ["git", "diff", "--name-only", "HEAD", "--", "*.py"],
                 ["git", "ls-files", "--others", "--exclude-standard",
                  "--", "*.py"]):
        proc = subprocess.run(argv, cwd=root, capture_output=True,
                              text=True)
        if proc.returncode != 0:
            raise SystemExit(
                f"copycheck: --changed {base}: `{' '.join(argv)}` failed: "
                f"{proc.stderr.strip() or proc.stdout.strip()}")
        out.update(line.strip() for line in proc.stdout.splitlines()
                   if line.strip())
    return sorted(out)


def run_lint(root: str | None = None, paths: list[str] | None = None,
             baseline_path: str | None = None,
             use_cache: bool = True,
             changed_base: str | None = None) -> LintResult:
    root = root or _repo_root()
    rels = discover(root, paths)
    sources: dict[str, str] = {}
    trees: dict[str, ast.Module] = {}
    parse_errors: list[str] = []
    for rel in rels:
        src = _read(os.path.join(root, rel))
        if src is None:
            continue
        try:
            trees[rel] = ast.parse(src)
            sources[rel] = src
        except SyntaxError as e:
            parse_errors.append(f"{rel}: {e}")
    ctx = LintContext.build(root, trees, sources)
    cache = _Cache(os.path.join(root, CACHE_FILE), use_cache)
    raw: list[Finding] = []
    for rel, tree in trees.items():
        digest = hashlib.sha256(sources[rel].encode()).hexdigest()
        for spec in RULE_GROUPS:
            config = ctx.group_digests[spec.key]
            cached = cache.get(rel, digest, spec.key, config)
            if cached is None:
                cached = spec.run(rel, sources[rel], tree, ctx)
                cache.put(rel, digest, spec.key, config, cached)
            raw.extend(cached)
    cache.save()

    baseline = Baseline.load(
        baseline_path or os.path.join(root, BASELINE_FILE))
    actionable: list[Finding] = []
    suppressed: list[Finding] = []
    baselined: list[Finding] = []
    suppressions_by_path: dict[str, dict[int, set[str]]] = {}
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        file_suppressions = suppressions_by_path.get(f.path)
        if file_suppressions is None:
            file_suppressions = scan_suppressions(sources.get(f.path, ""))
            suppressions_by_path[f.path] = file_suppressions
        if is_suppressed(f, file_suppressions):
            suppressed.append(f)
        elif baseline.match(f):
            baselined.append(f)
        else:
            actionable.append(f)
    stale = baseline.stale(baselined + actionable)
    changed: list[str] | None = None
    if changed_base is not None:
        changed = changed_files_since(root, changed_base)
        in_diff = set(changed)
        actionable = [f for f in actionable if f.path in in_diff]
        baselined = [f for f in baselined if f.path in in_diff]
        suppressed = [f for f in suppressed if f.path in in_diff]
        # a partial view can't judge the whole baseline: stale entries
        # are only reported for files the diff touched
        stale = [key for key in stale if key[1] in in_diff]
    return LintResult(
        findings=actionable, baselined=baselined, suppressed=suppressed,
        stale_baseline=stale, files=len(trees),
        parse_errors=parse_errors, changed_files=changed)


def write_baseline(result: LintResult, root: str | None = None,
                   baseline_path: str | None = None) -> str:
    root = root or _repo_root()
    path = baseline_path or os.path.join(root, BASELINE_FILE)
    existing = Baseline.load(path)
    merged = Baseline()
    for f in result.baselined:
        merged.entries[f.identity()] = existing.entries.get(f.identity(), "")
    for f in result.findings:
        merged.entries[f.identity()] = ""
    merged.save(path)
    return path


def update_wire_golden(root: str | None = None) -> str:
    root = root or _repo_root()
    src = _read(os.path.join(root, "copycat_tpu", "protocol", "messages.py"))
    if src is None:
        raise SystemExit("copycat_tpu/protocol/messages.py not found")
    golden = render_golden(ast.parse(src))
    path = os.path.join(root, GOLDEN_PATH)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write(golden)
    return path


def render_text(result: LintResult, strict: bool) -> str:
    lines: list[str] = []
    for f in result.findings:
        lines.append(f.render())
    for err in result.parse_errors:
        lines.append(f"PARSE ERROR: {err}")
    if result.stale_baseline:
        lines.append("")
        lines.append("stale baseline entries (fixed findings — prune them "
                     "from .copycheck-baseline.json):")
        for rule, path, symbol, message in result.stale_baseline:
            lines.append(f"  {path} [{symbol}] {rule}: {message[:60]}")
    failed = bool(result.findings or result.parse_errors
                  or (strict and result.stale_baseline))
    status = "FAIL" if failed else "ok"
    lines.append("")
    scope = (f" ({len(result.changed_files)} changed file(s) in scope)"
             if result.changed_files is not None else "")
    lines.append(
        f"copycheck: {status} — {result.files} files{scope}, "
        f"{len(result.findings)} finding(s), "
        f"{len(result.baselined)} baselined, "
        f"{len(result.suppressed)} suppressed"
        + (f", {len(result.stale_baseline)} stale baseline entr(ies)"
           if result.stale_baseline else ""))
    return "\n".join(lines)


def render_json(result: LintResult) -> str:
    return json.dumps({
        "findings": [f.to_json() for f in result.findings],
        "baselined": [f.to_json() for f in result.baselined],
        "suppressed": [f.to_json() for f in result.suppressed],
        "stale_baseline": [list(k) for k in result.stale_baseline],
        "files": result.files,
        "parse_errors": result.parse_errors,
        **({"changed_files": result.changed_files}
           if result.changed_files is not None else {}),
    }, indent=2)


def render_sarif(result: LintResult) -> str:
    """SARIF 2.1.0 (the GitHub code-scanning ingestion format): every
    actionable finding at level error; baselined findings ride along
    with an ``external`` suppression and inline-suppressed ones with
    ``inSource``, so the full picture annotates a PR without failing
    files the baseline already argues for."""
    all_rules = sorted({f.rule for f in (result.findings + result.baselined
                                         + result.suppressed)})

    def sarif_result(f: Finding, suppression: str | None) -> dict:
        out = {
            "ruleId": f.rule,
            "level": "error" if suppression is None else "note",
            "message": {"text": f.message
                        + (f" [via {' -> '.join(f.via)}]" if f.via else "")},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(1, f.line)},
                },
            }],
            "partialFingerprints": {
                "copycheckIdentity/v1": hashlib.sha256(
                    "|".join(f.identity()).encode()).hexdigest()},
        }
        if suppression is not None:
            out["suppressions"] = [{"kind": suppression}]
        return out

    results = ([sarif_result(f, None) for f in result.findings]
               + [sarif_result(f, "external") for f in result.baselined]
               + [sarif_result(f, "inSource") for f in result.suppressed])
    return json.dumps({
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "copycheck",
                "informationUri": "docs/ANALYSIS.md",
                "rules": [{"id": r} for r in all_rules],
            }},
            "results": results,
        }],
    }, indent=2)


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="copycat-tpu lint",
        description="copycheck: project-native static analysis "
                    "(docs/ANALYSIS.md)")
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to lint (default: the product "
                             "tree)")
    parser.add_argument("--strict", action="store_true",
                        help="exit 1 on any unsuppressed, unbaselined "
                             "finding AND on stale baseline entries (the "
                             "CI gate)")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text")
    parser.add_argument("--output", default=None, metavar="PATH",
                        help="write the rendered report to PATH instead "
                             "of stdout (stdout keeps the one-line "
                             "status) — how CI captures the SARIF "
                             "artifact in the gating run")
    parser.add_argument("--changed", default=None, metavar="BASE",
                        help="report findings only on files touched "
                             "since the git rev BASE (analysis still "
                             "runs package-wide — interprocedural "
                             "results need the whole tree)")
    parser.add_argument("--no-cache", action="store_true",
                        help="ignore + don't write .copycheck-cache.json")
    parser.add_argument("--baseline", default=None, metavar="PATH",
                        help="baseline file (default "
                             ".copycheck-baseline.json at the repo root)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="accept current findings into the baseline "
                             "(fill in the justifications!)")
    parser.add_argument("--update-golden", action="store_true",
                        help="regenerate tests/golden/wire_schema.json "
                             "from protocol/messages.py")
    args = parser.parse_args(argv)

    if args.write_baseline and args.changed:
        # write_baseline rebuilds the file from the run's findings; a
        # diff-scoped run would silently drop every entry (and its
        # hand-written justification) outside the diff
        parser.error("--write-baseline needs the full-tree view; "
                     "run it without --changed")

    if args.update_golden:
        path = update_wire_golden()
        print(f"wire-schema golden regenerated: {path}")
        return 0

    result = run_lint(paths=args.paths or None,
                      baseline_path=args.baseline,
                      use_cache=not args.no_cache,
                      changed_base=args.changed)
    if args.write_baseline:
        path = write_baseline(result, baseline_path=args.baseline)
        print(f"baseline written: {path} "
              f"({len(result.findings) + len(result.baselined)} entries)")
        return 0
    if args.format == "json":
        rendered = render_json(result)
    elif args.format == "sarif":
        rendered = render_sarif(result)
    else:
        rendered = render_text(result, args.strict)
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(rendered + "\n")
        # keep the human-readable verdict on stdout either way
        print(render_text(result, args.strict).splitlines()[-1])
        print(f"report written: {args.output}")
    else:
        print(rendered)
    if result.findings or result.parse_errors:
        return 1
    if args.strict and result.stale_baseline:
        return 1
    return 0
