"""Protocol-contract rules: ``durability-order``, ``span-pairing``,
``exit-code``.

Each encodes an invariant previous PRs could only enforce with tests —
the static complement of a runtime contract:

``durability-order`` — the PR 6 guarantee "followers fsync before ack,
leaders fsync at the commit boundary" (docs/DURABILITY.md), checked
statically inside ``RaftGroup``: no code path may resolve a
client-visible commit/command future or build a success append ack
unless *dominated* by the commit-boundary sync (``_sync_log()`` /
``<x>.log.sync()``). Dominance is lexical source order within a method,
closed interprocedurally through same-class call sites: an ack in
``_apply_entry`` is discharged because every chain of callers reaches
it through ``_apply_up_to`` call sites that sit lexically after a
commit-boundary sync. A method also reachable from OUTSIDE the class
(an attr call on a non-self receiver anywhere in the scanned tree)
cannot be proven dominated — conservative by design; the fused-dispatch
seam (``RaftServer.flush_fused`` → ``grp._finalize_vector_run``) is
exactly such a finding and carries its justification in the baseline.
Error resolves are exempt: a payload naming a ``msg.<ERROR_CODE>``
constant (NO_LEADER, INTERNAL, ...) is a failure report, not an ack,
and ``set_exception`` never acks anything.

``span-pairing`` — the causal-trace span discipline (docs/
OBSERVABILITY.md "Span-name vocabulary"): every literal span name at a
``Tracer.span``-family call site (``TRACER.span``, ``self._trace_span``)
must come from the vocabulary table, exactly as metric-registry
validates metric names — an off-vocabulary span silently falls out of
the cross-member assembly, the phase→histogram mapping, and the
critical-path decomposition. Forwarding wrappers (the name argument is
a parameter of the enclosing function) are exempt — their callers are
checked instead. The pairing half polices the family's completed-span
contract: the API records ``(start, end)`` pairs and returns ``None``,
so ``with TRACER.span(...)`` (an "open" that nothing will ever close)
is a finding, as is a span-family call missing its end timestamp; and a
``.timer(...)`` registry call used as a bare statement opens a Timer
context manager nothing ever enters — the histogram records only in
``__exit__``, so the site measures nothing, silently.

``exit-code`` — the supervisor restart policy is KEYED off child exit
codes (docs/DEPLOYMENT.md exit-code table: 0 = clean stay-down, 2 =
config error never restarted, anything else = crash with backoff).
A role main inventing exit code 3 silently lands in the crash-restart
lane — the deploy-plane mains (``deploy/child.py``, the
``copycat-server`` CLI) may only exit with a documented code.
"""

from __future__ import annotations

import ast
import re

from .astutil import const_str, dotted_name, enclosing_symbol, qualname_map
from .findings import Finding

# ---------------------------------------------------------------------------
# durability-order
# ---------------------------------------------------------------------------

DURABILITY_CLASS = "RaftGroup"

#: attribute names whose futures are client-visible acks
ACK_FUTURE_ATTRS = ("_commit_futures", "commit_futures", "command_futures")

#: ``msg.X`` all-caps constants in a resolve payload mark an error
#: resolve (failure report, not an ack) — scoped to the protocol
#: module's receivers, so an unrelated constant in a SUCCESS payload
#: (``cfg.MAX_INFLIGHT``) can't dodge the dominance check
_ERROR_CONST_RE = re.compile(r"^[A-Z][A-Z_0-9]+$")
_ERROR_RECEIVERS = ("msg", "messages")


def _durability_in_scope(path: str) -> bool:
    return "raft" in path.rsplit("/", 1)[-1]


def _contains_error_const(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) \
                and _ERROR_CONST_RE.match(sub.attr) \
                and isinstance(sub.value, ast.Name) \
                and sub.value.id in _ERROR_RECEIVERS:
            return True
    return False


class _MethodFacts:
    """Per-method lexical facts: commit-boundary syncs, ack events, and
    same-class call sites — nested defs/lambdas attribute to the
    enclosing method at their source line (a spawned completion closure
    still acks on behalf of the method that built it)."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.sync_lines: list[int] = []
        #: (line, description)
        self.acks: list[tuple[int, str]] = []
        #: (line, callee method name)
        self.calls: list[tuple[int, str]] = []


def _ack_future_names(fn: ast.AST) -> set[str]:
    """Local names bound (anywhere in the method, nested defs included)
    from an expression that touches an ack-future map — ``fut =
    futures.pop(...)`` where ``futures = self._commit_futures``, a
    for-target over ``.values()``, a ``session.command_futures.get``."""
    names: set[str] = set()
    aliases: set[str] = set()

    def touches(expr: ast.AST) -> bool:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Attribute) \
                    and sub.attr in ACK_FUTURE_ATTRS:
                return True
            if isinstance(sub, ast.Name) and sub.id in aliases:
                return True
        return False

    # two passes so `futures = self._commit_futures; fut = futures.pop()`
    # resolves regardless of visit order
    for _ in (0, 1):
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and touches(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        (aliases if isinstance(node.value, ast.Attribute)
                         else names).add(tgt.id)
                        names.add(tgt.id)
            elif isinstance(node, ast.For) and touches(node.iter):
                if isinstance(node.target, ast.Name):
                    names.add(node.target.id)
    return names


def _collect_method_facts(cls: ast.ClassDef) -> dict[str, _MethodFacts]:
    facts: dict[str, _MethodFacts] = {}
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        mf = _MethodFacts(item.name)
        fut_names = _ack_future_names(item)
        for node in ast.walk(item):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = dotted_name(func) or ""
            # commit-boundary syncs: self._sync_log() / <x>.log.sync()
            # / log.sync()
            if name.endswith("._sync_log") or name.endswith("log.sync") \
                    or name == "log.sync":
                mf.sync_lines.append(node.lineno)
                continue
            # ack events
            if isinstance(func, ast.Attribute) \
                    and func.attr == "set_result" \
                    and isinstance(func.value, ast.Name) \
                    and func.value.id in fut_names:
                if not (node.args
                        and _contains_error_const(node.args[0])):
                    mf.acks.append(
                        (node.lineno,
                         f"resolve of commit/command future "
                         f"`{func.value.id}`"))
                continue
            if name.rsplit(".", 1)[-1] == "AppendResponse" and any(
                    kw.arg == "success"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True for kw in node.keywords):
                mf.acks.append((node.lineno, "success append ack"))
                continue
            # same-class call sites (incl. inside nested defs/lambdas)
            if isinstance(func, ast.Attribute) \
                    and isinstance(func.value, ast.Name) \
                    and func.value.id == "self":
                mf.calls.append((node.lineno, func.attr))
        facts[item.name] = mf
    return facts


def check_durability_order(tree: ast.Module, path: str,
                           external_attr_calls: set[str] | None = None
                           ) -> list[Finding]:
    if not _durability_in_scope(path):
        return []
    findings: list[Finding] = []
    for cls in tree.body:
        if not isinstance(cls, ast.ClassDef) or cls.name != DURABILITY_CLASS:
            continue
        facts = _collect_method_facts(cls)
        callers: dict[str, list[tuple[str, int]]] = {}
        for mf in facts.values():
            for line, callee in mf.calls:
                if callee in facts:
                    callers.setdefault(callee, []).append((mf.name, line))

        def dominated(method: str, at_line: int,
                      seen: frozenset) -> bool:
            """Is source position ``at_line`` in ``method`` lexically
            preceded by a commit-boundary sync, on every chain of
            same-class callers?"""
            mf = facts[method]
            if any(s < at_line for s in mf.sync_lines):
                return True
            if method in seen:
                return False  # recursion: can't prove, stay conservative
            if external_attr_calls and method in external_attr_calls:
                return False  # entered from outside the class somewhere
            sites = callers.get(method)
            if not sites:
                return False  # an entry point (handler/loop): unproven
            return all(
                dominated(caller, line, seen | {method})
                for caller, line in sites)

        for mf in facts.values():
            for line, what in mf.acks:
                if dominated(mf.name, line, frozenset()):
                    continue
                findings.append(Finding(
                    rule="durability-order", path=path, line=line,
                    message=(f"{what} not dominated by the "
                             f"commit-boundary `_sync_log()` — an ack "
                             f"must never outrun the fsync that makes "
                             f"it durable (docs/DURABILITY.md; fix the "
                             f"order, or baseline with the dominance "
                             f"argument the analysis cannot see)"),
                    symbol=f"{cls.name}.{mf.name}"))
    return findings


# ---------------------------------------------------------------------------
# span-pairing
# ---------------------------------------------------------------------------

SPAN_VOCAB_HEADING = "### Span-name vocabulary"
_SPAN_ROW_RE = re.compile(r"^\|\s*`([a-z0-9_.]+)`\s*\|")

#: method names of the completed-span record family; the span NAME is
#: the second positional argument (trace, name, start, end, ...)
SPAN_RECORD_ATTRS = ("span", "_trace_span")
SPAN_NAME_ARG = 1
SPAN_MIN_ARGS = 4


def parse_span_catalog(observability_md: str) -> set[str] | None:
    """Span names from the docs/OBSERVABILITY.md vocabulary table
    (section scoped: the phase→histogram table further down repeats the
    names but is keyed differently), or ``None`` when missing."""
    idx = observability_md.find(SPAN_VOCAB_HEADING)
    if idx < 0:
        return None
    names: set[str] = set()
    section = observability_md[idx + len(SPAN_VOCAB_HEADING):]
    for line in section.splitlines():
        if line.startswith("#"):
            break
        m = _SPAN_ROW_RE.match(line.strip())
        if m:
            names.add(m.group(1))
    return names or None


def _span_family_call(node: ast.Call) -> bool:
    return (isinstance(node.func, ast.Attribute)
            and node.func.attr in SPAN_RECORD_ATTRS)


def _enclosing_params(tree: ast.Module, lineno: int) -> set[str]:
    params: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= lineno <= end:
                a = node.args
                for arg in (a.posonlyargs + a.args + a.kwonlyargs):
                    params.add(arg.arg)
    return params


def check_span_contract(tree: ast.Module, path: str,
                        catalog: set[str] | None) -> list[Finding]:
    if path.endswith("utils/tracing.py"):
        return []  # the substrate itself (ring, assembly, renderer)
    findings: list[Finding] = []
    for node in ast.walk(tree):
        # `with TRACER.span(...)`: the record family returns None — the
        # "open" can never be closed (and crashes at runtime)
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                ctx = item.context_expr
                if isinstance(ctx, ast.Call) \
                        and isinstance(ctx.func, ast.Attribute) \
                        and ctx.func.attr in SPAN_RECORD_ATTRS:
                    findings.append(Finding(
                        rule="span-pairing", path=path, line=ctx.lineno,
                        message=("`with` over a span-record call — the "
                                 "span family records completed "
                                 "(start, end) pairs and returns None; "
                                 "there is nothing to close. Record "
                                 "the span after the timed section with "
                                 "explicit timestamps"),
                        symbol=enclosing_symbol(tree, ctx.lineno)))
        # a `.timer(...)` opened as a bare statement: the Timer context
        # manager only records in __exit__ — this site measures nothing
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            call = node.value
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr == "timer" and call.args \
                    and const_str(call.args[0]) is not None:
                findings.append(Finding(
                    rule="span-pairing", path=path, line=call.lineno,
                    message=("`.timer(...)` opened and discarded — the "
                             "Timer records only via __exit__; enter it "
                             "(`with m.timer(...)`) or it measures "
                             "nothing, silently"),
                    symbol=enclosing_symbol(tree, call.lineno)))
        if not isinstance(node, ast.Call) or not _span_family_call(node):
            continue
        symbol = enclosing_symbol(tree, node.lineno)
        if len(node.args) < SPAN_MIN_ARGS:
            # the record family's signature is (trace, name, start,
            # end, ...): a shorter call is missing its timestamps — the
            # span can't represent a completed (start, end) pair
            findings.append(Finding(
                rule="span-pairing", path=path, line=node.lineno,
                message=("span-record call with fewer than 4 positional "
                         "args — the family records completed (trace, "
                         "name, start, end) tuples; a span missing its "
                         "timestamps records nothing pairable"),
                symbol=symbol))
            continue
        name_arg = node.args[SPAN_NAME_ARG]
        if isinstance(name_arg, ast.IfExp) \
                and const_str(name_arg.body) is not None \
                and const_str(name_arg.orelse) is not None:
            candidates = [const_str(name_arg.body),
                          const_str(name_arg.orelse)]
        else:
            candidates = [const_str(name_arg)]
        if candidates[0] is None:
            if isinstance(name_arg, ast.Name) \
                    and name_arg.id in _enclosing_params(tree,
                                                         node.lineno):
                continue  # forwarding wrapper: callers are checked
            findings.append(Finding(
                rule="span-pairing", path=path, line=node.lineno,
                message=("dynamic span name at a span-record site — use "
                         "a literal from the docs/OBSERVABILITY.md "
                         "vocabulary, or suppress with the source of "
                         "the names"),
                symbol=symbol))
            continue
        if catalog is None:
            continue
        for name in candidates:
            if name not in catalog:
                findings.append(Finding(
                    rule="span-pairing", path=path, line=node.lineno,
                    message=(f"span name `{name}` is not in the docs/"
                             f"OBSERVABILITY.md vocabulary — an "
                             f"off-vocabulary span falls out of the "
                             f"cross-member assembly and the "
                             f"phase histograms; document it first"),
                    symbol=symbol))
    return findings


# ---------------------------------------------------------------------------
# exit-code
# ---------------------------------------------------------------------------

EXIT_TABLE_HEADING = "| exit |"
_EXIT_ROW_RE = re.compile(r"^\|\s*(\d+)\s*\|")

#: the generic crash code: "anything else = crash" in the table; the
#: role mains deliberately use 1 for one-line-diagnosed fatals
CRASH_EXIT_CODE = 1

EXIT_SCOPE_SUFFIXES = ("deploy/child.py", "copycat_tpu/cli.py")


def parse_exit_codes(deployment_md: str) -> set[int] | None:
    """Documented exit codes from the docs/DEPLOYMENT.md table (plus the
    generic crash code), or ``None`` when the table is missing."""
    codes: set[int] = set()
    in_table = False
    for line in deployment_md.splitlines():
        stripped = line.strip()
        if stripped.startswith(EXIT_TABLE_HEADING):
            in_table = True
            continue
        if in_table:
            m = _EXIT_ROW_RE.match(stripped)
            if m:
                codes.add(int(m.group(1)))
            elif not stripped.startswith("|"):
                break
    if not codes:
        return None
    codes.add(CRASH_EXIT_CODE)
    return codes


def check_exit_contract(tree: ast.Module, path: str,
                        allowed: set[int] | None) -> list[Finding]:
    if allowed is None or not path.endswith(EXIT_SCOPE_SUFFIXES):
        return []
    findings: list[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func) or ""
        if name not in ("sys.exit", "exit", "SystemExit"):
            continue
        if name == "exit" and not isinstance(node.func, ast.Name):
            continue
        if not node.args:
            continue  # bare exit: code 0
        try:
            # literal_eval covers `sys.exit(-1)` (a UnaryOp, and 255 at
            # the process boundary) alongside plain int constants;
            # strings (`sys.exit("msg")` = code 1, documented crash)
            # and dynamic expressions fall out
            value = ast.literal_eval(node.args[0])
        except (ValueError, SyntaxError):
            continue
        if isinstance(value, int) and not isinstance(value, bool) \
                and value not in allowed:
            findings.append(Finding(
                rule="exit-code", path=path, line=node.lineno,
                message=(f"exit code {value} is outside the "
                         f"documented contract "
                         f"({sorted(allowed)}, docs/DEPLOYMENT.md) — "
                         f"the supervisor's restart policy is keyed "
                         f"off these codes; an undocumented code "
                         f"lands in the crash-restart lane silently"),
                symbol=enclosing_symbol(tree, node.lineno)))
    return findings
