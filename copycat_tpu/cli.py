"""Console entry points (installed via ``[project.scripts]``).

``copycat-server`` runs a standalone AtomixServer node — the packaged
equivalent of the reference's standalone-server example
(``StandaloneServerExample.java:27``); the runnable example in
``examples/standalone_server.py`` delegates here. ``copycat-tpu`` is the
operator multi-tool: ``copycat-tpu stats <host:port>`` reads a running
server's stats listener (enable with ``copycat-server --stats-port N``
or ``AtomixServer(..., stats_port=N)``; docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import argparse
import asyncio
import contextlib
import json
import shutil
import signal
import sys
import tempfile


def _server_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="copycat-server",
        description="Run a standalone copycat-tpu server node.")
    parser.add_argument("members", nargs="*", default=["127.0.0.1:5001"],
                        metavar="host:port",
                        help="this node's address, then its peers "
                             "(default 127.0.0.1:5001)")
    parser.add_argument("--stats-port", type=int, default=None,
                        metavar="PORT",
                        help="serve /stats (JSON), /metrics (Prometheus), "
                             "/health, /healthz and /traces on this port "
                             "(0 = ephemeral)")
    parser.add_argument("--stats-host", default="127.0.0.1", metavar="HOST",
                        help="stats listener bind host (default loopback; "
                             "the surface is unauthenticated — widen "
                             "deliberately)")
    parser.add_argument("--log-dir", default=None, metavar="DIR",
                        help="Raft log directory (default: a temp dir, "
                             "removed on exit)")
    parser.add_argument("--storage", default="disk",
                        choices=("memory", "mapped", "disk"),
                        help="log storage level (default disk)")
    parser.add_argument("--groups", type=int, default=None, metavar="N",
                        help="Raft groups hosted by this node "
                             "(docs/SHARDING.md; default COPYCAT_GROUPS)")
    parser.add_argument("--machine", default=None, metavar="MOD:FACTORY",
                        help="state-machine factory spec (one machine "
                             "per group; default: the ResourceManager "
                             "catalog) — docs/DEPLOYMENT.md")
    parser.add_argument("--name", default=None, metavar="NAME",
                        help="node name for logs/stats (default raft)")
    return parser


class ConfigError(Exception):
    """A deployment/config problem the operator (or the supervisor)
    must fix — exit code 2, never restarted (docs/DEPLOYMENT.md)."""


async def _open_with_bind_retry(open_fn, attempts: int = 3,
                                delay: float = 0.3) -> None:
    """Open a listener, absorbing TRANSIENT ``EADDRINUSE``: topology
    specs allocate ports with a release-then-rebind probe
    (``deploy/topology.py::allocate_ports``), so another bind(0) user
    can briefly hold our port between the probe and the child's bind.
    One short retry usually clears it; a port that stays taken after
    ``attempts`` IS a config error (docs/DEPLOYMENT.md) and propagates
    so the supervisor stops restarting it."""
    import errno

    for attempt in range(attempts):
        try:
            await open_fn()
            return
        except OSError as e:
            if e.errno != errno.EADDRINUSE or attempt == attempts - 1:
                raise
            await asyncio.sleep(delay * (attempt + 1))


async def _serve(args: argparse.Namespace) -> None:
    from .deploy.topology import load_machine
    from .io.tcp import TcpTransport
    from .io.transport import Address
    from .manager.atomix import AtomixServer
    from .server.log import Storage, StorageLevel

    members = args.members or ["127.0.0.1:5001"]
    try:
        address = Address.parse(members[0])
        member_addrs = [Address.parse(a) for a in members]
    except (ValueError, TypeError) as e:
        raise ConfigError(f"bad member address: {e}") from e

    # An explicit --log-dir is the operator's to keep; the temp-dir
    # default is ours to remove on exit (it used to leak one
    # copycat-tpu-* dir per run).
    log_dir = args.log_dir or tempfile.mkdtemp(prefix="copycat-tpu-")
    own_log_dir = args.log_dir is None
    level = StorageLevel(getattr(args, "storage", None) or "disk")
    if level is StorageLevel.MEMORY:
        storage = Storage(StorageLevel.MEMORY)
    else:
        storage = Storage(level, directory=log_dir,
                          max_entries_per_segment=16)
    try:
        machine = load_machine(getattr(args, "machine", None))
    except (ValueError, ImportError) as e:
        raise ConfigError(f"--machine: {e}") from e
    server = AtomixServer(
        address, member_addrs, TcpTransport(), storage=storage,
        stats_port=args.stats_port, stats_host=args.stats_host,
        groups=getattr(args, "groups", None), state_machine=machine,
        name=getattr(args, "name", None) or "raft")

    # Graceful shutdown: SIGINT/SIGTERM close the node (stats listener,
    # transport, log) instead of dying mid-write with the temp dir
    # leaked; a second SIGINT still kills the process the hard way.
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()

    def _on_signal() -> None:
        stop.set()
        # restore default handling so a SECOND signal kills the process
        # the hard way instead of re-setting an already-set event while
        # a wedged close() burns its timeout
        for s in (signal.SIGINT, signal.SIGTERM):
            with contextlib.suppress(Exception):
                loop.remove_signal_handler(s)

    for sig in (signal.SIGINT, signal.SIGTERM):
        with contextlib.suppress(NotImplementedError, ValueError):
            loop.add_signal_handler(sig, _on_signal)

    try:
        # inside the try: a failed open (port taken, bad stats bind)
        # must still remove the temp log dir below
        try:
            await _open_with_bind_retry(server.open)
        except OSError as e:
            # a bind that cannot succeed no matter how often the node
            # restarts (port taken, bad stats host) is a CONFIG error:
            # one line + exit 2, so a supervisor knows to stop
            # restarting and surface the spec problem instead
            raise ConfigError(
                f"cannot start at {address}"
                + (f" (stats {args.stats_host}:{args.stats_port})"
                   if args.stats_port is not None else "")
                + f": {e}") from e
        print(f"server listening at {address} (log: {log_dir})", flush=True)
        if server.stats is not None:
            print(f"stats listener on port {server.stats.port} "
                  f"(/stats /metrics /health /healthz /traces)",
                  flush=True)
        await stop.wait()
        print("shutting down...", flush=True)
    finally:
        try:
            await asyncio.wait_for(server.close(), 10)
        except (Exception, asyncio.TimeoutError) as e:
            # teardown-only failure: never mask the primary error (the
            # open/run exception already propagating), but say so in one
            # line instead of swallowing it invisibly
            print(f"copycat-server: close failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
        if own_log_dir:
            shutil.rmtree(log_dir, ignore_errors=True)


def server(argv: list[str] | None = None) -> None:
    """``copycat-server host:port [peers...] [--stats-port N]``

    Exit codes (what the deployment supervisor keys restart policy
    off): 0 = clean shutdown, 2 = config error (bad address/machine
    spec, unbindable port — restarting cannot fix it), 1 = crash. Both
    failure modes print a ONE-LINE diagnosis instead of a traceback."""
    args = _server_parser().parse_args(
        sys.argv[1:] if argv is None else argv)
    try:
        asyncio.run(_serve(args))
    except KeyboardInterrupt:
        pass
    except ConfigError as e:
        print(f"copycat-server: config error: {e}", file=sys.stderr)
        raise SystemExit(2) from None
    except Exception as e:  # noqa: BLE001 — a crash, diagnosed in one line
        print(f"copycat-server: fatal: {type(e).__name__}: {e}",
              file=sys.stderr)
        raise SystemExit(1) from None


# ---------------------------------------------------------------------------
# copycat-tpu: the operator multi-tool
# ---------------------------------------------------------------------------


def _flatten_numeric(snap: dict, prefix: str = "") -> dict:
    """Flatten a stats snapshot to dotted numeric keys (histogram
    summaries expand to .count/.mean/.p50/.p99/.max; ``_gauge_keys``
    hints are dropped). The watch renderer diffs these across polls."""
    out: dict = {}
    for k, v in snap.items():
        if k == "_gauge_keys":
            continue
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            if "count" in v and "mean" in v:  # histogram summary
                for q in ("count", "mean", "p50", "p99", "max"):
                    if q in v:
                        out[f"{key}.{q}"] = v[q]
            else:
                out.update(_flatten_numeric(v, f"{key}."))
        elif isinstance(v, bool):
            out[key] = int(v)
        elif isinstance(v, (int, float)):
            out[key] = v
    return out


def _series_sort_key(key: str) -> tuple:
    """Label-aware ordering for watch/series renders — the canonical
    implementation lives with the series plane
    (``utils/timeseries.py::series_sort_key``): ``name{label}``
    variants sort WITH their family, numeric label values in numeric
    order (``group=2`` before ``group=10``), so labeled delta rendering
    reads identically to the unlabeled path."""
    from .utils.timeseries import series_sort_key

    return series_sort_key(key)


def _render_header(snap: dict, lines: list, prefix: str = "") -> None:
    """Non-numeric leaves (node/role/leader...), recursively: a
    multi-group server's per-group role/leader strings live in nested
    sections and used to be silently dropped from the watch frame."""
    for k, v in snap.items():
        if k == "_gauge_keys":
            continue
        if isinstance(v, dict):
            if "count" in v and "mean" in v:
                continue  # histogram summary: numeric, handled below
            _render_header(v, lines, f"{prefix}{k}.")
        elif not isinstance(v, (int, float)) or isinstance(v, bool):
            lines.append(f"{prefix}{k}: {v}")


def _render_watch(snap: dict, prev: dict | None, dt: float) -> str:
    """One watch frame: scalar header lines, then every numeric series
    with its value and (from the second poll on) its delta/sec. Series
    are keyed by the FULL flattened name including labels — same-named
    series with different labels (per-group ``group=`` series, the
    per-consistency read mix) stay distinct, each with its own delta."""
    import time as _time

    lines = [f"--- {_time.strftime('%H:%M:%S')} ---"]
    _render_header(snap, lines)
    flat = _flatten_numeric(snap)
    for key in sorted(flat, key=_series_sort_key):
        v = flat[key]
        val = f"{v:.4f}".rstrip("0").rstrip(".") if isinstance(v, float) \
            else str(v)
        line = f"{key:<58} {val:>16}"
        if prev is not None and key in prev and dt > 0:
            rate = (flat[key] - prev[key]) / dt
            if rate:
                line += f"  {rate:+,.1f}/s"
        lines.append(line)
    return "\n".join(lines)


def _render_traces_watch(body: bytes, prev_ids: set | None,
                         slowest: int) -> tuple[str, set]:
    """One ``--watch --what traces`` frame: the slowest-N traces with a
    per-phase breakdown (phase lines keyed ``name{group=,member=}`` and
    ordered with the label-aware family sort, so per-group phases sit
    with their family), NEW-marking traces that appeared since the last
    poll."""
    import time as _time

    traces = json.loads(body)
    ids = {t["trace"] for t in traces}
    lines = [f"--- {_time.strftime('%H:%M:%S')} slowest "
             f"{min(slowest, len(traces))}/{len(traces)} traces ---"]
    for t in traces[:slowest]:
        new = "  NEW" if prev_ids is not None and t["trace"] not in prev_ids \
            else ""
        lines.append(f"trace {t['trace']}  total {t['total_ms']:.3f} ms"
                     f"{new}")
        phases: dict[str, float] = {}
        for s in t.get("spans", ()):
            labels = []
            if s.get("group") is not None:
                labels.append(f"group={s['group']}")
            if s.get("member") is not None:
                labels.append(f"member={s['member']}")
            key = s["name"] + (f"{{{','.join(labels)}}}" if labels else "")
            phases[key] = phases.get(key, 0.0) + s.get("duration_ms", 0.0)
        for key in sorted(phases, key=_series_sort_key):
            lines.append(f"  {key:<58} {phases[key]:>12.3f} ms")
    return "\n".join(lines), ids


def _stats(args: argparse.Namespace) -> int:
    import time

    from .server.stats import fetch_stats

    # ``all`` renders every surface in one shot (JSON snapshot first — it
    # carries all registries, the read-lane family included — then the
    # Prometheus text and the flight ring); its watch mode polls /stats,
    # whose delta renderer already covers every numeric series. Watch
    # mode for ``traces`` polls the JSON route (the slowest-N delta
    # renderer's shape) where one-shot mode prints the text rendering.
    watch = getattr(args, "watch", None)
    path = {"stats": "/stats", "metrics": "/metrics",
            "traces": "/traces" if watch is not None else "/traces.txt",
            "flight": "/flight.txt", "health": "/health",
            "all": "/stats"}[args.what]

    def fetch(p: str = path) -> bytes | None:
        try:
            return asyncio.run(fetch_stats(args.address, p))
        except (OSError, RuntimeError, asyncio.TimeoutError) as e:
            print(f"copycat-tpu stats: cannot read {args.address}{p}: "
                  f"{e}\n(is the server running with --stats-port?)",
                  file=sys.stderr)
            return None

    if watch is None:
        body = fetch()
        if body is None:
            return 1
        if args.what == "all":
            print("=== stats ===")
            print(json.dumps(json.loads(body), indent=2, sort_keys=True))
            for title, p in (("metrics", "/metrics"),
                             ("traces", "/traces.txt"),
                             ("flight", "/flight.txt"),
                             ("health", "/health")):
                extra = fetch(p)
                if extra is not None:
                    print(f"=== {title} ===")
                    print(extra.decode(), end="")
        elif args.what in ("metrics", "traces", "flight"):
            print(body.decode(), end="")
        else:
            print(json.dumps(json.loads(body), indent=2, sort_keys=True))
        return 0

    # --watch N: poll + re-render every N seconds; in stats mode each
    # numeric series shows its delta/sec vs the previous poll (how fast
    # is device.elections_started actually moving?); in traces mode the
    # slowest-N traces with per-phase breakdowns, NEW-marking traces
    # that landed since the last poll. Ctrl-C exits.
    prev: dict | None = None
    prev_ids: set | None = None
    prev_t = 0.0
    failures = 0
    try:
        while True:
            body = fetch()
            if body is None:
                failures += 1
                if failures >= 3:
                    return 1
            else:
                failures = 0
                now = time.monotonic()
                if args.what in ("stats", "all"):
                    snap = json.loads(body)
                    print(_render_watch(snap, prev, now - prev_t),
                          flush=True)
                    prev = _flatten_numeric(snap)
                    prev_t = now
                elif args.what == "traces":
                    frame, prev_ids = _render_traces_watch(
                        body, prev_ids, getattr(args, "slowest", 8))
                    print(frame, flush=True)
                else:
                    print(f"--- {time.strftime('%H:%M:%S')} "
                          f"{args.address}{path} ---", flush=True)
                    print(body.decode(), end="", flush=True)
            time.sleep(watch)
    except KeyboardInterrupt:
        return 0


def _bad_addresses(addresses: list[str]) -> int:
    """Reject malformed ``host:port`` arguments up front with a one-line
    actionable error (0 = all fine). Without this, a forgotten port
    would read as 'member unreachable' and degrade a doctor/trace run
    to an incomplete report with a partition/crash diagnosis — hiding a
    typo behind a scarier story."""
    bad = [a for a in addresses if not a.rpartition(":")[2].isdigit()]
    if bad:
        print(f"copycat-tpu: bad address(es) {', '.join(bad)} — expected "
              f"host:port (the server's --stats-port endpoint)",
              file=sys.stderr)
        return 2
    return 0


def _trace(args: argparse.Namespace) -> int:
    """``copycat-tpu trace addr [addr...]``: assemble cross-member
    causal waterfalls (docs/OBSERVABILITY.md "Cluster-wide causal
    tracing"). The FIRST address seeds the slowest-N trace ids (its
    ``/traces`` ring); every given address is then asked for its local
    spans of each id (``/traces/<id>``) and the merged timeline is
    rendered with the critical path highlighted. A member that cannot
    be reached marks the assembly ``incomplete`` — partial waterfalls
    are rendered, never dropped."""
    from .server.stats import fetch_stats
    from .utils.tracing import assemble_trace, render_waterfall

    rc = _bad_addresses(args.addresses)
    if rc:
        return rc

    async def fetch(address: str, path: str) -> bytes | None:
        try:
            return await fetch_stats(address, path)
        except (OSError, RuntimeError, asyncio.TimeoutError):
            return None

    async def collect():
        seed = await fetch(args.addresses[0], "/traces")
        if seed is None:
            return None
        slowest = json.loads(seed)[:args.slowest]
        # genuinely fan out: every member's /traces/<id> for every
        # slowest id in one gather — a slow/hung member costs one
        # timeout, not one timeout per serial fetch
        ids = [entry["trace"] for entry in slowest]
        bodies = await asyncio.gather(*(
            fetch(address, f"/traces/{trace_id}")
            for trace_id in ids for address in args.addresses))
        n = len(args.addresses)
        return [(trace_id, bodies[k * n:(k + 1) * n])
                for k, trace_id in enumerate(ids)]

    collected = asyncio.run(collect())
    if collected is None:
        print(f"copycat-tpu trace: cannot read {args.addresses[0]}/traces"
              f"\n(is the server running with --stats-port?)",
              file=sys.stderr)
        return 1
    if not collected:
        print("(no traces recorded — run a traced client: COPYCAT_TRACE=1)")
        return 0
    assemblies = []
    for trace_id, bodies in collected:
        spans_by_member: dict = {}
        failed: list = []
        for address, body in zip(args.addresses, bodies):
            if body is None:
                failed.append(address)
                continue
            local = json.loads(body)
            spans_by_member.setdefault(
                local.get("member", address), []).extend(local["spans"])
        assemblies.append(assemble_trace(trace_id, spans_by_member,
                                         failed_members=failed))
    if args.json:
        print(json.dumps(assemblies, indent=2))
        return 0
    for assembly in assemblies:
        print(render_waterfall(assembly))
        print()
    return 0


def _fetch_json_fn():
    """A ``fetch_json(address, path) -> dict | None`` closure over the
    stats fetcher — the shared fan-out primitive of every collection
    verb (trace/doctor/timeline/top)."""
    from .server.stats import fetch_stats

    async def fetch_json(address: str, path: str):
        try:
            return json.loads(await fetch_stats(address, path))
        except (OSError, RuntimeError, ValueError, asyncio.TimeoutError):
            return None

    return fetch_json


def _series_payload(raw: dict | None) -> dict | None:
    """Normalize a fetched ``/series`` body: a member with the series
    plane off answers the unknown-route error payload — that is "no
    series retained" (the assembler marks it incomplete), not a
    series."""
    if isinstance(raw, dict) and "samples" in raw:
        return raw
    return None


async def collect_doctor(addresses: list[str], slowest: int = 3,
                         last_s: float | None = None
                         ) -> tuple[dict, list, list]:
    """The doctor's fan-out (exposed for tests): every member's
    ``/health`` + ``/flight`` + ``/stats`` gathered in parallel, plus
    the slowest traces from the first reachable member. With ``last_s``
    (``doctor --last N``) each member's retained ``/series`` window
    rides along for retrospective time-correlation. Returns
    ``(members, failed, slowest_traces)`` where ``members`` maps each
    REACHED address to its payloads and ``failed`` lists the
    unreachable ones — partial fan-outs assemble an incomplete report,
    never a dropped one."""
    import time

    fetch_json = _fetch_json_fn()
    since = time.time() - last_s if last_s else None

    async def member(address: str):
        paths = ["/health", "/flight", "/stats"]
        if last_s:
            paths.append(f"/series?since={since}")
        payloads = await asyncio.gather(*(fetch_json(address, p)
                                          for p in paths))
        return address, payloads

    rows = await asyncio.gather(*(member(a) for a in addresses))
    members: dict = {}
    failed: list = []
    for address, payloads in rows:
        health, flight, stats = payloads[:3]
        if health is None and flight is None and stats is None:
            failed.append(address)
            continue
        members[address] = {"health": health, "flight": flight,
                            "stats": stats}
        if last_s:
            members[address]["series"] = _series_payload(payloads[3])
    traces: list = []
    for address in members:
        body = await fetch_json(address, "/traces")
        if body is not None:
            traces = sorted(body, key=lambda t: -t.get("total_ms", 0.0)
                            )[:slowest]
            break
    return members, failed, traces


def _doctor(args: argparse.Namespace) -> int:
    """``copycat-tpu doctor addr [addr...]``: fan out to every member's
    stats listener, correlate ``/health`` + ``/flight`` + ``/stats`` +
    slowest traces across members, and render a root-cause report
    (docs/OBSERVABILITY.md "Health & diagnosis"). Unreachable members
    mark the report ``incomplete`` — partial reports render, never
    drop; a fully unreachable cluster is a one-line error + exit 1."""
    from .utils.health import assemble_doctor_report, render_doctor_report

    rc = _bad_addresses(args.addresses)
    if rc:
        return rc
    members, failed, traces = asyncio.run(
        collect_doctor(args.addresses, args.slowest,
                       last_s=getattr(args, "last", None)))
    if not members:
        print(f"copycat-tpu doctor: none of {len(args.addresses)} "
              f"member(s) reachable ({', '.join(args.addresses)})\n"
              f"(are the servers running with --stats-port?)",
              file=sys.stderr)
        return 1
    report = assemble_doctor_report(members, failed_members=failed,
                                    slowest_traces=traces)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(render_doctor_report(report))
    return 0


async def collect_timeline(addresses: list[str]
                           ) -> tuple[dict, list]:
    """The timeline's fan-out (exposed for tests): every process's
    ``/series`` + ``/flight`` + ``/health`` gathered in parallel.
    Addresses answering NONE of the routes are failed; a reachable
    process without a ``/series`` route (series plane off) stays in the
    merge — the assembler marks the timeline incomplete, never drops
    it."""
    fetch_json = _fetch_json_fn()

    async def member(address: str):
        series, flight, health = await asyncio.gather(
            fetch_json(address, "/series"),
            fetch_json(address, "/flight"),
            fetch_json(address, "/health"))
        return address, series, flight, health

    rows = await asyncio.gather(*(member(a) for a in addresses))
    members: dict = {}
    failed: list = []
    for address, series, flight, health in rows:
        if series is None and flight is None and health is None:
            failed.append(address)
            continue
        members[address] = {"series": _series_payload(series),
                            "flight": flight, "health": health}
    return members, failed


def _timeline(args: argparse.Namespace) -> int:
    """``copycat-tpu timeline addr [addr...]``: fan out to every
    process's stats listener and render ONE merged cluster timeline
    (docs/OBSERVABILITY.md "Retrospective telemetry") — per-member
    metric sparklines time-aligned on a common grid with
    flight-recorder faults, black-box crash tails, health findings and
    elections/restarts as event marks. Unreachable members mark the
    timeline ``incomplete`` — partial timelines render, never drop; a
    fully unreachable cluster is a one-line error + exit 1."""
    from .utils.timeseries import assemble_timeline, render_timeline

    rc = _bad_addresses(args.addresses)
    if rc:
        return rc
    members, failed = asyncio.run(collect_timeline(args.addresses))
    if not members:
        print(f"copycat-tpu timeline: none of {len(args.addresses)} "
              f"member(s) reachable ({', '.join(args.addresses)})\n"
              f"(are the servers running with --stats-port?)",
              file=sys.stderr)
        return 1
    names = ([n for n in args.names.split(",") if n]
             if getattr(args, "names", None) else None)
    timeline = assemble_timeline(members, failed_members=failed,
                                 last_s=args.last, names=names)
    if args.json:
        print(json.dumps(timeline, indent=2))
    else:
        print(render_timeline(timeline))
    return 0


def _top(args: argparse.Namespace) -> int:
    """``copycat-tpu top addr [addr...]``: the timeline's live sibling
    — a cluster-wide dashboard (per-group role/term/commit rate, lane
    mix, replication in-flight, worst health verdict) refreshed in
    place every ``--watch`` seconds (Ctrl-C exits; ``--once`` prints a
    single frame; ``--json`` one machine-readable frame — the CI smoke
    shape, parity with ``timeline --json``). Unreachable members
    render as rows, never drop."""
    import time

    from .utils.timeseries import render_top, top_payload

    rc = _bad_addresses(args.addresses)
    if rc:
        return rc
    fetch_json = _fetch_json_fn()

    async def collect() -> tuple[dict, list]:
        async def member(address: str):
            stats, health = await asyncio.gather(
                fetch_json(address, "/stats"),
                fetch_json(address, "/health"))
            return address, stats, health

        rows = await asyncio.gather(*(member(a) for a in args.addresses))
        members: dict = {}
        failed: list = []
        for address, stats, health in rows:
            if stats is None and health is None:
                failed.append(address)
                continue
            members[address] = {"stats": stats, "health": health}
        return members, failed

    prev: dict | None = None
    prev_t = 0.0
    failures = 0
    try:
        while True:
            members, failed = asyncio.run(collect())
            if not members:
                failures += 1
                if args.once or getattr(args, "json", False) \
                        or failures >= 3:
                    print(f"copycat-tpu top: none of "
                          f"{len(args.addresses)} member(s) reachable "
                          f"({', '.join(args.addresses)})",
                          file=sys.stderr)
                    return 1
            else:
                failures = 0
                now = time.monotonic()
                if getattr(args, "json", False):
                    # --json implies one-shot: a single frame carries
                    # no prior poll, so rates are null, never a
                    # misleading 0.0
                    payload, _ = top_payload(members, failed)
                    print(json.dumps(payload, indent=2))
                    return 0
                frame, state = render_top(members, failed, prev,
                                          now - prev_t if prev else 0.0)
                if args.once:
                    print(frame, flush=True)
                    return 0
                # refresh in place: clear + home, then the new frame
                print(f"\x1b[2J\x1b[H{frame}", flush=True)
                prev, prev_t = state, now
            time.sleep(args.watch)
    except KeyboardInterrupt:
        return 0


def _profile_device(args: argparse.Namespace) -> int:
    """``copycat-tpu profile --device <trace_dir>``: the device-plane
    side of the one profiling entrypoint — summarize a captured xprof
    trace directory (``utils/profiling.py``) into per-op totals. The
    helper's actionable errors (no xplane files, no xprof package)
    surface as one-line messages + exit 1, never tracebacks."""
    from .utils.profiling import summarize_trace

    try:
        rows = summarize_trace(args.device, top=args.top)
    except (FileNotFoundError, RuntimeError) as exc:
        print(f"copycat-tpu profile: {exc}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps([{"op": op, "total_ms": round(ms, 3),
                           "count": n} for op, ms, n in rows], indent=2))
        return 0
    print(f"{'op':<48} {'total_ms':>10} {'count':>7}")
    for op, ms, n in rows:
        print(f"{op:<48} {ms:>10.3f} {n:>7}")
    return 0


def _profile(args: argparse.Namespace) -> int:
    """``copycat-tpu profile addr [addr...]``: fan out to every
    process's ``/profile`` route and merge the folded wall stacks into
    ONE cluster profile (docs/OBSERVABILITY.md "Profiling") — every
    stack prefixed with its member identity, top-K frames ranked with
    self/total percentages, the heaviest event-loop holds below.
    Unreachable members (and members serving no ``/profile`` —
    ``COPYCAT_PROFILE=0`` or a pre-profiler build) mark the merge
    ``incomplete``, never dropped. ``--json`` emits the merge (the
    ``--diff`` baseline shape); ``--diff saved.json`` ranks per-frame
    self%% moves against a saved artifact. ``--device <trace_dir>``
    routes to the xprof summary instead — host and device profiling
    behind one verb."""
    import time

    from .utils import profiler as profiler_mod

    if getattr(args, "device", None):
        return _profile_device(args)
    if not args.addresses:
        print("copycat-tpu profile: give member stats address(es) for "
              "a host profile, or --device <trace_dir> for a captured "
              "device trace", file=sys.stderr)
        return 2
    rc = _bad_addresses(args.addresses)
    if rc:
        return rc
    fetch_json = _fetch_json_fn()
    path = "/profile"
    if getattr(args, "last", None):
        path += f"?since={time.time() - args.last}"

    async def collect() -> tuple[dict, list]:
        bodies = await asyncio.gather(*(fetch_json(a, path)
                                        for a in args.addresses))
        members: dict = {}
        failed: list = []
        for address, body in zip(args.addresses, bodies):
            if body is None:
                failed.append(address)
            else:
                members[address] = body
        return members, failed

    members, failed = asyncio.run(collect())
    if not members:
        print(f"copycat-tpu profile: none of {len(args.addresses)} "
              f"member(s) reachable ({', '.join(args.addresses)})\n"
              f"(are the servers running with --stats-port?)",
              file=sys.stderr)
        return 1
    profile = profiler_mod.assemble_profile(members, failed_members=failed)
    diff_rows = None
    if getattr(args, "diff", None):
        try:
            with open(args.diff) as f:
                baseline = json.load(f)
        except (OSError, ValueError) as exc:
            print(f"copycat-tpu profile: cannot read baseline "
                  f"{args.diff}: {exc}", file=sys.stderr)
            return 1
        diff_rows = profiler_mod.diff_profiles(profile, baseline,
                                               top=args.top)
    if args.json:
        out = dict(profile)
        if diff_rows is not None:
            out["diff"] = diff_rows
        print(json.dumps(out, indent=2))
        return 0
    print(profiler_mod.render_profile(profile, top=args.top))
    if diff_rows is not None:
        print(f"diff vs {args.diff} (self% deltas, largest move first):")
        for r in diff_rows:
            print(f"  {r['frame']:<52} {r['baseline_self_pct']:>5.1f}% "
                  f"-> {r['self_pct']:>5.1f}%  ({r['delta_pct']:+.1f})")
    return 0


def _cluster(args: argparse.Namespace) -> int:
    """``copycat-tpu cluster <action>`` (docs/DEPLOYMENT.md): ``spawn``
    runs a supervised topology in the foreground — one OS process per
    member and per ingress proxy, real sockets, real fsync, crash
    restarts with backoff; ``status`` renders a running supervisor's
    per-child view from its control listener; ``kill-member`` SIGKILLs
    one child through the same surface (the supervisor restarts it —
    the process-level nemesis, operator edition)."""
    from .server.stats import fetch_stats

    if args.action == "spawn":
        from .deploy.supervisor import run_foreground
        from .deploy.topology import TopologySpec, load_machine
        from .utils import knobs

        try:
            load_machine(args.machine)  # fail fast: exit 2, not a child loop
        except (ValueError, ImportError) as e:
            print(f"copycat-tpu cluster: config error: --machine: {e}",
                  file=sys.stderr)
            return 2
        if args.ingresses and not knobs.get_bool("COPYCAT_INGRESS_TIER"):
            print("copycat-tpu cluster: COPYCAT_INGRESS_TIER=0 — "
                  "deploying no ingress processes (in-server ingress "
                  "path)", flush=True)
            args.ingresses = 0
        spec = TopologySpec.local(
            members=args.members, ingresses=args.ingresses,
            groups=args.groups, base_dir=args.base_dir,
            storage=args.storage, machine=args.machine,
            control_port=args.control_port)
        return run_foreground(spec)

    rc = _bad_addresses([args.address])
    if rc:
        return rc

    def fetch(path: str) -> dict | None:
        try:
            return json.loads(asyncio.run(
                fetch_stats(args.address, path)))
        except (OSError, RuntimeError, ValueError,
                asyncio.TimeoutError) as e:
            print(f"copycat-tpu cluster: cannot reach the supervisor at "
                  f"{args.address}: {e}\n(is `copycat-tpu cluster spawn` "
                  f"running, and is this its control port?)",
                  file=sys.stderr)
            return None

    if args.action == "status":
        snap = fetch("/stats")
        if snap is None:
            return 1
        if args.json:
            print(json.dumps(snap, indent=2, sort_keys=True))
            return 0
        print(f"supervisor pid {snap.get('pid')} — control "
              f"{snap.get('control')}, {snap.get('groups')} group(s)")
        print(f"clients connect to: "
              f"{', '.join(snap.get('client_addrs', ()))}")
        for name, child in snap.get("children", {}).items():
            up = f"pid {child['pid']}" if child.get("pid") else "down"
            print(f"  {name:<12} {child['role']:<8} {child['state']:<13} "
                  f"{up:<10} restarts={child['restarts']} "
                  f"uptime={child['uptime_s']}s stats={child['stats']}")
        return 0

    # kill-member: the write verb — /kill/<name> on the control surface
    out = fetch(f"/kill/{args.name}")
    if out is None:
        return 1
    print(out.get("detail", out))
    return 0 if out.get("ok") else 1


def main(argv: list[str] | None = None) -> None:
    """``copycat-tpu <verb>``: ``stats <host:port>`` reads a running
    server's observability surface; ``trace`` assembles cross-member
    causal waterfalls; ``doctor`` correlates every member's health +
    black-box + traces into a root-cause report; ``cluster`` runs and
    operates a multi-process deployment (docs/DEPLOYMENT.md);
    ``serve`` is ``copycat-server``; ``lint`` runs the copycheck
    static-analysis suite (jax-free — docs/ANALYSIS.md)."""
    raw = sys.argv[1:] if argv is None else argv
    if raw and raw[0] == "lint":
        # copycheck owns its own argparse surface (docs/ANALYSIS.md);
        # lazily imported so `lint` never pays for (or requires) jax
        from .analysis.engine import main as lint_main

        raise SystemExit(lint_main(raw[1:]))
    parser = argparse.ArgumentParser(prog="copycat-tpu")
    sub = parser.add_subparsers(dest="verb", required=True)

    stats = sub.add_parser(
        "stats", help="read a running server's stats listener")
    stats.add_argument("address", metavar="host:port",
                       help="the server's --stats-port endpoint")
    stats.add_argument("--what",
                       choices=("stats", "metrics", "traces", "flight",
                                "health", "all"),
                       default="stats",
                       help="stats = JSON snapshot (default), metrics = "
                            "Prometheus text, traces = slowest requests, "
                            "flight = device-plane flight recorder, "
                            "health = the detector verdict, "
                            "all = every surface in one shot (watch mode "
                            "polls the JSON snapshot's delta view)")
    stats.add_argument("--watch", type=float, default=None, metavar="N",
                       help="poll mode: re-render every N seconds; the "
                            "JSON snapshot view shows delta/sec per "
                            "numeric series between polls, the traces "
                            "view the slowest-N traces with per-phase "
                            "breakdowns and NEW markers (Ctrl-C exits)")
    stats.add_argument("--slowest", type=int, default=8, metavar="N",
                       help="traces watch mode: how many of the slowest "
                            "traces to render per poll (default 8)")

    trace = sub.add_parser(
        "trace", help="assemble cross-member causal waterfalls from "
                      "every member's stats listener")
    trace.add_argument("addresses", nargs="+", metavar="host:port",
                       help="stats endpoints of the members to query; "
                            "the first seeds the slowest-trace list, "
                            "unreachable members mark assemblies "
                            "incomplete (never dropped)")
    trace.add_argument("--slowest", type=int, default=3, metavar="N",
                       help="assemble the N slowest traces (default 3)")
    trace.add_argument("--json", action="store_true",
                       help="emit the assemblies as JSON instead of "
                            "the rendered waterfalls")

    doctor = sub.add_parser(
        "doctor", help="correlate every member's /health, /flight and "
                       "/stats into a cross-member root-cause report")
    doctor.add_argument("addresses", nargs="+", metavar="host:port",
                        help="stats endpoints of the members to "
                             "diagnose; unreachable members mark the "
                             "report incomplete (never dropped)")
    doctor.add_argument("--slowest", type=int, default=3, metavar="N",
                        help="slowest traces to attach to the report "
                             "(default 3)")
    doctor.add_argument("--json", action="store_true",
                        help="emit the report as JSON (the CI artifact "
                             "shape) instead of the rendered text")
    doctor.add_argument("--last", type=float, default=None, metavar="N",
                        help="retrospective mode: also pull each "
                             "member's /series for the last N seconds "
                             "and time-correlate retained metrics "
                             "(commit lag, elections, latency, SLO "
                             "burn) with the diagnosed causes")

    timeline = sub.add_parser(
        "timeline", help="merge every member's /series + /flight + "
                         "/health into one time-aligned cluster "
                         "timeline (sparklines + event marks)")
    timeline.add_argument("addresses", nargs="+", metavar="host:port",
                          help="stats endpoints to merge; unreachable "
                               "members mark the timeline incomplete "
                               "(never dropped)")
    timeline.add_argument("--last", type=float, default=60.0,
                          metavar="N",
                          help="window: render the last N seconds "
                               "(default 60; capped by each member's "
                               "retention ring)")
    timeline.add_argument("--names", default=None, metavar="P1,P2",
                          help="comma-separated metric-name prefixes "
                               "to render (default: commit index, "
                               "elections, commit lag, health status, "
                               "slo.*)")
    timeline.add_argument("--json", action="store_true",
                          help="emit the merged timeline as JSON (the "
                               "CI artifact shape) instead of the "
                               "rendered sparklines")

    top = sub.add_parser(
        "top", help="live cluster dashboard: per-member role, commit "
                    "rate, lane mix, replication in-flight and health "
                    "verdict, refreshed in place")
    top.add_argument("addresses", nargs="+", metavar="host:port",
                     help="stats endpoints to watch; unreachable "
                          "members render as rows, never dropped")
    top.add_argument("--watch", type=float, default=2.0, metavar="N",
                     help="refresh every N seconds (default 2; "
                          "Ctrl-C exits)")
    top.add_argument("--once", action="store_true",
                     help="print a single frame and exit (CI / "
                          "non-tty mode; rates need two polls, so a "
                          "single frame shows '-')")
    top.add_argument("--json", action="store_true",
                     help="emit one machine-readable frame and exit "
                          "(parity with `timeline --json`; rates are "
                          "null on a single poll)")

    profile = sub.add_parser(
        "profile", help="merged cluster wall-stack profile: fan out to "
                        "every member's /profile, merge the folded "
                        "stacks into one flame (per-member prefixes), "
                        "rank top frames and event-loop holds; "
                        "--device summarizes a captured xprof trace")
    profile.add_argument("addresses", nargs="*", metavar="host:port",
                         help="stats endpoints to merge; unreachable "
                              "members mark the profile incomplete, "
                              "never dropped (omit with --device)")
    profile.add_argument("--last", type=float, default=None, metavar="N",
                         help="window: merge the last N seconds "
                              "(default: each member's full retention "
                              "ring, COPYCAT_PROFILE_WINDOW_S)")
    profile.add_argument("--top", type=int, default=20, metavar="K",
                         help="frames ranked in the table (default 20)")
    profile.add_argument("--json", action="store_true",
                         help="emit the merged profile as JSON (the "
                              "--diff baseline / CI artifact shape)")
    profile.add_argument("--diff", default=None, metavar="BASELINE.json",
                         help="rank per-frame self%% moves against a "
                              "profile saved earlier with --json")
    profile.add_argument("--device", default=None, metavar="TRACE_DIR",
                         help="summarize a captured device trace "
                              "directory (utils/profiling.py xprof "
                              "helpers) instead of host profiling")

    cluster = sub.add_parser(
        "cluster", help="run/operate a multi-process deployment "
                        "(docs/DEPLOYMENT.md)")
    csub = cluster.add_subparsers(dest="action", required=True)
    spawn = csub.add_parser(
        "spawn", help="launch a supervised topology in the foreground "
                      "(one OS process per member + ingress proxy)")
    spawn.add_argument("--members", type=int, default=3, metavar="N",
                       help="Raft member processes (default 3)")
    spawn.add_argument("--ingresses", type=int, default=1, metavar="N",
                       help="standalone ingress/proxy processes fronting "
                            "the members (default 1; 0 = clients dial "
                            "members directly)")
    spawn.add_argument("--groups", type=int, default=1, metavar="G",
                       help="Raft groups per member (docs/SHARDING.md)")
    spawn.add_argument("--storage", default="disk",
                       choices=("memory", "mapped", "disk"),
                       help="member log storage level (default disk)")
    spawn.add_argument("--machine", default=None, metavar="MOD:FACTORY",
                       help="state-machine factory spec for every "
                            "process (default: the ResourceManager "
                            "catalog)")
    spawn.add_argument("--base-dir", default=None, metavar="DIR",
                       help="log dirs + child stdout logs live here "
                            "(default: a /tmp topology dir)")
    spawn.add_argument("--control-port", type=int, default=0,
                       metavar="PORT",
                       help="supervisor control listener port "
                            "(default: ephemeral, printed at boot)")
    status = csub.add_parser(
        "status", help="per-child state from a running supervisor")
    status.add_argument("address", metavar="host:port",
                        help="the supervisor's control listener")
    status.add_argument("--json", action="store_true",
                        help="emit the raw /stats payload")
    killm = csub.add_parser(
        "kill-member", help="SIGKILL one child through the control "
                            "surface (the supervisor restarts it)")
    killm.add_argument("address", metavar="host:port",
                       help="the supervisor's control listener")
    killm.add_argument("name", metavar="NAME",
                       help="child name (see `cluster status`), e.g. "
                            "member-1 or ingress-0")

    serve = sub.add_parser("serve", help="run a standalone server node")
    serve.add_argument("rest", nargs=argparse.REMAINDER)

    # registered for --help discoverability; dispatched above before
    # argparse so copycheck's own flags (--strict, --format...) pass
    # through untouched
    sub.add_parser("lint", help="run the copycheck static-analysis "
                                "suite (docs/ANALYSIS.md; --strict is "
                                "the CI gate, --format sarif the "
                                "code-scanning emitter, --changed BASE "
                                "the diff mode)",
                   add_help=False)

    args = parser.parse_args(raw)
    if args.verb == "stats":
        raise SystemExit(_stats(args))
    if args.verb == "trace":
        raise SystemExit(_trace(args))
    if args.verb == "doctor":
        raise SystemExit(_doctor(args))
    if args.verb == "timeline":
        raise SystemExit(_timeline(args))
    if args.verb == "top":
        raise SystemExit(_top(args))
    if args.verb == "profile":
        raise SystemExit(_profile(args))
    if args.verb == "cluster":
        raise SystemExit(_cluster(args))
    if args.verb == "serve":
        server(args.rest)


if __name__ == "__main__":
    # `python -m copycat_tpu.cli ...` == `copycat-tpu ...`: CI and the
    # deployment supervisor run from a bare checkout, no entry points
    main()
