"""Console entry points (installed via ``[project.scripts]``).

``copycat-server`` runs a standalone AtomixServer node — the packaged
equivalent of the reference's standalone-server example
(``StandaloneServerExample.java:27``); the runnable example in
``examples/standalone_server.py`` delegates here.
"""

from __future__ import annotations

import asyncio
import sys
import tempfile


async def _serve(argv: list[str]) -> None:
    from .io.tcp import TcpTransport
    from .io.transport import Address
    from .manager.atomix import AtomixServer
    from .server.log import Storage, StorageLevel

    args = argv or ["127.0.0.1:5001"]
    address = Address.parse(args[0])
    members = [Address.parse(a) for a in args]

    storage = Storage(StorageLevel.DISK,
                      directory=tempfile.mkdtemp(prefix="copycat-tpu-"),
                      max_entries_per_segment=16)
    server = (AtomixServer.builder(address, members)
              .with_transport(TcpTransport())
              .with_storage(storage)
              .build())
    await server.open()
    print(f"server listening at {address} (log: {storage.directory})")

    while True:
        await asyncio.sleep(10)


def server(argv: list[str] | None = None) -> None:
    """``copycat-server host:port [peers...]``"""
    asyncio.run(_serve(sys.argv[1:] if argv is None else argv))
