"""Concurrency and lifecycle utilities (Catalyst ``io.atomix.catalyst.util`` equivalent).

Reference behaviors reconstructed from consumed API surface (SURVEY.md §2.3):
``Listener``/``Listeners`` (closeable callback registrations), ``Managed``
(open/close lifecycle), ``Assert``, ``Scheduled`` (cancellable timers),
``ThreadContext`` (per-node serialized execution context -> here an asyncio
task-group bound to the shared event loop).
"""

from .assertions import check_arg, check_not_null, check_state
from .listeners import Listener, Listeners
from .managed import Managed
from .metrics import Counter, Histogram, MetricsRegistry, Timer
from .scheduled import Scheduled

__all__ = [
    "check_arg",
    "check_not_null",
    "check_state",
    "Counter",
    "Histogram",
    "Listener",
    "Listeners",
    "Managed",
    "MetricsRegistry",
    "Scheduled",
    "Timer",
]
