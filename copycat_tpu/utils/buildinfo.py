"""Process identity for liveness payloads: uptime and the build's git
SHA.

The supervisor's health watch and a rolling-restart check both poll
``/healthz``; without these two fields a freshly restarted child is
indistinguishable from one that was healthy all along (same ok/role
payload), and a half-rolled cluster is indistinguishable from a
finished one. ``uptime_s`` resets on restart; ``git_sha`` changes on
redeploy — together they answer both questions from the cheap route.

The SHA is resolved once per process (a subprocess on first use, cached
forever — ``/healthz`` must stay safe to poll at any frequency) and is
``None`` outside a git checkout (installed wheels, containers without
``.git``), which the payload reports honestly rather than guessing.
"""

from __future__ import annotations

import os
import time

#: process start anchor — import time is process start for every entry
#: point that serves /healthz (server, ingress, supervisor)
_STARTED_MONOTONIC = time.monotonic()

_GIT_SHA: tuple[str | None] | None = None


def process_uptime_s() -> float:
    """Seconds since this process imported the module (monotonic — wall
    clock jumps cannot fake a restart)."""
    return round(time.monotonic() - _STARTED_MONOTONIC, 1)


def git_sha() -> str | None:
    """The checkout's HEAD SHA, resolved once and cached; ``None`` when
    not running from a git checkout."""
    global _GIT_SHA
    if _GIT_SHA is None:
        import subprocess

        sha = None
        try:
            out = subprocess.run(
                ["git", "rev-parse", "HEAD"],
                cwd=os.path.dirname(os.path.abspath(__file__)),
                capture_output=True, text=True, timeout=10)
            if out.returncode == 0:
                sha = out.stdout.strip() or None
        except (OSError, subprocess.SubprocessError):
            pass
        _GIT_SHA = (sha,)
    return _GIT_SHA[0]


def healthz_identity() -> dict:
    """The two fields every role's ``/healthz`` payload carries."""
    return {"uptime_s": process_uptime_s(), "git_sha": git_sha()}
