"""Closeable callback registrations (Catalyst ``Listener``/``Listeners`` equivalent).

The reference registers event callbacks everywhere and relies on the returned
registration being closeable (e.g. ``InstanceSession`` unregisters its parent
listener when the last local listener closes)."""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Callable, Generic, Iterator, TypeVar

from .tasks import spawn

T = TypeVar("T")

class Listener(Generic[T]):
    """A single closeable callback registration.

    Callbacks may be sync or async: a coroutine returned by the callback
    is scheduled on the running event loop (event dispatch happens inside
    the session's loop), mirroring the message-bus handler contract —
    without this, an async callback would be silently dropped ("coroutine
    never awaited"), a footgun for an asyncio-first API.
    """

    def __init__(self, callback: Callable[[T], Any], parent: "Listeners[T] | None" = None):
        self._callback = callback
        self._parent = parent
        self._open = True

    def accept(self, event: T) -> Any:
        if not self._open:
            return None
        result = self._callback(event)
        if asyncio.iscoroutine(result):
            # tasks.spawn strong-refs the task until done (the loop
            # keeps only weak refs, so a suspended callback could
            # otherwise be GC'd mid-execution) and logs exceptions
            # (sync callbacks raise into the emitter; async ones cannot).
            try:
                asyncio.get_running_loop()
            except RuntimeError:
                # Off-loop dispatch: there is nowhere to schedule the
                # coroutine. Log-and-drop instead of raising into the
                # emitter (which is usually a transport/session internals
                # path that cannot handle listener failures).
                result.close()
                logging.getLogger(__name__).error(
                    "async listener callback dropped: no running event "
                    "loop at dispatch (register sync callbacks for "
                    "off-loop emitters)")
                return None
            return spawn(result, name="listener-callback")
        return result

    def close(self) -> None:
        if self._open:
            self._open = False
            if self._parent is not None:
                self._parent._remove(self)

    @property
    def is_open(self) -> bool:
        return self._open


class Listeners(Generic[T]):
    """An ordered collection of listeners; iteration-safe under close()."""

    def __init__(self) -> None:
        self._listeners: list[Listener[T]] = []

    def add(self, callback: Callable[[T], Any]) -> Listener[T]:
        listener = Listener(callback, self)
        self._listeners.append(listener)
        return listener

    def _remove(self, listener: Listener[T]) -> None:
        try:
            self._listeners.remove(listener)
        except ValueError:
            pass

    def accept(self, event: T) -> None:
        for listener in list(self._listeners):
            listener.accept(event)

    def __len__(self) -> int:
        return len(self._listeners)

    def __iter__(self) -> Iterator[Listener[T]]:
        return iter(list(self._listeners))

    def close(self) -> None:
        for listener in list(self._listeners):
            listener.close()
