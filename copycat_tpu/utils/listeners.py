"""Closeable callback registrations (Catalyst ``Listener``/``Listeners`` equivalent).

The reference registers event callbacks everywhere and relies on the returned
registration being closeable (e.g. ``InstanceSession`` unregisters its parent
listener when the last local listener closes)."""

from __future__ import annotations

from typing import Any, Callable, Generic, Iterator, TypeVar

T = TypeVar("T")


class Listener(Generic[T]):
    """A single closeable callback registration."""

    def __init__(self, callback: Callable[[T], Any], parent: "Listeners[T] | None" = None):
        self._callback = callback
        self._parent = parent
        self._open = True

    def accept(self, event: T) -> Any:
        if self._open:
            return self._callback(event)
        return None

    def close(self) -> None:
        if self._open:
            self._open = False
            if self._parent is not None:
                self._parent._remove(self)

    @property
    def is_open(self) -> bool:
        return self._open


class Listeners(Generic[T]):
    """An ordered collection of listeners; iteration-safe under close()."""

    def __init__(self) -> None:
        self._listeners: list[Listener[T]] = []

    def add(self, callback: Callable[[T], Any]) -> Listener[T]:
        listener = Listener(callback, self)
        self._listeners.append(listener)
        return listener

    def _remove(self, listener: Listener[T]) -> None:
        try:
            self._listeners.remove(listener)
        except ValueError:
            pass

    def accept(self, event: T) -> None:
        for listener in list(self._listeners):
            listener.accept(event)

    def __len__(self) -> int:
        return len(self._listeners)

    def __iter__(self) -> Iterator[Listener[T]]:
        return iter(list(self._listeners))

    def close(self) -> None:
        for listener in list(self._listeners):
            listener.close()
