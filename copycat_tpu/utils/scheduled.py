"""Cancellable timers (Catalyst ``Scheduled`` equivalent).

The reference's ``ThreadContext.schedule(delay[, interval]) -> Scheduled`` backs
every election timeout and heartbeat.  State-machine TTL timers do NOT use this:
they are log-time driven (see server/state_machine.py), matching the reference's
deterministic timer discipline (SURVEY.md §5.9)."""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable

logger = logging.getLogger(__name__)


class Scheduled:
    """Handle for a scheduled (optionally repeating) callback on the event loop.

    Must be constructed inside a running event loop.  A repeating callback that
    raises is logged and the schedule continues — a heartbeat/election timer
    must never die silently on a transient error.
    """

    def __init__(
        self,
        delay: float,
        interval: float | None,
        callback: Callable[[], Awaitable[None] | None],
    ) -> None:
        self._delay = delay
        self._interval = interval
        self._callback = callback
        self._task: asyncio.Task | None = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        try:
            await asyncio.sleep(self._delay)
            while True:
                try:
                    result = self._callback()
                    if asyncio.iscoroutine(result):
                        await result
                except asyncio.CancelledError:
                    raise
                except Exception:
                    logger.exception("scheduled callback failed")
                if self._interval is None:
                    return
                await asyncio.sleep(self._interval)
        except asyncio.CancelledError:
            pass

    def cancel(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    @property
    def is_done(self) -> bool:
        return self._task is None or self._task.done()


def schedule(delay: float, callback: Callable[[], Awaitable[None] | None]) -> Scheduled:
    return Scheduled(delay, None, callback)


def schedule_repeating(
    delay: float, interval: float, callback: Callable[[], Awaitable[None] | None]
) -> Scheduled:
    return Scheduled(delay, interval, callback)
