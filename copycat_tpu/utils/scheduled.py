"""Cancellable timers (Catalyst ``Scheduled`` equivalent).

The reference's ``ThreadContext.schedule(delay[, interval]) -> Scheduled`` backs
every election timeout and heartbeat.  State-machine TTL timers do NOT use this:
they are log-time driven (see server/state_machine.py), matching the reference's
deterministic timer discipline (SURVEY.md §5.9)."""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable

from .tasks import spawn

logger = logging.getLogger(__name__)


class Scheduled:
    """Handle for a scheduled (optionally repeating) callback on the event loop.

    Must be constructed inside a running event loop.  Async callbacks run in a
    detached (but strongly-referenced) task so that ``cancel()`` only cancels
    the pending timer, never an in-flight callback — an election-timer callback
    that resets its own timer must not cancel itself.  For repeating timers a
    new invocation is skipped while the previous one is still running, so slow
    callbacks (e.g. keep-alives during leader loss) never pile up.
    """

    def __init__(
        self,
        delay: float,
        interval: float | None,
        callback: Callable[[], Awaitable[None] | None],
    ) -> None:
        self._delay = delay
        self._interval = interval
        self._callback = callback
        self._inflight: asyncio.Task | None = None
        self._task: asyncio.Task | None = spawn(self._run(), name="scheduled-timer")

    async def _run(self) -> None:
        try:
            await asyncio.sleep(self._delay)
            while True:
                self._invoke()
                if self._interval is None:
                    return
                await asyncio.sleep(self._interval)
        except asyncio.CancelledError:
            pass

    def _invoke(self) -> None:
        if self._inflight is not None and not self._inflight.done():
            return  # previous invocation still running - don't overlap
        try:
            result = self._callback()
        except Exception:
            logger.exception("scheduled callback failed")
            return
        if asyncio.iscoroutine(result):
            self._inflight = spawn(self._guard(result), name="scheduled-callback")

    @staticmethod
    async def _guard(coro) -> None:
        try:
            await coro
        except asyncio.CancelledError:
            pass
        except Exception:
            logger.exception("scheduled async callback failed")

    def cancel(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    @property
    def is_done(self) -> bool:
        return self._task is None or self._task.done()


def schedule(delay: float, callback: Callable[[], Awaitable[None] | None]) -> Scheduled:
    return Scheduled(delay, None, callback)


def schedule_repeating(
    delay: float, interval: float, callback: Callable[[], Awaitable[None] | None]
) -> Scheduled:
    return Scheduled(delay, interval, callback)
