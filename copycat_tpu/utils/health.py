"""Cluster health plane: online anomaly detectors, the durable
black-box, and the cross-member ``doctor`` assembly
(docs/OBSERVABILITY.md "Health & diagnosis").

PRs 2/3/9 built the *recording* tiers — the metrics substrate, the
device-plane flight recorder, cross-member causal tracing — but nothing
in the tree *interpreted* them: an operator staring at ``/stats`` on
three members had to correlate leader churn, commit stalls, fsync
spikes and ingress backlog by hand. This module is the interpretation
layer, three pieces:

- **Detectors + :class:`HealthMonitor`** — a small library of host-side
  anomaly detectors evaluated on metric-registry deltas at a fixed
  cadence (``COPYCAT_HEALTH_INTERVAL_S``). Each detector grades one
  failure signature ``ok``/``warn``/``critical`` and attaches the
  evidence series it judged, so the ``/health`` verdict explains
  itself. The monitor feeds the ``health.*`` metric family and spills
  non-ok findings into the black-box.
- **:class:`BlackBox`** — the flight recorder's crash-surviving on-disk
  spill: a CRC-framed append-only ring in the storage directory
  (``server/snapshot.py``'s framing discipline, one frame per event,
  two rotated generations bounded by ``COPYCAT_BLACKBOX_BYTES``). Boot
  reloads the previous life's events tagged ``recovered=true``, so
  post-SIGKILL forensics see exactly the events leading up to death.
  Records are flushed per event: a SIGKILL loses nothing (page cache
  survives process death); power-loss durability is bounded by the
  storage fsync policy like everything else host-side.
- **Doctor assembly** — :func:`assemble_doctor_report` /
  :func:`render_doctor_report`: pure functions correlating the
  ``/health`` + ``/flight`` + ``/stats`` payloads fanned out from every
  member (``copycat-tpu doctor``) into a root-cause report — "group 0
  commit stalled 4.1s: follower local:6002 fsync spike (disk),
  replication window pinned at floor". Unreachable members mark the
  report ``incomplete=true`` with reasons, mirroring the trace
  assembly's semantics — partial reports render, never drop.

``COPYCAT_HEALTH=0`` removes the whole plane — no monitor task, no
black-box file, no ``health.*`` keys, no fsync timing — restoring the
pre-health server bit-identically (the standing A/B discipline).
"""

from __future__ import annotations

import json
import logging
import os
import time
from collections import deque
from typing import Any, Iterable

from . import knobs
from .scheduled import Scheduled, schedule_repeating
from .timeseries import series_onsets

logger = logging.getLogger(__name__)

OK, WARN, CRITICAL = "ok", "warn", "critical"
_RANK = {OK: 0, WARN: 1, CRITICAL: 2}


def worst(grades: Iterable[str]) -> str:
    """The worst severity in ``grades`` (``ok`` when empty)."""
    top = OK
    for g in grades:
        if _RANK.get(g, 0) > _RANK[top]:
            top = g
    return top


class Finding:
    """One detector's graded verdict for one scope (a group, or the
    server when ``group`` is ``None``) with the evidence series that
    produced it."""

    __slots__ = ("detector", "severity", "reason", "group", "evidence")

    def __init__(self, detector: str, severity: str, reason: str = "",
                 group: int | None = None,
                 evidence: dict[str, list] | None = None) -> None:
        self.detector = detector
        self.severity = severity
        self.reason = reason
        self.group = group
        self.evidence = evidence or {}

    def as_dict(self) -> dict:
        d = {"detector": self.detector, "severity": self.severity,
             "reason": self.reason, "evidence": self.evidence}
        if self.group is not None:
            d["group"] = self.group
        return d


# ---------------------------------------------------------------------------
# detectors: each grades ONE failure signature over a window of samples
# ---------------------------------------------------------------------------

#: a history row is ``(t_monotonic, sample_dict)``; samples come from
#: ``RaftGroup.health_sample()`` (group scope) or
#: ``RaftServer.health_sample()`` (server scope)
History = "deque[tuple[float, dict]]"


def _series(history, key: str) -> list:
    return [s.get(key, 0) for _, s in history]


class Detector:
    """Base: subclasses set ``name``/``scope`` and implement
    :meth:`evaluate` over one scope's sample window."""

    name = "detector"
    scope = "group"  # or "server"

    def evaluate(self, history, group: int | None) -> Finding:
        raise NotImplementedError

    def _finding(self, severity: str, reason: str, group: int | None,
                 **evidence) -> Finding:
        return Finding(self.name, severity, reason, group,
                       {k: v for k, v in evidence.items()})


class LeaderChurnDetector(Detector):
    """Elections + leader transitions per window above the churn bound:
    the election-instability signature (partitions, overloaded members,
    mistimed election timeouts)."""

    name = "leader_churn"

    def __init__(self) -> None:
        self.warn_at = max(1, knobs.get_int("COPYCAT_HEALTH_CHURN_WARN"))

    def evaluate(self, history, group):
        elections = _series(history, "elections")
        transitions = _series(history, "transitions")
        churn = (elections[-1] - elections[0]) \
            + (transitions[-1] - transitions[0])
        if churn >= 2 * self.warn_at:
            sev = CRITICAL
        elif churn >= self.warn_at:
            sev = WARN
        else:
            return self._finding(OK, "", group)
        return self._finding(
            sev, f"{churn} elections/transitions in the last "
                 f"{len(history)} samples (warn at {self.warn_at})",
            group, elections=elections, transitions=transitions)


class CommitStallDetector(Detector):
    """The commit index frozen behind the log tail for longer than the
    stall bound; lag GROWING meanwhile (appends still landing with no
    quorum to commit them — the partitioned-leader signature) grades
    critical."""

    name = "commit_stall"

    def __init__(self) -> None:
        self.stall_s = knobs.get_float("COPYCAT_HEALTH_STALL_S")

    def evaluate(self, history, group):
        t_last, last = history[-1]
        commit = last.get("commit_index", 0)
        lag = last.get("log_last_index", 0) - commit
        if lag <= 0:
            return self._finding(OK, "", group)
        # how long has commit sat at exactly this value with lag open?
        stalled_since = t_last
        lag_at_start = lag
        for t, s in reversed(history):
            if s.get("commit_index", 0) != commit \
                    or s.get("log_last_index", 0) <= commit:
                break
            stalled_since = t
            lag_at_start = s.get("log_last_index", 0) - commit
        stalled = t_last - stalled_since
        if stalled < self.stall_s:
            return self._finding(OK, "", group)
        growing = lag > lag_at_start
        sev = CRITICAL if growing else WARN
        detail = "and growing" if growing else "frozen"
        return self._finding(
            sev, f"commit stalled {stalled:.1f}s at index {commit} with "
                 f"{lag} uncommitted entries ({detail})",
            group, commit_index=_series(history, "commit_index"),
            log_last_index=_series(history, "log_last_index"))


class WindowCollapseDetector(Detector):
    """A replication stream's AIMD window collapsing to its floor (the
    congested/slow-follower signature). Judged on the stream's
    cumulative floor-hit counter, not the sampled window value — the
    pinned state is transient by design (AIMD regrows once its EWMA
    re-baselines) and a gauge would miss it. Rewinds landing in the
    same window grade critical (divergence storms, not just
    congestion)."""

    name = "window_collapse"

    def evaluate(self, history, group):
        _, first = history[0]
        _, last = history[-1]
        now: dict = last.get("repl_windows", {})
        before: dict = first.get("repl_windows", {})
        rewinds = _series(history, "rewinds")
        rewind_delta = rewinds[-1] - rewinds[0]
        collapsed = sorted(
            peer for peer, wf in now.items()
            if wf[2] > before.get(peer, (0, 0, 0))[2])
        pinned = sorted(peer for peer, wf in now.items()
                        if wf[0] <= wf[1])
        if not collapsed and not (pinned and rewind_delta > 0):
            return self._finding(OK, "", group)
        peers = sorted(set(collapsed) | set(pinned))
        sev = CRITICAL if rewind_delta > 0 else WARN
        tail = f", {rewind_delta} rewinds" if rewind_delta else ""
        return self._finding(
            sev, f"replication window collapsed to floor for "
                 f"{', '.join(peers)}{tail}",
            group, peers=peers, rewinds=rewinds,
            windows={p: list(wf) for p, wf in now.items()})


class FsyncSpikeDetector(Detector):
    """Commit-boundary fsync latency spiking past the pre-window EWMA
    baseline: the slow/failing-disk signature. Judged against the
    baseline at the window START, so a sustained slow disk cannot hide
    by dragging the EWMA up to meet itself."""

    name = "fsync_spike"

    def __init__(self) -> None:
        self.factor = knobs.get_float("COPYCAT_HEALTH_FSYNC_FACTOR")

    def evaluate(self, history, group):
        _, first = history[0]
        _, last = history[-1]
        if last.get("fsyncs", 0) <= first.get("fsyncs", 0):
            return self._finding(OK, "", group)  # no fsyncs this window
        # baseline: the EARLIEST learned EWMA in the window — not
        # blindly sample 0, which is 0.0 on a server whose monitor
        # started ticking before its first commit fsync (the detector
        # would then sit blind for a whole window's worth of samples)
        learned = next((s.get("fsync_ewma_ms", 0.0) for _, s in history
                        if s.get("fsync_ewma_ms", 0.0) > 0.0), 0.0)
        if learned <= 0.0:
            return self._finding(OK, "", group)  # baseline not learned yet
        # the 1 ms noise floor: page-cache fsyncs baseline in the tens
        # of microseconds, where scheduler jitter alone is a 4x "spike"
        # — a real disk problem clears 4 ms without help
        baseline = max(learned, 1.0)
        recent = max(_series(history, "fsync_max_ms"))
        if recent >= 3 * self.factor * baseline:
            sev = CRITICAL
        elif recent >= self.factor * baseline:
            sev = WARN
        else:
            return self._finding(OK, "", group)
        return self._finding(
            sev, f"fsync {recent:.1f}ms vs {baseline:.2f}ms baseline "
                 f"({recent / baseline:.0f}x)",
            group, fsync_max_ms=_series(history, "fsync_max_ms"),
            fsync_ewma_ms=_series(history, "fsync_ewma_ms"))


class SessionExpiryDetector(Detector):
    """Session expiries per window above the storm bound: clients dying
    en masse, or keep-alives not getting through (an ingress or
    partition symptom seen from the session plane)."""

    name = "session_expiry"

    def __init__(self) -> None:
        self.warn_at = max(1, knobs.get_int("COPYCAT_HEALTH_EXPIRY_WARN"))

    def evaluate(self, history, group):
        expired = _series(history, "sessions_expired")
        delta = expired[-1] - expired[0]
        if delta >= 3 * self.warn_at:
            sev = CRITICAL
        elif delta >= self.warn_at:
            sev = WARN
        else:
            return self._finding(OK, "", group)
        return self._finding(
            sev, f"{delta} sessions expired in the last "
                 f"{len(history)} samples (warn at {self.warn_at})",
            group, sessions_expired=expired)


class SnapshotFailureDetector(Detector):
    """Snapshot capture or install failures since the window start:
    each one silently degrades recovery (longer replays, installs
    falling back) long before anything else looks wrong."""

    name = "snapshot_failure"

    def evaluate(self, history, group):
        failures = _series(history, "snap_failures")
        delta = failures[-1] - failures[0]
        if delta == 0:
            return self._finding(OK, "", group)
        sev = CRITICAL if delta >= 3 else WARN
        return self._finding(
            sev, f"{delta} snapshot capture/install failure(s)",
            group, snap_failures=failures)


class IngressBacklogDetector(Detector):
    """Server-scope: the ingress/proxy plane backing up — in-flight
    proxied sub-requests plus undelivered session events growing past
    the queue bound (a saturated or unreachable group leader seen from
    the ingress side)."""

    name = "ingress_backlog"
    scope = "server"

    def __init__(self) -> None:
        self.warn_at = max(1, knobs.get_int("COPYCAT_HEALTH_QUEUE_WARN"))

    def evaluate(self, history, group):
        depth = [s.get("proxy_inflight", 0) + s.get("event_backlog", 0)
                 for _, s in history]
        now = depth[-1]
        growing = len(depth) >= 2 and now > depth[0]
        if now >= 4 * self.warn_at:
            sev = CRITICAL
        elif now >= self.warn_at and growing:
            sev = WARN
        else:
            return self._finding(OK, "", group)
        return self._finding(
            sev, f"ingress backlog at {now} "
                 f"({'growing' if growing else 'flat'}, warn at "
                 f"{self.warn_at})",
            group, backlog=depth)


class SloBurnDetector(Detector):
    """Error-budget burn against the operator's service objectives
    (``COPYCAT_SLO_P99_MS`` latency, ``COPYCAT_SLO_AVAIL``
    availability), judged over the RETAINED series window
    (``utils/timeseries.py``) — minutes of history, not the monitor's
    short evidence deque — and exported as the ``slo.*`` gauge family.

    Availability: an interval burns budget when any group's commit sat
    frozen behind its log tail across the sample (lag open, commit not
    advancing — the cluster could not serve that group). Burn rate is
    the window error rate over the objective's error budget; sustained
    burn >= 1x eats the whole budget, >= 10x is the classic fast-burn
    page. Latency: the fraction of ACTIVE intervals (commit-latency
    histogram advanced) whose sampled p99 exceeded the objective —
    needs tracing on, since ``latency.commit_ms`` only advances for
    traced requests.

    Constructed only when the host server carries a series store
    (``COPYCAT_SERIES=1`` + health plane on), so the off-plane stays
    bit-identical."""

    name = "slo_burn"
    scope = "server"

    def __init__(self, server: Any) -> None:
        self.server = server
        raw_p99 = knobs.get_raw("COPYCAT_SLO_P99_MS")
        raw_avail = knobs.get_raw("COPYCAT_SLO_AVAIL")
        self.p99_ms = float(raw_p99) if raw_p99 else None
        self.avail = float(raw_avail) if raw_avail else None
        # slo.* gauges exist only for objectives the operator actually
        # set: an unconfigured detector leaves the registry untouched
        m = server.metrics_server_registry()
        self._m: dict = {}
        if self.p99_ms is not None:
            self._m["p99_objective_ms"] = m.gauge("slo.p99_objective_ms")
            self._m["p99_observed_ms"] = m.gauge("slo.p99_observed_ms")
            self._m["p99_burn"] = m.gauge("slo.p99_burn")
            self._m["p99_objective_ms"].set(self.p99_ms)
        if self.avail is not None:
            self._m["avail_objective"] = m.gauge("slo.avail_objective")
            self._m["avail_observed"] = m.gauge("slo.avail_observed")
            self._m["avail_burn"] = m.gauge("slo.avail_burn")
            self._m["avail_objective"].set(self.avail)

    def evaluate(self, history, group):
        store = getattr(self.server, "series", None)
        if store is None or (self.p99_ms is None and self.avail is None):
            return self._finding(OK, "", group)
        rows = store.rows()
        if len(rows) < 2:
            return self._finding(OK, "", group)
        sev = OK
        reasons: list[str] = []
        evidence: dict = {}
        if self.avail is not None:
            bad = 0
            stuck_series: list[int] = []
            lag_keys = sorted({k for _, v in rows for k in v
                               if k.split("{", 1)[0] == "raft_commit_lag"})
            for i in range(1, len(rows)):
                prev_v, cur = rows[i - 1][1], rows[i][1]
                stuck = any(
                    cur.get(lk, 0) > 0
                    and cur.get(lk.replace("raft_commit_lag",
                                           "raft_commit_index", 1), 0)
                    <= prev_v.get(lk.replace("raft_commit_lag",
                                             "raft_commit_index", 1), 0)
                    for lk in lag_keys)
                stuck_series.append(1 if stuck else 0)
                bad += 1 if stuck else 0
            total = len(rows) - 1
            error_rate = bad / total
            observed = 1.0 - error_rate
            burn = error_rate / max(1e-9, 1.0 - self.avail)
            self._m["avail_observed"].set(round(observed, 6))
            self._m["avail_burn"].set(round(burn, 3))
            if burn >= 1.0:
                sev = worst((sev, CRITICAL if burn >= 10.0 else WARN))
                reasons.append(
                    f"availability burn {burn:.1f}x budget (observed "
                    f"{100 * observed:.2f}% vs objective "
                    f"{100 * self.avail:.2f}% over {total} intervals)")
                evidence["unavailable_intervals"] = stuck_series[-30:]
        if self.p99_ms is not None:
            judged = violations = 0
            worst_p99 = 0.0
            p99_series: list[float] = []
            for i in range(1, len(rows)):
                cur = rows[i][1]
                if not any(v > 0 for k, v in cur.items()
                           if k.startswith("latency.commit_ms")
                           and k.endswith(".count")):
                    continue
                judged += 1
                p = max((v for k, v in cur.items()
                         if k.startswith("latency.commit_ms")
                         and k.endswith(".p99")), default=0.0)
                p99_series.append(round(p, 3))
                worst_p99 = max(worst_p99, p)
                if p > self.p99_ms:
                    violations += 1
            if judged:
                frac = violations / judged
                self._m["p99_observed_ms"].set(round(worst_p99, 3))
                self._m["p99_burn"].set(round(frac, 3))
                if frac >= 0.1:
                    sev = worst((sev, CRITICAL if frac >= 0.5 else WARN))
                    reasons.append(
                        f"commit p99 {worst_p99:.1f}ms breached the "
                        f"{self.p99_ms:.0f}ms objective in "
                        f"{100 * frac:.0f}% of {judged} active intervals")
                    evidence["commit_p99_ms"] = p99_series[-30:]
        if sev == OK:
            return self._finding(OK, "", group)
        return self._finding(sev, "; ".join(reasons), group, **evidence)


class LoopStallDetector(Detector):
    """Event-loop holds over ``COPYCAT_PROFILE_HOLD_MS``, judged on the
    per-window max hold with the holding frame as evidence — the
    profiling plane's runtime complement to the copycheck loop-blocking
    rule. A hold at the threshold grades ``warn``; 5x the threshold
    grades ``critical`` (a 500ms+ hold under the default freezes
    heartbeats and elections alike).

    Reads the profiler's bounded hold ring over the evidence window's
    actual span (the history deque's timestamps), so one old hold ages
    out of the verdict exactly like every delta detector's evidence.
    Constructed only when the host carries a profiler
    (``COPYCAT_PROFILE=1``), keeping the off-plane detector set — and
    every ``health.*`` key — bit-identical.

    In-process multi-server clusters share one process-wide profiler,
    so every co-resident member's detector sees the same holds: honest
    for a process-level property (the loop and the GIL are shared)."""

    name = "loop_stall"
    scope = "server"

    def __init__(self, server: Any) -> None:
        self.server = server
        self.hold_ms = max(1.0,
                           knobs.get_float("COPYCAT_PROFILE_HOLD_MS"))

    def evaluate(self, history, group):
        prof = getattr(self.server, "profiler", None)
        if prof is None:
            return self._finding(OK, "", group)
        lookback = 30.0
        if len(history) >= 2:
            lookback = max(1.0, history[-1][0] - history[0][0])
        holds = prof.holds_since(time.time() - lookback)
        if not holds:
            return self._finding(OK, "", group)
        worst_hold = max(holds, key=lambda h: h["ms"])
        sev = (CRITICAL if worst_hold["ms"] >= 5 * self.hold_ms
               else WARN)
        return self._finding(
            sev,
            f"event loop held {worst_hold['ms']:.0f}ms by "
            f"{worst_hold['frame']} ({len(holds)} hold(s) >= "
            f"{self.hold_ms:.0f}ms in {lookback:.0f}s)",
            group,
            max_hold_ms=worst_hold["ms"],
            frames=[h["frame"] for h in holds[-5:]],
            stack=worst_hold.get("stack", ""))


GROUP_DETECTORS = (LeaderChurnDetector, CommitStallDetector,
                   WindowCollapseDetector, FsyncSpikeDetector,
                   SessionExpiryDetector, SnapshotFailureDetector)
SERVER_DETECTORS = (IngressBacklogDetector,)
#: the catalog of detector names (docs/OBSERVABILITY.md) — slo_burn
#: and loop_stall construct with the host server, so they ride
#: neither class tuple
DETECTOR_NAMES = tuple(d.name for d in GROUP_DETECTORS
                       + SERVER_DETECTORS) \
    + (SloBurnDetector.name, LoopStallDetector.name)


# ---------------------------------------------------------------------------
# the monitor: cadence sampling + evaluation + exposition
# ---------------------------------------------------------------------------


class HealthMonitor:
    """Samples a :class:`RaftServer`'s groups at a fixed cadence,
    evaluates every detector on the windows, and keeps the last verdict
    for the ``/health`` route. Constructed only when ``COPYCAT_HEALTH``
    is on — its absence IS the A/B off-plane."""

    def __init__(self, server: Any, interval: float | None = None,
                 window: int | None = None) -> None:
        self.server = server
        self.interval = (interval if interval is not None
                         else knobs.get_float("COPYCAT_HEALTH_INTERVAL_S"))
        self.window = max(2, window if window is not None
                          else knobs.get_int("COPYCAT_HEALTH_WINDOW"))
        self.group_detectors = [cls() for cls in GROUP_DETECTORS]
        self.server_detectors = [cls() for cls in SERVER_DETECTORS]
        if getattr(server, "series", None) is not None:
            # SLO burn judges the RETAINED series window, so it exists
            # exactly when the series plane does — COPYCAT_SERIES=0
            # keeps the detector set (and every health.* key)
            # bit-identical to the pre-series plane
            self.server_detectors.append(SloBurnDetector(server))
        if getattr(server, "profiler", None) is not None:
            # loop_stall judges the profiler's hold ring, so it exists
            # exactly when the profiling plane does — COPYCAT_PROFILE=0
            # keeps the detector set (and every health.* key)
            # bit-identical to the pre-profiler plane
            self.server_detectors.append(LoopStallDetector(server))
        self._history: dict[int, deque] = {}
        self._server_history: deque = deque(maxlen=self.window)
        self._timer: Scheduled | None = None
        self._last_severity: dict[tuple, str] = {}
        self._last_tick = 0.0
        self.ticks = 0
        self.last_verdict: dict | None = None
        m = server.metrics_server_registry()
        self._m_checks = m.counter("health.checks")
        self._m_findings = {sev: m.counter("health.findings", severity=sev)
                            for sev in (WARN, CRITICAL)}
        self._m_status = m.gauge("health.status")
        self._m_detector = {
            d.name: m.gauge("health.detector_status", detector=d.name)
            for d in self.group_detectors + self.server_detectors}

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._timer is None:
            self._timer = schedule_repeating(self.interval, self.interval,
                                             self.tick)

    def stop(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    # -- sampling + evaluation ---------------------------------------------

    def verdict(self) -> dict:
        """The current verdict for the ``/health`` route: re-evaluates
        at most once per half-cadence, serving the cached verdict to
        faster pollers. Every tick APPENDS a sample to the
        count-bounded evidence deques — an unthrottled 5 Hz probe would
        shrink every delta detector's lookback from ~window x interval
        to ~window/5 seconds, so observing health would suppress it."""
        now = time.monotonic()
        if self.last_verdict is None \
                or now - self._last_tick >= self.interval / 2:
            return self.tick()
        return self.last_verdict

    def tick(self) -> dict:
        """Sample every group + the server plane, evaluate all
        detectors, update the ``health.*`` family, spill newly non-ok
        findings to the black-box, and return (and keep) the verdict."""
        now = time.monotonic()
        self._last_tick = now
        self.server._attach_flight_spill()
        # the retrospective-telemetry ring rides THIS cadence — the
        # series plane spawns no task of its own (utils/timeseries.py)
        tick_series = getattr(self.server, "series_tick", None)
        if tick_series is not None:
            tick_series()
        findings: list[Finding] = []
        for grp in self.server.groups:
            hist = self._history.get(grp.group_id)
            if hist is None:
                hist = self._history[grp.group_id] = deque(
                    maxlen=self.window)
            hist.append((now, grp.health_sample()))
            for det in self.group_detectors:
                findings.append(det.evaluate(hist, grp.group_id))
        self._server_history.append((now, self.server.health_sample()))
        for det in self.server_detectors:
            findings.append(det.evaluate(self._server_history, None))
        self.ticks += 1
        self._m_checks.inc()
        verdict = self._fold(findings)
        self.last_verdict = verdict
        return verdict

    def _fold(self, findings: list[Finding]) -> dict:
        by_detector: dict[str, dict] = {}
        reasons: list[str] = []
        group_status: dict[int, str] = {}
        for f in findings:
            entry = by_detector.setdefault(
                f.detector, {"status": OK, "groups": {}})
            scope = {"status": f.severity}
            if f.severity != OK:
                scope["reason"] = f.reason
                scope["evidence"] = f.evidence
                where = (f"group {f.group}" if f.group is not None
                         else "server")
                reasons.append(f"{where}: {f.reason} [{f.detector}]")
                self._m_findings[f.severity].inc()
                key = (f.detector, f.group)
                if self._last_severity.get(key, OK) != f.severity:
                    # spill TRANSITIONS, not every tick — the black-box
                    # ring must survive a long outage without the storm
                    # evicting its own onset
                    self.server.health_note(
                        "health", detector=f.detector,
                        severity=f.severity, group=f.group,
                        reason=f.reason)
            self._last_severity[(f.detector, f.group)] = f.severity
            entry["groups"][("server" if f.group is None
                             else str(f.group))] = scope
            entry["status"] = worst((entry["status"], f.severity))
            if f.group is not None:
                group_status[f.group] = worst(
                    (group_status.get(f.group, OK), f.severity))
        status = worst(e["status"] for e in by_detector.values())
        self._m_status.set(_RANK[status])
        for name, entry in by_detector.items():
            self._m_detector[name].set(_RANK[entry["status"]])
        g0 = self.server.groups[0]
        return {
            "status": status,
            "node": str(self.server.address),
            "role": g0.role,
            "term": g0.term,
            "ticks": self.ticks,
            "checked_at": round(time.time(), 3),
            "reasons": reasons,
            "detectors": by_detector,
            "groups": {str(g): s for g, s in sorted(group_status.items())},
        }


# ---------------------------------------------------------------------------
# the durable black-box
# ---------------------------------------------------------------------------


class BlackBox:
    """Crash-surviving flight-recorder spill: one CRC-framed record per
    event appended to ``<path>``, rotated to ``<path>.1`` past
    ``max_bytes`` (two generations = a bounded on-disk ring). Reads
    distrust everything past the first torn frame, same discipline as
    the log segments."""

    def __init__(self, path: str, max_bytes: int | None = None,
                 recovered_cap: int = 512) -> None:
        self.path = path
        self.max_bytes = max(4096, max_bytes if max_bytes is not None
                             else knobs.get_int("COPYCAT_BLACKBOX_BYTES"))
        self._seq = 0
        self._live: deque = deque(maxlen=recovered_cap)
        self.torn = 0
        #: previous lives' events, oldest first, each tagged
        #: ``recovered=True`` — what post-SIGKILL forensics read
        self.recovered: list[dict] = []
        directory = os.path.dirname(self.path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        self._load(recovered_cap)
        self._fh = open(self.path, "ab")

    # -- write path --------------------------------------------------------

    def record(self, kind: str, **fields) -> dict:
        self._seq += 1
        event = {"seq": self._seq, "t": round(time.time(), 3),
                 "kind": kind, **fields}
        self._append(event)
        self._live.append(event)
        return event

    def spill_event(self, event: dict) -> None:
        """Spill hook for a :class:`~copycat_tpu.models.telemetry.
        FlightRecorder`: persists the ring event as-is (it already
        carries seq/t/kind)."""
        self._append(event)

    def _append(self, event: dict) -> None:
        from ..server.snapshot import frame

        try:
            payload = json.dumps(event, default=str).encode()
            if self._fh.tell() + len(payload) > self.max_bytes:
                self._rotate()
            self._fh.write(frame(payload))
            # flush, no fsync: a SIGKILL cannot lose page-cache bytes;
            # power-loss durability is the storage fsync policy's job
            self._fh.flush()
        except (OSError, ValueError):  # pragma: no cover - disk full/EIO
            logger.warning("black-box append to %s failed", self.path,
                           exc_info=True)

    def _rotate(self) -> None:
        self._fh.close()
        os.replace(self.path, self.path + ".1")
        self._fh = open(self.path, "ab")

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:  # pragma: no cover
            pass

    # -- read path ---------------------------------------------------------

    def _load(self, cap: int) -> None:
        from ..server.snapshot import _HEADER, MAGIC, unframe

        events: list[dict] = []
        for path in (self.path + ".1", self.path):
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                continue
            off = 0
            torn_here = False
            while off < len(data):
                # the length field sits right after the frame magic —
                # derived from the imported constants so the framing
                # has exactly one definition (server/snapshot.py)
                length = int.from_bytes(
                    data[off + len(MAGIC):off + len(MAGIC) + 8], "little")
                payload = unframe(data[off:off + _HEADER + length])
                if payload is None:
                    # torn/corrupt frame: distrust everything after it
                    self.torn += 1
                    torn_here = True
                    break
                try:
                    event = json.loads(payload)
                except ValueError:
                    self.torn += 1
                    torn_here = True
                    break
                event["recovered"] = True
                events.append(event)
                off += _HEADER + length
            if torn_here and path == self.path:
                # THIS life appends to this file: truncate the torn
                # tail first, or everything we write lands after
                # garbage and the NEXT boot's scan (which stops at the
                # first bad frame) would silently discard this whole
                # life's forensics
                try:
                    with open(path, "r+b") as f:
                        f.truncate(off)
                except OSError:  # pragma: no cover - disk trouble
                    pass
        self.recovered = events[-cap:]

    def events(self) -> list[dict]:
        """Recovered (previous lives) then live events, in order."""
        return self.recovered + list(self._live)

    def summary(self) -> dict:
        return {"path": self.path, "recovered_events": len(self.recovered),
                "live_events": len(self._live), "torn_frames": self.torn}


# ---------------------------------------------------------------------------
# the doctor: cross-member correlation into a root-cause report
# ---------------------------------------------------------------------------

#: detector -> the cause phrasing the doctor report uses when the
#: finding explains a commit stall on the same group
_CAUSE_PHRASES = {
    "fsync_spike": "slow disk (fsync spike)",
    "window_collapse": "replication window collapsed",
    "leader_churn": "election instability (leader churn)",
}

#: the retained series the doctor's retrospective scans for anomaly
#: onsets — the families whose "when did it start" answers root-cause
#: questions (lag/elections = consensus, latency./repl. = data plane,
#: slo. = the burn itself)
_RETRO_PREFIXES = ("raft_commit_lag", "raft_elections_started",
                   "latency.", "repl.", "slo.")


def _member_label(member: str, payload: dict | None) -> str:
    """A member's stable identity: the raft node address its ``/health``
    payload self-reports (the label every OTHER member's evidence uses
    — peer lists, leader fields), falling back to the fetch address."""
    return ((payload or {}).get("health") or {}).get("node") or member


def _member_findings(members: dict[str, dict]) -> list[dict]:
    """Flatten every member's ``/health`` payload into rows of
    ``{member, detector, group, severity, reason}`` (non-ok only),
    labeled by node identity so cross-member evidence (a leader's
    pinned-peer list) matches."""
    rows: list[dict] = []
    for key, payload in members.items():
        member = _member_label(key, payload)
        health = (payload or {}).get("health") or {}
        for name, entry in (health.get("detectors") or {}).items():
            for scope, info in (entry.get("groups") or {}).items():
                if info.get("status", OK) == OK:
                    continue
                rows.append({
                    "member": member, "detector": name,
                    "group": None if scope == "server" else int(scope),
                    "severity": info.get("status"),
                    "reason": info.get("reason", ""),
                    "evidence": info.get("evidence", {}),
                })
    return rows


def _invariant_counts(members: dict[str, dict]) -> dict[str, int]:
    """Total invariant violations per member from its ``/stats``
    snapshot (server ``repl.invariant_violations`` + every
    ``device.invariant_violations{kind=}`` series)."""
    out: dict[str, int] = {}
    for key, payload in members.items():
        member = _member_label(key, payload)
        total = 0
        stats = (payload or {}).get("stats") or {}
        raft = stats.get("raft") or {}
        for key, value in raft.items():
            if key.startswith("repl.invariant_violations") \
                    and isinstance(value, (int, float)):
                total += int(value)
        device = ((stats.get("manager") or {}).get("device")
                  or {}) if isinstance(stats.get("manager"), dict) else {}
        for key, value in device.items():
            if key.startswith("device.invariant_violations") \
                    and isinstance(value, (int, float)):
                total += int(value)
        if total:
            out[member] = total
    return out


def assemble_doctor_report(members: dict[str, dict],
                           failed_members: Iterable[str] = (),
                           slowest_traces: list | None = None) -> dict:
    """Correlate the per-member payloads into one root-cause report.

    ``members`` maps a member address to
    ``{"health": <//health JSON>, "flight": <//flight JSON>,
    "stats": <//stats JSON>}`` (any value may be ``None`` when that
    route failed); addresses whose fan-out failed entirely go in
    ``failed_members`` and mark the report ``incomplete=true`` with
    reasons — mirroring the trace assembly's semantics, partial reports
    render, never drop.

    The correlation: every commit stall is matched with candidate
    causes from the SAME group on ANY member (fsync spikes = disk,
    window collapse = replication, leader churn = elections,
    unreachable members = partition), crash recoveries surface the
    black-box events leading up to death, and invariant-counter
    violations always rank first.
    """
    failed = sorted(set(failed_members))
    rows = _member_findings(members)
    causes: list[dict] = []

    # stall notes from the profiling plane: recent loop_stall flight /
    # black-box events per member, heaviest hold first — the "which
    # code held the loop" evidence the commit_stall / fsync_spike
    # causes cite below when the notes fall inside the report's
    # lookback (~2 profile windows)
    stall_notes: dict[str, list[dict]] = {}
    now = time.time()
    for key, payload in sorted(members.items()):
        member = _member_label(key, payload)
        flight = (payload or {}).get("flight") or {}
        events = list(flight.get("events", ()))
        events += ((flight.get("blackbox") or {}).get("events") or [])
        notes = [e for e in events
                 if e.get("kind") == "loop_stall"
                 and not e.get("recovered")
                 and isinstance(e.get("t"), (int, float))
                 and now - e["t"] <= 240.0]
        if notes:
            notes.sort(key=lambda e: -float(e.get("hold_ms", 0.0)))
            stall_notes[member] = notes

    # 1. invariant violations: a safety counter that moved outranks any
    #    performance symptom
    for member, count in sorted(_invariant_counts(members).items()):
        causes.append({
            "severity": CRITICAL, "group": None,
            "symptom": f"{count} invariant violation(s) on {member}",
            "cause": "safety invariant violated — inspect /flight on "
                     f"{member}",
            "members": [member], "detectors": ["invariants"],
        })

    # 2. commit stalls, matched with same-group causes across members
    stalls = [r for r in rows if r["detector"] == "commit_stall"]
    explained: set[tuple] = set()
    for stall in stalls:
        g = stall["group"]
        support = [r for r in rows
                   if r["group"] == g and r["detector"] in _CAUSE_PHRASES]
        cause_bits: list[str] = []
        cause_members: list[str] = [stall["member"]]
        cause_detectors = ["commit_stall"]
        for r in support:
            phrase = _CAUSE_PHRASES.get(r["detector"])
            if phrase is None:
                continue
            cause_bits.append(f"{r['member']}: {phrase} — {r['reason']}")
            cause_members.append(r["member"])
            cause_detectors.append(r["detector"])
            explained.add((r["member"], r["detector"], r["group"]))
        if failed:
            cause_bits.append(
                "unreachable member(s) " + ", ".join(failed)
                + " (partition or crash)")
        causes.append({
            "severity": stall["severity"], "group": g,
            "symptom": f"group {g} {stall['reason']} "
                       f"(on {stall['member']})",
            "cause": ("; ".join(cause_bits) if cause_bits
                      else "no co-located cause found — suspect quorum "
                           "loss (partition) or a dead peer"),
            "members": sorted(set(cause_members)),
            "detectors": sorted(set(cause_detectors)),
        })
        explained.add((stall["member"], "commit_stall", g))

    # 3. replication-window collapses matched with the slow peer's own
    #    fsync findings: "replication to X collapsed — X reports a
    #    fsync spike (disk)" is the cross-member attribution a single
    #    member's /stats can never make
    for r in rows:
        if r["detector"] != "window_collapse" \
                or (r["member"], "window_collapse", r["group"]) in explained:
            continue
        pinned_peers = set(r.get("evidence", {}).get("peers", ()))
        disk = [f for f in rows
                if f["detector"] == "fsync_spike"
                and f["member"] in pinned_peers]
        if not disk:
            continue
        bits = [f"{f['member']}: fsync spike (disk) — {f['reason']}"
                for f in disk]
        causes.append({
            "severity": worst([r["severity"]]
                              + [f["severity"] for f in disk]),
            "group": r["group"],
            "symptom": f"group {r['group']} replication collapsed on "
                       f"{r['member']}: {r['reason']}",
            "cause": "; ".join(bits),
            "members": sorted({r["member"]} | {f["member"] for f in disk}),
            "detectors": ["window_collapse", "fsync_spike"],
        })
        explained.add((r["member"], "window_collapse", r["group"]))
        for f in disk:
            explained.add((f["member"], "fsync_spike", f["group"]))

    # 4. unreachable members are a symptom in their own right (crash,
    #    partition, or a dead stats listener), not just missing data
    for member in failed:
        causes.append({
            "severity": WARN, "group": None,
            "symptom": f"{member} unreachable",
            "cause": "member crashed, partitioned away, or its stats "
                     "listener is down — the report is missing its "
                     "side of the story",
            "members": [member], "detectors": ["fanout"],
        })

    # 5. crash recoveries: a member whose flight ring carries recovered
    #    black-box events died recently — surface what preceded death
    for key, payload in sorted(members.items()):
        member = _member_label(key, payload)
        flight = (payload or {}).get("flight") or {}
        bb = flight.get("blackbox") or {}
        recovered = [e for e in flight.get("events", ())
                     if e.get("recovered")] or bb.get("recovered", [])
        if not recovered:
            continue
        tail = recovered[-3:]
        kinds = ", ".join(e.get("kind", "?") for e in tail)
        causes.append({
            "severity": WARN, "group": None,
            "symptom": f"{member} recovered from a crash "
                       f"({len(recovered)} black-box events from the "
                       f"previous life)",
            "cause": f"black-box tail before death: {kinds}",
            "members": [member], "detectors": ["blackbox"],
            "events": tail,
        })

    # 6. remaining standalone findings (churn with no stall, expiry
    #    storms, snapshot failures, ingress backlog...)
    for r in rows:
        if (r["member"], r["detector"], r["group"]) in explained:
            continue
        if r["detector"] == "commit_stall":
            continue
        where = f"group {r['group']}" if r["group"] is not None \
            else "server"
        causes.append({
            "severity": r["severity"], "group": r["group"],
            "symptom": f"{r['member']} {where}: {r['reason']}",
            "cause": {"leader_churn":
                      "election instability — check connectivity "
                      "between members and election timeouts",
                      "fsync_spike": "slow disk on this member",
                      "window_collapse":
                      "slow or unreachable follower(s)",
                      "session_expiry":
                      "clients dying or keep-alives not landing",
                      "snapshot_failure":
                      "snapshot plane degraded — recovery will replay",
                      "ingress_backlog":
                      "group leaders saturated or unreachable from "
                      "this ingress",
                      "slo_burn":
                      "SLO error budget burning faster than the "
                      "objective allows — see the retained window "
                      "(doctor --last N / copycat-tpu timeline)",
                      "loop_stall":
                      "synchronous code holding the event loop — the "
                      "cited frame blocked heartbeats, elections and "
                      "appends alike (copycat-tpu profile for the "
                      "full flame)"
                      }.get(r["detector"], r["detector"]),
            "members": [r["member"]], "detectors": [r["detector"]],
        })

    # 7. members whose status is not a graded severity — "disabled"
    #    (COPYCAT_HEALTH=0) or "unknown" (health route unreadable) —
    #    must not read as healthy: zero checks ran there, so a stalled
    #    cluster would render a clean OK verdict
    statuses = {key: ((m or {}).get("health") or {})
                .get("status", "unknown") for key, m in members.items()}
    for key, status in sorted(statuses.items()):
        if status in _RANK:
            continue
        member = _member_label(key, members.get(key))
        causes.append({
            "severity": WARN, "group": None,
            "symptom": f"{member} health status {status!r}",
            "cause": "no detectors ran on this member (health plane "
                     "disabled or /health unreadable) — its side of "
                     "the story is ungraded, not healthy",
            "members": [member], "detectors": ["health_plane"],
        })

    # the profiling plane's citation: a commit stall or fsync spike
    # whose members carry stall notes inside the lookback gets the top
    # holding frames attached — symptom, disk and the blocking CODE in
    # one cause row (what no single detector can say alone)
    for c in causes:
        if not set(c["detectors"]) & {"commit_stall", "fsync_spike"}:
            continue
        frames = []
        for m in c["members"]:
            for note in stall_notes.get(m, ())[:3]:
                frames.append({"member": m,
                               "frame": note.get("frame", "?"),
                               "hold_ms": note.get("hold_ms")})
        if frames:
            c["profile_frames"] = frames

    causes.sort(key=lambda c: -_RANK.get(c["severity"], 0))
    verdict = worst(s for s in statuses.values() if s in _RANK)
    if causes:
        verdict = worst([verdict] + [c["severity"] for c in causes])
    report = {
        "verdict": verdict,
        "members": sorted(members),
        "incomplete": bool(failed),
        "incomplete_why": [f"member {m} unreachable" for m in failed],
        "causes": causes,
        "member_status": {_member_label(m, p):
                          ((p or {}).get("health") or {})
                          .get("status", "unknown")
                          for m, p in sorted(members.items())},
    }
    if slowest_traces:
        report["slowest_traces"] = [
            {"trace": t.get("trace"), "total_ms": t.get("total_ms")}
            for t in slowest_traces[:3]]

    # 8. retrospective (doctor --last N): members whose payloads carry a
    #    retained /series window get their anomaly ONSETS scanned —
    #    "commit lag started climbing 40 s ago" time-correlates the
    #    causes above instead of only grading the present. Members
    #    without series (plane off, pre-series build, no --last) simply
    #    contribute nothing — the section is additive, never required.
    retrospect: dict[str, list] = {}
    for key, payload in sorted(members.items()):
        series = (payload or {}).get("series")
        if not series:
            continue
        onsets = series_onsets(series, _RETRO_PREFIXES)
        if onsets:
            retrospect[_member_label(key, payload)] = onsets
    if retrospect:
        report["retrospect"] = retrospect
        for c in causes:
            notes = []
            for m in c["members"]:
                for o in retrospect.get(m, ())[:2]:
                    start = ("window start"
                             if o.get("from_window_start")
                             else f"{o['ago_s']:.0f}s ago")
                    notes.append(f"{m}: {o['key']} rose to "
                                 f"{o['value']:g} from {start} "
                                 f"(window median {o['median']:g})")
            if notes:
                c["retrospect"] = notes
    return report


def render_doctor_report(report: dict) -> str:
    """The human rendering: verdict banner, per-member one-liners, then
    the ranked root-cause list (incomplete reports carry a loud banner
    — rendered, never dropped)."""
    lines = [f"cluster verdict: {report['verdict'].upper()} "
             f"across {len(report['members'])} member(s)"]
    if report["incomplete"]:
        lines.append("!! INCOMPLETE: "
                     + "; ".join(report["incomplete_why"]))
    for member, status in report["member_status"].items():
        lines.append(f"  {member:<24} {status}")
    if not report["causes"]:
        lines.append("no anomalies detected")
    for i, c in enumerate(report["causes"], 1):
        g = f" [group {c['group']}]" if c.get("group") is not None else ""
        lines.append(f"{i}. {c['severity'].upper()}{g} {c['symptom']}")
        lines.append(f"   cause: {c['cause']}")
        for f in c.get("profile_frames", ()):
            hold = f.get("hold_ms")
            held = f" ({hold:g} ms)" if isinstance(hold, (int, float)) \
                else ""
            lines.append(f"   held by: {f['member']}: "
                         f"{f['frame']}{held}")
        for note in c.get("retrospect", ()):
            lines.append(f"   onset: {note}")
    for t in report.get("slowest_traces", ()):
        lines.append(f"   slow trace {t['trace']}: {t['total_ms']} ms")
    retrospect = report.get("retrospect") or {}
    if retrospect:
        lines.append("retrospective (retained series onsets):")
        for member, onsets in retrospect.items():
            for o in onsets:
                start = ("breaching since window start"
                         if o.get("from_window_start")
                         else f"started {o['ago_s']:.0f}s ago")
                lines.append(f"  {member:<24} {o['key']} -> "
                             f"{o['value']:g} ({start}; window median "
                             f"{o['median']:g})")
    return "\n".join(lines)
