"""First-class metrics: counters + latency histograms (ops/sec, p99).

The reference ships no metrics registry (SURVEY.md §5.5 — "build
obligation: add ops/sec + p99 commit latency counters as first-class";
they are BASELINE.json's headline metric). Host-side and dependency-free:
device code stays pure, the driver feeds the registry.
"""

from __future__ import annotations

import random
import time


class Counter:
    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Histogram:
    """Reservoir-sampled value distribution with exact count/sum."""

    def __init__(self, reservoir: int = 65536, seed: int = 0) -> None:
        self._values: list[float] = []
        self._reservoir = reservoir
        self._rng = random.Random(seed)
        self.count = 0
        self.sum = 0.0

    def record(self, value: float) -> None:
        self.count += 1
        self.sum += value
        if len(self._values) < self._reservoir:
            self._values.append(value)
        else:
            i = self._rng.randrange(self.count)
            if i < self._reservoir:
                self._values[i] = value

    def percentile(self, p: float) -> float:
        if not self._values:
            return 0.0
        vals = sorted(self._values)
        idx = min(len(vals) - 1, int(p / 100.0 * len(vals)))
        return vals[idx]

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0


class Timer:
    """Context manager recording elapsed milliseconds into a histogram."""

    def __init__(self, hist: Histogram) -> None:
        self._hist = hist

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._hist.record((time.perf_counter() - self._t0) * 1e3)
        return False


class MetricsRegistry:
    """Named counters and histograms with a JSON-able snapshot."""

    def __init__(self) -> None:
        self._counters: dict[str, Counter] = {}
        self._histograms: dict[str, Histogram] = {}
        self._t0 = time.perf_counter()

    def counter(self, name: str) -> Counter:
        return self._counters.setdefault(name, Counter())

    def histogram(self, name: str) -> Histogram:
        return self._histograms.setdefault(name, Histogram())

    def timer(self, name: str) -> Timer:
        return Timer(self.histogram(name))

    def rate(self, name: str) -> float:
        """Events/sec for a counter since registry creation."""
        dt = time.perf_counter() - self._t0
        return self._counters[name].value / dt if dt > 0 else 0.0

    def snapshot(self) -> dict:
        out: dict = {"uptime_s": round(time.perf_counter() - self._t0, 3)}
        for name, ctr in self._counters.items():
            out[name] = ctr.value
        for name, h in self._histograms.items():
            out[name] = {
                "count": h.count,
                "mean": round(h.mean, 4),
                "p50": round(h.percentile(50), 4),
                "p99": round(h.percentile(99), 4),
            }
        return out
