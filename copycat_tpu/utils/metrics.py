"""First-class metrics: counters, gauges + latency histograms (ops/sec, p99).

The reference ships no metrics registry (SURVEY.md §5.5 — "build
obligation: add ops/sec + p99 commit latency counters as first-class";
they are BASELINE.json's headline metric). Host-side and dependency-free:
device code stays pure, the driver feeds the registry.

The observability plane (docs/OBSERVABILITY.md) builds on three pieces
here:

- **labels**: ``registry.counter("frames_in", direction="rx")`` keys the
  metric by ``(name, labels)``; snapshots flatten to
  ``frames_in{direction=rx}`` so per-node/per-lane series coexist in one
  registry.
- **merge**: ``registry.merge(other, node="5001")`` folds another
  registry in (counters add, gauges overwrite, histogram reservoirs
  combine), optionally stamping extra labels — how per-transport and
  per-client registries roll up into one server snapshot.
  ``merge_snapshots`` does the lossier JSON-level equivalent for
  snapshots collected from other processes.
- **renderers**: ``render_prometheus()`` (text exposition format) and
  ``render_json()`` feed the ``/metrics`` stats listener
  (``server/stats.py``) and ``copycat-tpu stats``.
"""

from __future__ import annotations

import json
import random
import time

_EMPTY_LABELS: tuple = ()


def _key(name: str, labels: dict) -> tuple[str, tuple]:
    return (name, tuple(sorted(labels.items())) if labels else _EMPTY_LABELS)


def _flat(key: tuple[str, tuple]) -> str:
    name, labels = key
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in labels)
    return f"{name}{{{inner}}}"


class Counter:
    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """A point-in-time value (term, commit index, open sessions, queue
    depth): set/inc/dec, last write wins."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = value

    def inc(self, n: float = 1) -> None:
        self.value += n

    def dec(self, n: float = 1) -> None:
        self.value -= n


class Histogram:
    """Reservoir-sampled value distribution with exact count/sum."""

    def __init__(self, reservoir: int = 65536, seed: int = 0) -> None:
        self._values: list[float] = []
        self._reservoir = reservoir
        self._rng = random.Random(seed)
        self.count = 0
        self.sum = 0.0
        # exact running max (like count/sum): the reservoir can evict
        # the worst sample, and "max" exists to surface outliers
        self.max_value = 0.0

    def record(self, value: float) -> None:
        self.count += 1
        self.sum += value
        if self.count == 1 or value > self.max_value:
            self.max_value = value
        if len(self._values) < self._reservoir:
            self._values.append(value)
        else:
            i = self._rng.randrange(self.count)
            if i < self._reservoir:
                self._values[i] = value

    def percentile(self, p: float) -> float:
        """Linear-interpolated percentile (numpy's default method).

        Floor-indexing biased small samples: p50 of [1..100] returned 51
        and p-anything of a 2-sample histogram snapped to an endpoint.
        Interpolating at rank ``p/100 * (n-1)`` matches what every
        reader of a "p99" expects from small reservoirs.
        """
        if not self._values:
            return 0.0
        vals = sorted(self._values)
        n = len(vals)
        if n == 1:
            return vals[0]
        rank = max(0.0, min(p, 100.0)) / 100.0 * (n - 1)
        lo = int(rank)
        hi = min(lo + 1, n - 1)
        return vals[lo] + (vals[hi] - vals[lo]) * (rank - lo)

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def merge_from(self, other: "Histogram") -> None:
        """Fold another histogram in: exact count/sum, combined
        reservoir (downsampled back to capacity if the union overflows)."""
        if other.count and (not self.count
                            or other.max_value > self.max_value):
            self.max_value = other.max_value
        self.count += other.count
        self.sum += other.sum
        combined = self._values + other._values
        if len(combined) > self._reservoir:
            combined = self._rng.sample(combined, self._reservoir)
        self._values = combined


class Timer:
    """Context manager recording elapsed milliseconds into a histogram."""

    def __init__(self, hist: Histogram) -> None:
        self._hist = hist

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._hist.record((time.perf_counter() - self._t0) * 1e3)
        return False


class MetricsRegistry:
    """Named counters, gauges and histograms with a JSON-able snapshot.

    Metrics are keyed by ``(name, sorted(labels))``; the snapshot
    flattens keys to ``name`` or ``name{k=v,...}``.
    """

    def __init__(self) -> None:
        self._counters: dict[tuple, Counter] = {}
        self._gauges: dict[tuple, Gauge] = {}
        self._histograms: dict[tuple, Histogram] = {}
        self._t0 = time.perf_counter()

    def counter(self, name: str, **labels) -> Counter:
        key = _key(name, labels)
        ctr = self._counters.get(key)
        if ctr is None:
            ctr = self._counters[key] = Counter()
        return ctr

    def gauge(self, name: str, **labels) -> Gauge:
        key = _key(name, labels)
        g = self._gauges.get(key)
        if g is None:
            g = self._gauges[key] = Gauge()
        return g

    def histogram(self, name: str, **labels) -> Histogram:
        key = _key(name, labels)
        h = self._histograms.get(key)
        if h is None:
            h = self._histograms[key] = Histogram()
        return h

    def timer(self, name: str, **labels) -> Timer:
        return Timer(self.histogram(name, **labels))

    def rate(self, name: str, **labels) -> float:
        """Events/sec for a counter since registry creation (0.0 for a
        counter that was never incremented — asking for a rate must not
        be the thing that crashes the stats surface)."""
        ctr = self._counters.get(_key(name, labels))
        if ctr is None:
            return 0.0
        dt = time.perf_counter() - self._t0
        return ctr.value / dt if dt > 0 else 0.0

    # -- aggregation -------------------------------------------------------

    def merge(self, other: "MetricsRegistry", **extra_labels) -> None:
        """Fold ``other`` into this registry: counters add, gauges
        overwrite, histograms combine reservoirs. ``extra_labels`` are
        stamped onto every merged key — the cluster roll-up idiom:
        ``total.merge(node_registry, node="5001")``."""

        def rekey(key: tuple) -> tuple:
            if not extra_labels:
                return key
            name, labels = key
            merged = dict(labels)
            merged.update(extra_labels)
            return _key(name, merged)

        for key, ctr in other._counters.items():
            name, labels = rekey(key)
            self.counter(name, **dict(labels)).inc(ctr.value)
        for key, g in other._gauges.items():
            name, labels = rekey(key)
            self.gauge(name, **dict(labels)).set(g.value)
        for key, h in other._histograms.items():
            name, labels = rekey(key)
            self.histogram(name, **dict(labels)).merge_from(h)

    # -- exposition --------------------------------------------------------

    def snapshot(self) -> dict:
        out: dict = {"uptime_s": round(time.perf_counter() - self._t0, 3)}
        for key, ctr in self._counters.items():
            out[_flat(key)] = ctr.value
        if self._gauges:
            # gauges are indistinguishable from counters once flattened
            # to JSON; the hint lets merge_snapshots keep them point-in-
            # time (max) instead of summing them into nonsense
            out["_gauge_keys"] = [_flat(k) for k in self._gauges]
        for key, g in self._gauges.items():
            out[_flat(key)] = g.value
        for key, h in self._histograms.items():
            out[_flat(key)] = {
                "count": h.count,
                "mean": round(h.mean, 4),
                "p50": round(h.percentile(50), 4),
                "p99": round(h.percentile(99), 4),
                "max": round(h.max_value, 4) if h.count else 0.0,
            }
        return out

    def render_json(self) -> str:
        return json.dumps(self.snapshot())

    def render_prometheus(self, namespace: str = "copycat") -> str:
        """Prometheus text exposition format (counters/gauges as-is,
        histograms as summaries with p50/p99 quantile samples)."""
        lines: list[str] = []

        def sample(name: str, labels: tuple, value, extra: dict | None = None):
            all_labels = dict(labels)
            if extra:
                all_labels.update(extra)
            if all_labels:
                inner = ",".join(f'{_sanitize(k)}="{v}"'
                                 for k, v in sorted(all_labels.items()))
                lines.append(f"{name}{{{inner}}} {value}")
            else:
                lines.append(f"{name} {value}")

        for (name, labels), ctr in self._counters.items():
            metric = f"{namespace}_{_sanitize(name)}"
            lines.append(f"# TYPE {metric} counter")
            sample(metric, labels, ctr.value)
        for (name, labels), g in self._gauges.items():
            metric = f"{namespace}_{_sanitize(name)}"
            lines.append(f"# TYPE {metric} gauge")
            sample(metric, labels, g.value)
        for (name, labels), h in self._histograms.items():
            metric = f"{namespace}_{_sanitize(name)}"
            lines.append(f"# TYPE {metric} summary")
            sample(metric, labels, h.percentile(50), {"quantile": "0.5"})
            sample(metric, labels, h.percentile(99), {"quantile": "0.99"})
            sample(f"{metric}_count", labels, h.count)
            sample(f"{metric}_sum", labels, h.sum)
        return "\n".join(lines) + "\n"


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def merge_snapshots(snaps: list[dict]) -> dict:
    """JSON-level merge of :meth:`MetricsRegistry.snapshot` dicts from
    OTHER processes (no reservoirs to combine): counters sum; gauges
    (identified by the snapshot's ``_gauge_keys`` hint) take the max —
    summing a per-node ``raft_term`` or ``raft_is_leader`` would
    fabricate values; histogram entries merge with exact count/weighted
    mean and worst-case (max) percentiles — an upper bound, honest for
    alerting."""
    gauge_keys: set = set()
    for snap in snaps:
        gauge_keys.update(snap.get("_gauge_keys", ()))
    out: dict = {}
    if gauge_keys:
        out["_gauge_keys"] = sorted(gauge_keys)
    for snap in snaps:
        for key, val in snap.items():
            if key == "_gauge_keys":
                continue
            if key == "uptime_s" or key in gauge_keys:
                out[key] = max(out.get(key, 0.0), val)
            elif isinstance(val, dict):
                cur = out.get(key)
                if cur is None:
                    out[key] = dict(val)
                else:
                    n = cur["count"] + val["count"]
                    if n:
                        cur["mean"] = round(
                            (cur["mean"] * cur["count"]
                             + val["mean"] * val["count"]) / n, 4)
                    cur["count"] = n
                    for q in ("p50", "p99", "max"):
                        cur[q] = max(cur.get(q, 0.0), val.get(q, 0.0))
            else:
                out[key] = out.get(key, 0) + val
    return out
