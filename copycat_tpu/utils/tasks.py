"""Background task spawning with strong references.

``loop.create_task`` holds only a weak reference: a fire-and-forget task can be
garbage-collected mid-execution. ``spawn`` keeps tasks alive until done and
logs unexpected exceptions.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Coroutine

logger = logging.getLogger(__name__)

_BACKGROUND: set[asyncio.Task] = set()


def spawn(coro: Coroutine[Any, Any, Any], name: str | None = None) -> asyncio.Task:
    task = asyncio.get_running_loop().create_task(coro, name=name)
    _BACKGROUND.add(task)
    task.add_done_callback(_finish)
    return task


def _finish(task: asyncio.Task) -> None:
    _BACKGROUND.discard(task)
    if task.cancelled():
        return
    exc = task.exception()
    if exc is not None:
        # exc_info keeps the traceback in the log record: background
        # failures have no awaiter to re-raise into, so this line is
        # the only place the stack ever surfaces
        logger.error("background task %s failed: %r", task.get_name(),
                     exc, exc_info=exc)
