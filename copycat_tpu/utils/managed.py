"""Async open/close lifecycle (Catalyst ``Managed<T>`` equivalent).

Consumed by the reference as ``Managed{open(), isOpen(), close(), isClosed()}``
returning ``CompletableFuture`` (SURVEY.md §2.3); here ``open``/``close`` are
coroutines."""

from __future__ import annotations

import abc
import asyncio
from typing import Any


class Managed(abc.ABC):
    """A resource with an async open/close lifecycle."""

    def __init__(self) -> None:
        self._open = False
        self._lifecycle_lock: asyncio.Lock | None = None

    def _lock(self) -> asyncio.Lock:
        if self._lifecycle_lock is None:
            self._lifecycle_lock = asyncio.Lock()
        return self._lifecycle_lock

    async def open(self) -> "Managed":
        async with self._lock():
            if not self._open:
                await self._do_open()
                self._open = True
        return self

    async def close(self) -> None:
        async with self._lock():
            if self._open:
                self._open = False
                await self._do_close()

    @property
    def is_open(self) -> bool:
        return self._open

    @property
    def is_closed(self) -> bool:
        return not self._open

    async def _do_open(self) -> None:  # pragma: no cover - default no-op
        pass

    async def _do_close(self) -> None:  # pragma: no cover - default no-op
        pass

    async def __aenter__(self) -> Any:
        return await self.open()

    async def __aexit__(self, *exc: object) -> None:
        await self.close()
