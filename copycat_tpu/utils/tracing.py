"""Per-request tracing: a trace id carried from client submit through
log append, commit/apply, and response (SURVEY.md §5.1 names tracing a
build obligation; the XLA profiler in :mod:`profiling` covers the device
plane — this covers the host request path).

Design constraints, in order:

1. **Zero overhead when disabled.** The hot path (client submit, server
   command handlers) does ONE attribute read (``TRACER.enabled``) and
   branches away. No span objects, no clock reads, no dict lookups.
   Verified by the spi bench A/B in PERF.md.
2. **Propagation rides the existing frames.** ``CommandRequest`` /
   ``CommandBatchRequest`` grew a trailing ``trace`` field
   (``protocol/messages.py``); it is ``None`` when tracing is off, and a
   server records spans whenever a request carries a non-None id — the
   client's flag IS the propagation switch, so a traced client against
   an untouched server config still yields server-side spans.
3. **Bounded storage.** Completed spans land in a per-process ring
   (``capacity`` traces, oldest evicted); :meth:`Tracer.dump_slowest`
   renders the slowest N requests as text or JSON.

Usage::

    from copycat_tpu.utils import tracing

    tracing.enable()                  # or COPYCAT_TRACE=1 in the env
    ... drive requests ...
    print(tracing.TRACER.dump_slowest(5))

Span semantics (one trace per wire request; names are stable API,
documented in docs/OBSERVABILITY.md):

- ``client.submit`` — client-side, submit flush -> responses correlated
  (includes connect/retry time).
- ``server.append`` — server receipt -> log append staged (meta:
  ``index``, ``n`` entries).
- ``server.commit`` — append -> commit future resolved (replication +
  quorum + APPLY: the entry's state-machine application completes
  before its future resolves).
- ``server.respond`` — commit -> response object built (event gating).
"""

from __future__ import annotations

import itertools
import json
import time
from collections import OrderedDict
from typing import Any

from . import knobs

_ids = itertools.count(1)


class Span:
    __slots__ = ("trace_id", "name", "start", "end", "meta")

    def __init__(self, trace_id: int, name: str, start: float, end: float,
                 meta: dict | None = None) -> None:
        self.trace_id = trace_id
        self.name = name
        self.start = start
        self.end = end
        self.meta = meta

    @property
    def duration_ms(self) -> float:
        return (self.end - self.start) * 1e3

    def as_dict(self) -> dict:
        d = {"trace": self.trace_id, "name": self.name,
             "start": round(self.start, 6),
             "duration_ms": round(self.duration_ms, 3)}
        if self.meta:
            d.update(self.meta)
        return d

    def __repr__(self) -> str:
        return (f"Span({self.name} trace={self.trace_id} "
                f"{self.duration_ms:.3f}ms)")


class Tracer:
    """Ring-buffered span storage keyed by trace id.

    ``enabled`` is a plain attribute so the disabled check costs one
    LOAD_ATTR; every recording entry point re-checks nothing else.
    """

    #: hard cap on spans recorded per trace id: a request produces ~5,
    #: so the cap only bites a peer replaying one id forever — without
    #: it that would grow a server-side list without bound (spans are
    #: recorded for ANY non-None wire id, even with local tracing off)
    MAX_SPANS_PER_TRACE = 64

    def __init__(self, capacity: int = 512) -> None:
        self.enabled = False
        self.capacity = capacity
        self._traces: "OrderedDict[int, list[Span]]" = OrderedDict()

    # -- recording ---------------------------------------------------------

    def new_trace(self) -> int:
        """A fresh trace id (call only when ``enabled`` — callers branch
        on the attribute first; ids are process-unique, not global)."""
        return next(_ids)

    def span(self, trace_id: int, name: str, start: float, end: float,
             **meta: Any) -> None:
        """Record one completed span under ``trace_id``.

        Explicit timestamps fit the async call sites (the caller already
        holds t0 from before its awaits). Accepts any trace id —
        including one minted by a REMOTE client and carried in a frame.
        """
        spans = self._traces.get(trace_id)
        if spans is None:
            if len(self._traces) >= self.capacity:
                self._traces.popitem(last=False)
            spans = self._traces[trace_id] = []
        if len(spans) < self.MAX_SPANS_PER_TRACE:
            spans.append(Span(trace_id, name, start, end, meta or None))

    # -- reading -----------------------------------------------------------

    def traces(self) -> dict[int, list[Span]]:
        return dict(self._traces)

    def spans_for(self, trace_id: int) -> list[Span]:
        return list(self._traces.get(trace_id, ()))

    def slowest(self, n: int = 10) -> list[tuple[int, float, list[Span]]]:
        """The slowest ``n`` traces as ``(trace_id, total_ms, spans)``,
        total = wall span from first start to last end."""
        scored = []
        for trace_id, spans in self._traces.items():
            total = (max(s.end for s in spans)
                     - min(s.start for s in spans)) * 1e3
            scored.append((trace_id, total, spans))
        scored.sort(key=lambda t: t[1], reverse=True)
        return scored[:n]

    def dump_slowest(self, n: int = 10, as_json: bool = False) -> str:
        slow = self.slowest(n)
        if as_json:
            return json.dumps([
                {"trace": trace_id, "total_ms": round(total, 3),
                 "spans": [s.as_dict() for s in spans]}
                for trace_id, total, spans in slow])
        lines = []
        for trace_id, total, spans in slow:
            lines.append(f"trace {trace_id}: {total:.3f} ms total")
            t0 = min(s.start for s in spans)
            for s in sorted(spans, key=lambda s: s.start):
                meta = (" " + " ".join(f"{k}={v}" for k, v in s.meta.items())
                        if s.meta else "")
                lines.append(f"  +{(s.start - t0) * 1e3:8.3f} ms "
                             f"{s.name:<16} {s.duration_ms:8.3f} ms{meta}")
        return "\n".join(lines) if lines else "(no traces recorded)"

    def clear(self) -> None:
        self._traces.clear()


#: the per-process tracer every layer records into (client + server in
#: one process share it, so in-process tests see end-to-end traces; over
#: TCP each process keeps its own ring, correlated by trace id).
TRACER = Tracer()

if knobs.get_bool("COPYCAT_TRACE"):
    TRACER.enabled = True


def enable() -> None:
    TRACER.enabled = True


def disable() -> None:
    TRACER.enabled = False


def now() -> float:
    return time.perf_counter()
