"""Cluster-wide causal tracing: one trace id carried from the client
submit across every member a request touches (SURVEY.md §5.1 names
tracing a build obligation; the XLA profiler in :mod:`profiling` covers
the device plane — this covers the host request path, now including the
multi-group ingress/proxy/replication hops of docs/SHARDING.md).

Design constraints, in order:

1. **Zero overhead when disabled.** The hot path (client submit, server
   command handlers, the replication window stager, the apply loop) does
   ONE attribute read (``TRACER.enabled`` / ``request.trace is None`` /
   an empty-dict truthiness check) and branches away. No span objects,
   no clock reads, no dict lookups. Verified by the spi + sharded bench
   A/Bs in PERF.md (rounds 7 and 13).
2. **Propagation rides the existing frames — invisibly when off.**
   ``CommandRequest`` / ``CommandBatchRequest`` carry ``trace`` as a
   regular field (PR 2); the cross-member hops added since ride
   *optional trailing* fields on ``ProxyRequest`` / ``ProxyResponse`` /
   ``AppendRequest`` / ``PublishRequest`` (protocol/messages.py): the
   field is OMITTED from the wire when ``None``, so with tracing off
   every frame is byte-identical to the pre-tracing plane (the golden
   differential in tests/test_trace_plane.py proves it). The client's
   flag IS the propagation switch: a traced client against untouched
   server configs still yields spans on every member the request
   crossed.
3. **Bounded storage.** Completed spans land in a per-process ring
   (``COPYCAT_TRACE_CAPACITY`` traces, oldest evicted; evicted ids are
   TOMBSTONED so a late remote span can never resurrect a partial
   trace); :meth:`Tracer.dump_slowest` renders the slowest N requests.

Usage::

    from copycat_tpu.utils import tracing

    tracing.enable()                  # or COPYCAT_TRACE=1 in the env
    ... drive requests ...
    print(tracing.TRACER.dump_slowest(5))

Span-name vocabulary (stable API, documented with the phase→histogram
mapping in docs/OBSERVABILITY.md):

- ``client.submit`` — client-side, submit flush -> responses correlated.
- ``ingress.queue`` — multi-group ingress: request receipt -> the
  routed sub-block's dispatch chain released it.
- ``proxy.hop`` — ingress -> owning group leader wire round trip (one
  span per attempt; failed attempts carry ``error=`` meta).
- ``group.append`` — owning leader: receipt -> log append staged.
- ``quorum.wait`` — append staged -> commit index covered the entry.
- ``group.fsync`` — the commit-boundary fsync that made it durable.
- ``apply`` — commit -> state-machine application / engine round done
  (the commit future resolved).
- ``respond`` — apply -> response object built.
- ``group.commit`` — coarse append->commit+apply span on the per-seq
  lanes (single command / general batch), where the commit index is
  not known at staging time.
- ``group.cached`` — exactly-once cache hit served without an append.
- ``follower.append`` — a follower ingesting the replication window
  that carried the traced entry (fsync included).
- ``event.push`` — session event delivery send -> ack.
- ``client.event`` — client-side receipt/dispatch of a traced publish.

Every server-side span is tagged ``member=<address>`` and ``group=<id>``
so the cross-member assembly below can attribute phases. Spans store
``time.perf_counter()`` instants plus a per-process wall-clock anchor
(``wall`` in :meth:`Span.as_dict`): within one process alignment is
exact; across hosts it is as good as the hosts' clock sync, and the
assembly orders causally either way.
"""

from __future__ import annotations

import itertools
import json
import time
from collections import OrderedDict
from typing import Any, Iterable

from . import knobs

_ids = itertools.count(1)

#: perf_counter -> wall-clock anchor for this process: spans are
#: recorded on the monotonic clock (cheap, ordering-safe) and exported
#: with ``wall = start + _WALL_OFFSET`` so rings collected from
#: different processes can be laid on one timeline.
_WALL_OFFSET = time.time() - time.perf_counter()


class Span:
    __slots__ = ("trace_id", "name", "start", "end", "meta")

    def __init__(self, trace_id: int, name: str, start: float, end: float,
                 meta: dict | None = None) -> None:
        self.trace_id = trace_id
        self.name = name
        self.start = start
        self.end = end
        self.meta = meta

    @property
    def duration_ms(self) -> float:
        return (self.end - self.start) * 1e3

    def as_dict(self) -> dict:
        d = {"trace": self.trace_id, "name": self.name,
             "start": round(self.start, 6),
             "wall": round(self.start + _WALL_OFFSET, 6),
             "duration_ms": round(self.duration_ms, 3)}
        if self.meta:
            d.update(self.meta)
        return d

    def __repr__(self) -> str:
        return (f"Span({self.name} trace={self.trace_id} "
                f"{self.duration_ms:.3f}ms)")


class Tracer:
    """Ring-buffered span storage keyed by trace id.

    ``enabled`` is a plain attribute so the disabled check costs one
    LOAD_ATTR; every recording entry point re-checks nothing else.
    """

    #: hard cap on spans recorded per trace id: a request produces ~10
    #: across the cluster, so the cap only bites a peer replaying one id
    #: forever — without it that would grow a server-side list without
    #: bound (spans are recorded for ANY non-None wire id, even with
    #: local tracing off)
    MAX_SPANS_PER_TRACE = 64

    def __init__(self, capacity: int = 512) -> None:
        self.enabled = False
        self.capacity = capacity
        self._traces: "OrderedDict[int, list[Span]]" = OrderedDict()
        # Tombstones for recently-evicted ids: a late span (a straggler
        # ack, a replayed frame) for an evicted trace must be DROPPED,
        # not re-admitted — a resurrected entry holds a partial span
        # list that pollutes dump_slowest with nonsense totals. Bounded
        # at 2x capacity (older tombstones age out; by then the id is
        # process-ancient and a late span for it is noise either way).
        self._tombstones: "OrderedDict[int, None]" = OrderedDict()

    # -- recording ---------------------------------------------------------

    def new_trace(self) -> int:
        """A fresh trace id (call only when ``enabled`` — callers branch
        on the attribute first; ids are process-unique, not global)."""
        return next(_ids)

    def span(self, trace_id: int, name: str, start: float, end: float,
             **meta: Any) -> None:
        """Record one completed span under ``trace_id``.

        Explicit timestamps fit the async call sites (the caller already
        holds t0 from before its awaits). Accepts any trace id —
        including one minted by a REMOTE client and carried in a frame —
        except ids evicted from this ring (tombstoned: late spans for
        them are dropped, never resurrected as partial traces).
        """
        spans = self._traces.get(trace_id)
        if spans is None:
            if trace_id in self._tombstones:
                return
            if len(self._traces) >= self.capacity:
                evicted, _ = self._traces.popitem(last=False)
                self._tombstones[evicted] = None
                if len(self._tombstones) > 2 * self.capacity:
                    self._tombstones.popitem(last=False)
            spans = self._traces[trace_id] = []
        if len(spans) < self.MAX_SPANS_PER_TRACE:
            spans.append(Span(trace_id, name, start, end, meta or None))

    # -- reading -----------------------------------------------------------

    def traces(self) -> dict[int, list[Span]]:
        return dict(self._traces)

    def spans_for(self, trace_id: int) -> list[Span]:
        return list(self._traces.get(trace_id, ()))

    def slowest(self, n: int = 10) -> list[tuple[int, float, list[Span]]]:
        """The slowest ``n`` traces as ``(trace_id, total_ms, spans)``,
        total = wall span from first start to last end."""
        scored = []
        for trace_id, spans in self._traces.items():
            total = (max(s.end for s in spans)
                     - min(s.start for s in spans)) * 1e3
            scored.append((trace_id, total, spans))
        scored.sort(key=lambda t: t[1], reverse=True)
        return scored[:n]

    def dump_slowest(self, n: int = 10, as_json: bool = False) -> str:
        slow = self.slowest(n)
        if as_json:
            return json.dumps([
                {"trace": trace_id, "total_ms": round(total, 3),
                 "spans": [s.as_dict() for s in spans],
                 **({"profile": prof} if (prof := self._profile_window(
                     spans)) else {})}
                for trace_id, total, spans in slow])
        lines = []
        for trace_id, total, spans in slow:
            lines.append(f"trace {trace_id}: {total:.3f} ms total")
            t0 = min(s.start for s in spans)
            for s in sorted(spans, key=lambda s: s.start):
                meta = (" " + " ".join(f"{k}={v}" for k, v in s.meta.items())
                        if s.meta else "")
                lines.append(f"  +{(s.start - t0) * 1e3:8.3f} ms "
                             f"{s.name:<16} {s.duration_ms:8.3f} ms{meta}")
            prof = self._profile_window(spans)
            if prof:
                top = prof["stacks"][0]
                lines.append(f"  profile: {prof['samples']} sample(s) in "
                             f"the window, hottest "
                             f"{top['stack'].rsplit(';', 1)[-1]} "
                             f"(x{top['count']})")
        return "\n".join(lines) if lines else "(no traces recorded)"

    @staticmethod
    def _profile_window(spans: list) -> dict | None:
        """The continuous profiler's top stacks over this trace's wall
        window (utils/profiler.py) — a slow trace names the code the
        process was ACTUALLY running while it was slow, not just its
        own spans. Empty/absent when the plane is off or no sample
        landed in the window."""
        from . import profiler  # lazy: tracing must not require the plane

        prof = profiler.PROFILER
        if prof is None:
            return None
        try:
            w0 = min(s.start for s in spans) + _WALL_OFFSET
            w1 = max(s.end for s in spans) + _WALL_OFFSET
            window = prof.window_top(w0, w1, top=3)
            return window if window["samples"] else None
        except Exception:  # noqa: BLE001 - never wound the dump
            return None

    def clear(self) -> None:
        self._traces.clear()
        self._tombstones.clear()


#: the per-process tracer every layer records into (client + server in
#: one process share it, so in-process tests see end-to-end traces; over
#: TCP each process keeps its own ring, correlated by trace id).
TRACER = Tracer(capacity=max(16, knobs.get_int("COPYCAT_TRACE_CAPACITY")))

if knobs.get_bool("COPYCAT_TRACE"):
    TRACER.enabled = True


def enable() -> None:
    TRACER.enabled = True


def disable() -> None:
    TRACER.enabled = False


def now() -> float:
    return time.perf_counter()


# ---------------------------------------------------------------------------
# Cross-member assembly: lay the spans collected from every member's
# ring (`/traces/<id>` on the stats listener, or the shared in-process
# ring filtered by the `member` tag) on one causal timeline, decide
# completeness, and extract the critical path.
# ---------------------------------------------------------------------------

#: span names that prove a group actually served a routed sub-request —
#: the completeness check looks for one of these after every dispatch
GROUP_PHASES = frozenset((
    "group.append", "group.commit", "group.cached", "quorum.wait",
    "apply", "respond"))


def _norm_span(raw: Any) -> dict:
    """One span as an assembly row: accepts a :class:`Span` or the
    ``as_dict``/JSON shape served by ``/traces/<id>``."""
    if isinstance(raw, Span):
        d = raw.as_dict()
    else:
        d = dict(raw)
    d.setdefault("member", "client")
    d.setdefault("wall", d.get("start", 0.0))
    return d


def assemble_trace(trace_id: int, spans_by_member: dict[str, Iterable],
                   failed_members: Iterable[str] = ()) -> dict:
    """Assemble one cross-member causal timeline.

    ``spans_by_member`` maps a member label to the spans fetched from
    that member's ring (Span objects or ``/traces/<id>`` dicts); members
    whose fetch FAILED go in ``failed_members`` — their absence marks
    the assembly ``incomplete`` rather than silently dropping it.

    Returns ``{trace, members, spans, e2e_ms, incomplete,
    incomplete_why, critical_path, critical_path_ms}`` — spans sorted by
    wall start with ``offset_ms`` relative to the earliest, the critical
    path as innermost-cover segments over the full wall interval (their
    durations sum to ``e2e_ms`` by construction), and completeness
    decided both structurally (a dispatched sub-block with no group-side
    phase landed) and operationally (an unreachable member).
    """
    seen: set = set()
    spans: list[dict] = []
    for member, raw_spans in spans_by_member.items():
        for raw in raw_spans:
            d = _norm_span(raw)
            if d.get("trace") not in (None, trace_id):
                continue
            key = (d["member"], d["name"], round(d["wall"], 6),
                   d.get("duration_ms"))
            if key in seen:  # in-process rings served by N listeners
                continue
            seen.add(key)
            spans.append(d)
    failed = sorted(set(failed_members))
    if not spans:
        return {"trace": trace_id, "members": [], "spans": [],
                "e2e_ms": 0.0, "incomplete": True,
                "incomplete_why": (["no spans landed"]
                                   + [f"member {m} unreachable"
                                      for m in failed]),
                "critical_path": [], "critical_path_ms": 0.0}
    spans.sort(key=lambda d: (d["wall"], -d.get("duration_ms", 0.0)))
    t0 = spans[0]["wall"]
    t1 = max(d["wall"] + d.get("duration_ms", 0.0) / 1e3 for d in spans)
    for d in spans:
        d["offset_ms"] = round((d["wall"] - t0) * 1e3, 3)

    why: list[str] = [f"member {m} unreachable" for m in failed]
    # structural completeness: every routed dispatch must be answered by
    # a group-side phase for the same group — a proxy hop (or a queued
    # sub-block) with no trace of the owning group's work is the
    # partition-in-flight signature
    served_groups = {d.get("group") for d in spans
                     if d["name"] in GROUP_PHASES}
    for d in spans:
        g = d.get("group")
        if d["name"] == "proxy.hop":
            if g in served_groups:
                continue  # a retry served it: an errored attempt alone
                # does not make the assembly incomplete
            if "error" in d:
                why.append(f"proxy hop to group {g} failed ({d['error']})")
            else:
                why.append(f"no group-side spans for proxied group {g}")
        elif d["name"] == "ingress.queue" and g not in served_groups:
            hops = [h for h in spans
                    if h["name"] == "proxy.hop" and h.get("group") == g]
            if not hops:
                why.append(f"sub-block for group {g} dispatched but "
                           f"never served")

    critical = _critical_path(spans, t0, t1)
    return {
        "trace": trace_id,
        "members": sorted({d["member"] for d in spans}),
        "spans": spans,
        "e2e_ms": round((t1 - t0) * 1e3, 3),
        "incomplete": bool(why),
        "incomplete_why": why,
        "critical_path": critical,
        "critical_path_ms": round(sum(c["duration_ms"] for c in critical),
                                  3),
    }


def _critical_path(spans: list[dict], t0: float, t1: float) -> list[dict]:
    """Innermost-cover decomposition of ``[t0, t1]``: at every instant
    the critical path charges the ACTIVE span that started last (the
    most specific phase — a ``quorum.wait`` inside a ``client.submit``
    wins the interval it covers); instants no span covers are charged to
    the most recent enclosing span, so the segment durations always sum
    to the end-to-end wall time."""
    if t1 <= t0:
        return []
    edges = sorted({t0, t1}
                   | {d["wall"] for d in spans}
                   | {d["wall"] + d.get("duration_ms", 0.0) / 1e3
                      for d in spans})
    edges = [e for e in edges if t0 <= e <= t1]
    segments: list[dict] = []
    last_owner: dict | None = None
    for lo, hi in zip(edges, edges[1:]):
        if hi - lo <= 0:
            continue
        mid = (lo + hi) / 2
        active = [d for d in spans
                  if d["wall"] <= mid
                  < d["wall"] + d.get("duration_ms", 0.0) / 1e3]
        owner = (max(active, key=lambda d: d["wall"]) if active
                 else last_owner)
        if owner is None:
            continue
        last_owner = owner
        if segments and segments[-1]["_owner"] is owner \
                and abs(segments[-1]["_end"] - lo) < 1e-9:
            segments[-1]["duration_ms"] += (hi - lo) * 1e3
            segments[-1]["_end"] = hi
            continue
        segments.append({"name": owner["name"],
                         "member": owner["member"],
                         "group": owner.get("group"),
                         "offset_ms": round((lo - t0) * 1e3, 3),
                         "duration_ms": (hi - lo) * 1e3,
                         "_owner": owner, "_end": hi})
    for seg in segments:
        seg["duration_ms"] = round(seg["duration_ms"], 3)
        del seg["_owner"], seg["_end"]
    return segments


def render_waterfall(assembly: dict) -> str:
    """The human rendering of one assembled trace: spans in causal
    order, one line each, critical-path phases starred; incomplete
    assemblies carry a loud banner (they are rendered, never dropped)."""
    lines = [f"trace {assembly['trace']}: {assembly['e2e_ms']:.3f} ms "
             f"end-to-end across {len(assembly['members'])} member(s) "
             f"({', '.join(assembly['members'])})"]
    if assembly["incomplete"]:
        lines.append("  !! INCOMPLETE ASSEMBLY: "
                     + "; ".join(assembly["incomplete_why"]))
    crit = {(c["name"], c["member"]) for c in assembly["critical_path"]}
    crit_ms = {}
    for c in assembly["critical_path"]:
        key = (c["name"], c["member"])
        crit_ms[key] = crit_ms.get(key, 0.0) + c["duration_ms"]
    for d in assembly["spans"]:
        key = (d["name"], d["member"])
        star = "*" if key in crit else " "
        g = f" g={d['group']}" if d.get("group") is not None else ""
        extra = (f"  [critical {crit_ms[key]:.3f} ms]"
                 if star == "*" else "")
        lines.append(
            f" {star} +{d['offset_ms']:9.3f} ms  {d['name']:<16} "
            f"{d.get('duration_ms', 0.0):9.3f} ms  "
            f"{d['member']}{g}{extra}")
    lines.append(f"  critical path: {assembly['critical_path_ms']:.3f} ms "
                 f"over {len(assembly['critical_path'])} segment(s)")
    return "\n".join(lines)
