"""Continuous host profiling plane (docs/OBSERVABILITY.md "Profiling").

The observability tiers before this module say *what* is slow — the
``latency.*`` phase histograms attribute a proxied write, the health
detectors grade commit stalls, ``/series`` shows when — but nothing
says *which code* held the event loop when it happened. This module is
the runtime complement to the copycheck loop-blocking rule (static
hazards) and the device-plane xprof helpers in ``utils/profiling.py``
(device time): a per-process **wall-stack sampler** plus **event-loop
hold attribution**, two pieces:

- **:class:`Profiler`** — ONE daemon thread per process folding
  ``sys._current_frames()`` stacks at ``COPYCAT_PROFILE_HZ`` (default
  ~19 Hz, deliberately off-cadence from the 1 Hz health/series timers)
  into a bounded, time-bucketed aggregate ring — the same ``?since=``
  retention model as ``utils/timeseries.py``. Stacks fold to the
  flamegraph.pl collapsed format (``thread;mod.func;...;leaf count``,
  root -> leaf), so ``/profile.txt`` pipes straight into flamegraph
  tooling. The sampler self-measures (``profile.overhead_ms``): the
  plane's cost is itself a series.
- **Hold attribution** — ``asyncio.events.Handle._run`` is patched
  while the profiler runs: every callback/task step is timed on the
  hot path with two ``perf_counter`` reads and nothing else; a step
  holding the loop at least ``COPYCAT_PROFILE_HOLD_MS`` records a
  *hold* carrying the owning frame — the sampler's most recent stack
  of the holding thread when one landed inside the hold (any 19 Hz
  sample during a 100 ms+ block does), else the callback/coroutine
  qualname. Holds feed the ``profile.hold_*`` gauges, a bounded hold
  ring (the ``loop_stall`` detector's evidence), and flight-recorder
  stall notes via each host's note callback.

The profiler is **process-wide and refcounted**: in-process test
clusters construct several servers per process, and per-server sampler
threads would multiply the cost for identical data. Every host
(member / ingress / supervisor) calls :func:`acquire` with its metric
registry — the first acquire starts the thread and installs the loop
patch, the last :func:`release` stops and uninstalls both. The
``profile.*`` family therefore reports *process* totals on every
co-resident host's registry — honest for a process-level property (the
GIL and the loop are shared), and exactly what the multi-process
deployment plane measures per process.

``COPYCAT_PROFILE=0`` removes all of it — no thread, no loop patch, no
``profile.*`` keys, no ``/profile`` routes, no ``loop_stall`` detector
— restoring the pre-profiler process bit-identically (the standing
``COPYCAT_*=0`` A/B discipline).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import weakref
from collections import Counter, deque
from typing import Any, Callable, Iterable

from . import knobs

#: aggregate-ring bucket width (seconds): `?since=` resolution
_BUCKET_S = 1.0
#: frames folded per stack before truncation (runaway recursion guard)
_MAX_DEPTH = 64
#: holds retained for /profile + the loop_stall detector's evidence
_HOLD_RING = 128


def fold_stack(frame: Any, thread_name: str) -> str:
    """Fold one thread's leaf frame into the collapsed flamegraph.pl
    form ``thread;mod.func;mod.func;...;leaf`` (root -> leaf, thread
    name first — separators stripped from names so the one-line-per-
    stack format survives any input)."""
    parts: list[str] = []
    f = frame
    depth = 0
    while f is not None and depth < _MAX_DEPTH:
        code = f.f_code
        mod = os.path.splitext(os.path.basename(code.co_filename))[0]
        parts.append(f"{mod}.{code.co_name}")
        f = f.f_back
        depth += 1
    parts.append(thread_name.replace(";", "_").replace(" ", "_"))
    parts.reverse()
    return ";".join(parts)


def _describe_callback(handle: Any) -> str:
    """A handle's owning frame name for holds too short for any sample
    to land in: the stepped task's coroutine qualname, else the
    callback qualname."""
    cb = getattr(handle, "_callback", None)
    task = getattr(cb, "__self__", None)
    coro = getattr(task, "get_coro", None)
    if callable(coro):  # a Task.__step: name the coroutine, not __step
        try:
            return getattr(coro(), "__qualname__", None) \
                or task.get_name()
        except Exception:  # noqa: BLE001 - naming must never raise
            pass
    return getattr(cb, "__qualname__", None) or repr(cb)


class _HostView:
    """One host's registration: the ``profile.*`` gauges on its metric
    registry (refreshed by the sampler thread) + its stall-note
    callback (``RaftServer.health_note`` on members; the ingress and
    supervisor have no flight ring and pass ``None``).

    The view holds its host WEAKLY — the registry by ``weakref.ref``
    and a bound-method note callback by ``weakref.WeakMethod``. An
    orderly teardown goes through :func:`release`; a host that simply
    vanishes (SIGKILL-shaped test teardown never releases) must not be
    pinned alive by its view nor serviced by the sampler forever —
    ``refresh`` reports the registry dead and the sampler prunes the
    view. Plain functions (test callbacks) are kept strongly: only a
    bound method implies an owning host whose lifetime governs."""

    __slots__ = ("_reg", "_note", "_note_strong")

    def __init__(self, registry: Any,
                 note_fn: Callable[..., None] | None) -> None:
        self._reg = weakref.ref(registry)
        self._note = self._note_strong = None
        if note_fn is not None:
            try:
                self._note = weakref.WeakMethod(note_fn)
            except TypeError:  # a plain function: no host to outlive
                self._note_strong = note_fn

    @property
    def registry(self) -> Any:
        return self._reg()

    @property
    def note_fn(self) -> Callable[..., None] | None:
        if self._note_strong is not None:
            return self._note_strong
        if self._note is not None:
            return self._note()
        return None

    def refresh(self, prof: "Profiler") -> bool:
        """Publish the process counters; False once the host is gone."""
        registry = self._reg()
        if registry is None:
            return False
        registry.gauge("profile.samples").set(prof.samples)
        registry.gauge("profile.holds").set(prof.holds)
        registry.gauge("profile.hold_max_ms").set(round(prof.hold_max_ms, 2))
        registry.gauge("profile.hold_ms").set(round(prof.hold_total_ms, 2))
        registry.gauge("profile.overhead_ms").set(round(prof.overhead_ms, 2))
        return True


class Profiler:
    """The per-process sampling profiler (see the module docstring).

    Construct via :func:`acquire`, never directly — the refcounted
    singleton is what keeps one sampler thread per process."""

    def __init__(self, hz: float | None = None,
                 hold_ms: float | None = None,
                 window_s: float | None = None) -> None:
        self.hz = max(0.5, hz if hz is not None
                      else knobs.get_float("COPYCAT_PROFILE_HZ"))
        self.hold_threshold_ms = max(
            1.0, hold_ms if hold_ms is not None
            else knobs.get_float("COPYCAT_PROFILE_HOLD_MS"))
        self.window_s = max(2.0, window_s if window_s is not None
                            else knobs.get_int("COPYCAT_PROFILE_WINDOW_S"))
        # (bucket wall t, {folded stack: sample count}) oldest-first
        self._buckets: deque = deque(
            maxlen=max(2, int(self.window_s / _BUCKET_S)))
        self._holds: deque = deque(maxlen=_HOLD_RING)
        # thread ident -> (wall t, folded stack): the sampler's latest
        # view per thread, what hold attribution reads (GIL-atomic
        # tuple swap; no lock on the loop's hot path)
        self._last_stack: dict[int, tuple[float, str]] = {}
        self._lock = threading.Lock()
        self._views: list[_HostView] = []
        self._refs = 0
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._orig_handle_run: Any = None
        self.samples = 0
        self.holds = 0
        self.hold_max_ms = 0.0
        self.hold_total_ms = 0.0
        self.overhead_ms = 0.0

    # -- lifecycle ---------------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._install_loop_patch()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="copycat-profiler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._uninstall_loop_patch()
        thread, self._thread = self._thread, None
        if thread is not None:
            self._stop.set()
            thread.join(timeout=2.0)

    def register_view(self, registry: Any,
                      note_fn: Callable[..., None] | None) -> None:
        view = _HostView(registry, note_fn)
        view.refresh(self)  # keys exist in snapshots before any sample
        with self._lock:
            self._views.append(view)

    def unregister_view(self, registry: Any) -> None:
        with self._lock:  # drop the host's view + any dead ones
            self._views = [v for v in self._views
                           if (r := v.registry) is not None
                           and r is not registry]

    # -- the sampler thread ------------------------------------------------

    def _run(self) -> None:
        period = 1.0 / self.hz
        while not self._stop.wait(period):
            t0 = time.perf_counter()
            try:
                self._sample_once()
            except Exception:  # noqa: BLE001 - never kill the sampler
                pass
            self.overhead_ms += (time.perf_counter() - t0) * 1e3

    def _sample_once(self) -> None:
        now = time.time()
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        me = threading.get_ident()
        folded: dict[str, int] = {}
        for ident, frame in frames.items():
            if ident == me:  # the sampler never profiles itself
                continue
            stack = fold_stack(frame, names.get(ident, f"thread-{ident}"))
            folded[stack] = folded.get(stack, 0) + 1
            self._last_stack[ident] = (now, stack)
        with self._lock:
            bucket = self._bucket_for(now)
            for stack, n in folded.items():
                bucket[stack] = bucket.get(stack, 0) + n
            self.samples += 1
            views = list(self._views)
        dead = [v for v in views if not v.refresh(self)]
        if dead:  # hosts that vanished without release: stop servicing
            with self._lock:
                self._views = [v for v in self._views if v not in dead]

    def _bucket_for(self, now: float) -> dict:
        """The open bucket for ``now`` (callers hold the lock)."""
        if not self._buckets or now - self._buckets[-1][0] >= _BUCKET_S:
            self._buckets.append((round(now, 3), {}))
        return self._buckets[-1][1]

    # -- hold attribution (the asyncio.Handle._run patch) ------------------

    def _install_loop_patch(self) -> None:
        import asyncio.events as aio_events

        if self._orig_handle_run is not None:
            return
        orig = self._orig_handle_run = aio_events.Handle._run
        threshold_s = self.hold_threshold_ms / 1e3
        prof = self
        perf = time.perf_counter

        def _profiled_run(handle: Any) -> None:
            # THE hot path: two perf_counter reads and a compare per
            # callback; everything else happens only on a real hold
            t0 = perf()
            try:
                orig(handle)
            finally:
                dt = perf() - t0
                if dt >= threshold_s:
                    prof._record_hold(handle, dt)

        aio_events.Handle._run = _profiled_run

    def _uninstall_loop_patch(self) -> None:
        import asyncio.events as aio_events

        if self._orig_handle_run is not None:
            aio_events.Handle._run = self._orig_handle_run
            self._orig_handle_run = None

    def _record_hold(self, handle: Any, dt_s: float) -> None:
        """One loop hold over the threshold: attribute, meter, note.
        Runs on the (just-released) loop thread — swallow everything,
        observability must never wound the host."""
        try:
            dt_ms = dt_s * 1e3
            end = time.time()
            callback = _describe_callback(handle)
            sampled = self._last_stack.get(threading.get_ident())
            if sampled is not None and end - dt_s <= sampled[0] <= end:
                stack = sampled[1]
            else:  # too short for a sample to land: name the callback
                stack = (threading.current_thread().name
                         .replace(";", "_").replace(" ", "_")
                         + ";" + callback.replace(";", "_"))
            frame = stack.rsplit(";", 1)[-1]
            hold = {"t": round(end, 3), "ms": round(dt_ms, 2),
                    "frame": frame, "callback": callback, "stack": stack}
            with self._lock:
                self.holds += 1
                self.hold_total_ms += dt_ms
                self.hold_max_ms = max(self.hold_max_ms, dt_ms)
                self._holds.append(hold)
                views = list(self._views)
            for view in views:
                if not view.refresh(self):
                    continue  # host vanished without release
                note_fn = view.note_fn
                if note_fn is not None:
                    note_fn("loop_stall", hold_ms=round(dt_ms, 1),
                            frame=frame, callback=callback,
                            stack=stack)
        except Exception:  # noqa: BLE001
            pass

    # -- query side --------------------------------------------------------

    def holds_since(self, since: float) -> list[dict]:
        """Retained holds newer than ``since`` (wall seconds) — the
        ``loop_stall`` detector's per-window evidence read."""
        with self._lock:
            return [dict(h) for h in self._holds if h["t"] > since]

    def payload(self, since: float | None = None,
                top: int | None = None) -> dict:
        """The ``/profile`` JSON payload: folded stacks aggregated over
        the retained buckets, optionally windowed to ``t > since``
        (wall seconds, the ``/series`` model) and truncated to the
        ``top`` heaviest stacks."""
        with self._lock:
            merged: dict[str, int] = {}
            for t, bucket in self._buckets:
                if since is not None and t <= since:
                    continue
                for stack, n in bucket.items():
                    merged[stack] = merged.get(stack, 0) + n
            holds = [dict(h) for h in self._holds
                     if since is None or h["t"] > since]
            counters = {
                "samples": self.samples,
                "holds": self.holds,
                "hold_max_ms": round(self.hold_max_ms, 2),
                "hold_ms": round(self.hold_total_ms, 2),
                "overhead_ms": round(self.overhead_ms, 2),
            }
        stacks = sorted(merged.items(), key=lambda kv: (-kv[1], kv[0]))
        if top is not None:
            stacks = stacks[:max(1, top)]
        return {
            "pid": os.getpid(),
            "hz": self.hz,
            "hold_threshold_ms": self.hold_threshold_ms,
            "window_s": self.window_s,
            "now": round(time.time(), 3),
            "window_samples": sum(merged.values()),
            "stacks": [{"stack": s, "count": n} for s, n in stacks],
            "holds": holds,
            "counters": counters,
        }

    def render_text(self, since: float | None = None,
                    top: int | None = None) -> str:
        """The ``/profile.txt`` rendering: pure flamegraph.pl collapsed
        lines (``stack count``) — pipeable into flamegraph tooling
        as-is, nothing else on the wire."""
        payload = self.payload(since=since, top=top)
        return "".join(f"{row['stack']} {row['count']}\n"
                       for row in payload["stacks"])

    def window_top(self, t0: float, t1: float, top: int = 3) -> dict:
        """Top folded stacks whose buckets overlap ``[t0, t1]`` (wall
        seconds) — what slow traces stamp so a trace's waterfall points
        at the code the process was actually running during it."""
        with self._lock:
            merged: dict[str, int] = {}
            for t, bucket in self._buckets:
                if t0 - _BUCKET_S <= t <= t1:
                    for stack, n in bucket.items():
                        merged[stack] = merged.get(stack, 0) + n
        stacks = sorted(merged.items(), key=lambda kv: (-kv[1], kv[0]))
        return {
            "t0": round(t0, 3), "t1": round(t1, 3),
            "samples": sum(merged.values()),
            "stacks": [{"stack": s, "count": n}
                       for s, n in stacks[:max(1, top)]],
        }

    def top_summary(self, top: int = 10) -> dict:
        """The compact top-frame summary ``bench --metrics-json``
        embeds: the frame table over the retained window plus the
        plane's own counters (so the artifact carries its cost)."""
        payload = self.payload(top=max(top * 4, 40))
        return {
            "hz": self.hz,
            "window_s": self.window_s,
            "window_samples": payload["window_samples"],
            "counters": payload["counters"],
            "frames": frame_table(
                [(r["stack"], r["count"]) for r in payload["stacks"]],
                top=top),
        }


# ---------------------------------------------------------------------------
# pure aggregation + cluster merge (the CLI/bench side; no profiler needed)
# ---------------------------------------------------------------------------


def frame_table(stacks: Iterable[tuple[str, int]], top: int = 20,
                skip: int = 1) -> list[dict]:
    """Per-frame self/total aggregation over folded stacks.

    ``self`` counts samples where the frame is the leaf (on-CPU there),
    ``total`` samples where it appears anywhere (itself or callees
    below it — counted once per stack, so recursion can't exceed 100%).
    ``skip`` drops leading non-frame segments: 1 for a process profile
    (the thread name), 2 for a cluster merge (member prefix + thread).
    """
    rows = [(s.split(";")[skip:], n) for s, n in stacks]
    rows = [(frames, n) for frames, n in rows if frames]
    grand = sum(n for _, n in rows)
    self_c: Counter = Counter()
    total_c: Counter = Counter()
    for frames, n in rows:
        self_c[frames[-1]] += n
        for f in set(frames):
            total_c[f] += n
    table = [{"frame": f,
              "self": self_c.get(f, 0),
              "total": total,
              "self_pct": round(100 * self_c.get(f, 0) / grand, 1)
              if grand else 0.0,
              "total_pct": round(100 * total / grand, 1) if grand
              else 0.0}
             for f, total in total_c.items()]
    table.sort(key=lambda r: (-r["self"], -r["total"], r["frame"]))
    return table[:max(1, top)]


def assemble_profile(members: dict[str, dict | None],
                     failed_members: Iterable[str] = ()) -> dict:
    """Merge per-member ``/profile`` payloads into ONE cluster profile:
    every folded stack prefixed with its member identity (so one flame
    graph shows the whole cluster, per-member subtrees side by side).
    Unreachable members — and reachable ones serving no ``/profile``
    (plane off, pre-profiler build) — mark the merge ``incomplete=true``
    with reasons: partial profiles render, never drop (the trace/
    timeline assembly semantics)."""
    failed = sorted(set(failed_members))
    incomplete_why = [f"member {m} unreachable" for m in failed]
    stacks: dict[str, int] = {}
    contributed: dict[str, int] = {}
    holds: list[dict] = []
    for addr in sorted(members):
        payload = members[addr]
        if not isinstance(payload, dict) or "stacks" not in payload:
            incomplete_why.append(
                f"member {addr} serves no /profile "
                f"(COPYCAT_PROFILE=0 or a pre-profiler build)")
            contributed[addr] = 0
            continue
        node = payload.get("node") or addr
        n = 0
        for row in payload["stacks"]:
            key = f"{node};{row['stack']}"
            stacks[key] = stacks.get(key, 0) + int(row["count"])
            n += int(row["count"])
        contributed[node] = n
        for hold in payload.get("holds", ()):
            holds.append({**hold, "member": node})
    holds.sort(key=lambda h: -h.get("ms", 0.0))
    ordered = sorted(stacks.items(), key=lambda kv: (-kv[1], kv[0]))
    return {
        "members": sorted(contributed),
        "contributed": contributed,
        "incomplete": bool(incomplete_why),
        "incomplete_why": incomplete_why,
        "total_samples": sum(stacks.values()),
        "stacks": [{"stack": s, "count": n} for s, n in ordered],
        "holds": holds[:50],
    }


def diff_profiles(current: dict, baseline: dict, top: int = 20
                  ) -> list[dict]:
    """Frame-table diff of two assembled cluster profiles (the saved
    ``--json`` artifact shape): per-frame self%% deltas, largest move
    first — "what got hotter since the baseline". Frames only on one
    side diff against 0."""
    cur = {r["frame"]: r for r in frame_table(
        [(s["stack"], s["count"]) for s in current.get("stacks", ())],
        top=10_000, skip=2)}
    base = {r["frame"]: r for r in frame_table(
        [(s["stack"], s["count"]) for s in baseline.get("stacks", ())],
        top=10_000, skip=2)}
    rows = []
    for frame in set(cur) | set(base):
        c = cur.get(frame, {}).get("self_pct", 0.0)
        b = base.get(frame, {}).get("self_pct", 0.0)
        if c == b == 0.0:
            continue
        rows.append({"frame": frame, "self_pct": c,
                     "baseline_self_pct": b,
                     "delta_pct": round(c - b, 1)})
    rows.sort(key=lambda r: (-abs(r["delta_pct"]), r["frame"]))
    return rows[:max(1, top)]


def render_profile(profile: dict, top: int = 20) -> str:
    """The human rendering of an assembled cluster profile: banner,
    per-member contribution, the frame table (self/total %%), then the
    heaviest loop holds. Incomplete merges carry a loud banner —
    rendered, never dropped."""
    lines = [f"cluster profile: {len(profile['members'])} member(s), "
             f"{profile['total_samples']} folded sample(s)"]
    if profile["incomplete"]:
        lines.append("!! INCOMPLETE: "
                     + "; ".join(profile["incomplete_why"]))
    for member in profile["members"]:
        lines.append(f"  {member:<24} "
                     f"{profile['contributed'].get(member, 0)} sample(s)")
    table = frame_table([(s["stack"], s["count"])
                         for s in profile.get("stacks", ())],
                        top=top, skip=2)
    if table:
        lines.append(f"{'frame':<52} {'self%':>6} {'total%':>7} "
                     f"{'self':>7} {'total':>7}")
        for row in table:
            lines.append(f"{row['frame']:<52} {row['self_pct']:>5.1f}% "
                         f"{row['total_pct']:>6.1f}% {row['self']:>7} "
                         f"{row['total']:>7}")
    else:
        lines.append("(no stacks in the window)")
    holds = profile.get("holds") or []
    lines.append(f"loop holds ({len(holds)}):")
    if not holds:
        lines.append("  (none recorded)")
    for hold in holds[:5]:
        mark = time.strftime("%H:%M:%S", time.localtime(hold.get("t", 0)))
        lines.append(f"  {mark} {hold.get('member', '?'):<22} "
                     f"{hold.get('ms', 0):>8.1f} ms  "
                     f"{hold.get('frame', '?')}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# the refcounted process-wide singleton
# ---------------------------------------------------------------------------

_ACQUIRE_LOCK = threading.Lock()

#: THE per-process profiler while any host holds a reference; ``None``
#: when the plane is off or no host is alive (slow-trace stamping and
#: bench read this directly)
PROFILER: Profiler | None = None


def acquire(registry: Any = None,
            note_fn: Callable[..., None] | None = None
            ) -> Profiler | None:
    """Refcounted acquire of the process-wide profiler: the first
    caller starts the sampler thread and installs the loop patch;
    every caller with a ``registry`` gets the ``profile.*`` gauges
    registered there (refreshed by the sampler). Returns ``None`` —
    and touches NOTHING — under ``COPYCAT_PROFILE=0``: no thread, no
    keys, no patch (the A/B off-state)."""
    global PROFILER
    if not knobs.get_bool("COPYCAT_PROFILE"):
        return None
    with _ACQUIRE_LOCK:
        if PROFILER is None:
            PROFILER = Profiler()
            PROFILER.start()
        PROFILER._refs += 1
        if registry is not None:
            PROFILER.register_view(registry, note_fn)
        return PROFILER


def release(profiler: Profiler | None, registry: Any = None) -> None:
    """Release one host's reference (no-op on ``None``, so callers
    release unconditionally): drops the host's gauge view, and the
    LAST release stops the sampler and uninstalls the loop patch —
    the process returns to its unpatched shape."""
    global PROFILER
    if profiler is None:
        return
    with _ACQUIRE_LOCK:
        if registry is not None:
            profiler.unregister_view(registry)
        profiler._refs -= 1
        if profiler._refs <= 0:
            profiler.stop()
            if PROFILER is profiler:
                PROFILER = None


__all__ = [
    "Profiler", "acquire", "release", "assemble_profile", "frame_table",
    "diff_profiles", "render_profile", "fold_stack", "PROFILER",
]
