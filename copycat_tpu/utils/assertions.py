"""Argument/state checking helpers (Catalyst ``Assert`` equivalent)."""

from __future__ import annotations

from typing import TypeVar

T = TypeVar("T")


def check_not_null(value: T | None, message: str = "value cannot be null") -> T:
    if value is None:
        raise ValueError(message)
    return value


def check_arg(condition: bool, message: str = "illegal argument", *args: object) -> None:
    if not condition:
        raise ValueError(message % args if args else message)


def check_state(condition: bool, message: str = "illegal state", *args: object) -> None:
    if not condition:
        raise RuntimeError(message % args if args else message)
