"""Backend selection helpers for entry points."""

from __future__ import annotations

import os


def honor_jax_platforms_env() -> None:
    """Re-assert the ``JAX_PLATFORMS`` env var against plugin site config.

    Site customization (e.g. a TPU plugin) may pin ``jax_platforms`` via
    ``jax.config``, which overrides the env var — entry points that
    document ``JAX_PLATFORMS=cpu`` (CI smokes, the verdict runner) call
    this right after importing jax, before any backend initializes, so
    the env var wins everywhere.
    """
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
