"""Backend selection helpers for entry points."""

from __future__ import annotations

import os


def honor_jax_platforms_env() -> None:
    """Re-assert the ``JAX_PLATFORMS`` env var against plugin site config.

    Site customization (e.g. a TPU plugin) may pin ``jax_platforms`` via
    ``jax.config``, which overrides the env var — entry points that
    document ``JAX_PLATFORMS=cpu`` (CI smokes, the verdict runner) call
    this right after importing jax, before any backend initializes, so
    the env var wins everywhere.
    """
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def require_devices(env: str = "COPYCAT_DEVICE_TIMEOUT",
                    default_s: float = 300.0) -> None:
    """Fail FAST (exit 2) when the accelerator is unreachable.

    Device enumeration through a tunneled TPU backend can hang
    indefinitely when the tunnel is down (observed: ``jax.devices()``
    blocks forever), which wedges any pipeline that runs an entry point
    and waits on it. Healthy enumeration takes well under a minute, so a
    generous timeout (``env`` seconds, default ``default_s``) cleanly
    separates 'slow' from 'dead'. Call at the top of device-touching
    entry points, before any other backend use.
    """
    import sys
    import threading

    import jax

    timeout_s = float(os.environ.get(env, str(default_s)))
    result: dict = {}

    def probe() -> None:
        try:
            result["devices"] = jax.devices()
        except Exception as e:  # noqa: BLE001 — report any backend error
            result["error"] = e

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    err = sys.stderr
    if t.is_alive():
        print(f"FATAL: device enumeration did not return within "
              f"{timeout_s:.0f}s — accelerator/tunnel unreachable",
              file=err, flush=True)
        os._exit(2)  # the probe thread holds the backend lock — hard exit
    if "error" in result:
        print(f"FATAL: device enumeration failed: {result['error']!r}",
              file=err, flush=True)
        raise SystemExit(2)
    print(f"devices: {result['devices']}", file=err, flush=True)
