"""Backend selection helpers for entry points."""

from __future__ import annotations

import os
import re

from . import knobs


def honor_jax_platforms_env() -> None:
    """Re-assert the ``JAX_PLATFORMS`` env var against plugin site config.

    Site customization (e.g. a TPU plugin) may pin ``jax_platforms`` via
    ``jax.config``, which overrides the env var — entry points that
    document ``JAX_PLATFORMS=cpu`` (CI smokes, the verdict runner) call
    this right after importing jax, before any backend initializes, so
    the env var wins everywhere.
    """
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


# The cache dir most recently set by enable_compilation_cache, so later
# calls can tell operator config from this helper's own earlier work.
_cache_dir_applied: str | None = None


def enable_compilation_cache(path: str | None = None) -> str | None:
    """Point XLA's persistent compilation cache at a stable directory.

    The engine's one-time jit compile dominates server-open latency
    (measured: ~8-9 s on CPU for the DeviceEngine step at any capacity,
    tens of seconds for a first-ever TPU compile — see
    ``manager/device_executor.py`` warm-up note). XLA can persist
    compiled executables keyed by (HLO, backend, flags); with this cache
    every later process on the machine — server restarts, bench reps,
    recovery after a crash — skips straight to execution.

    Resolution order: explicit ``path`` argument, else
    ``COPYCAT_COMPILE_CACHE`` env (set to ``0``/empty to disable), else
    ``~/.cache/copycat_tpu/xla``. Idempotent; returns the directory in
    use, or ``None`` when disabled or unavailable. Safe to call before
    backend initialization (it only sets jax config values).
    """
    explicit_path = path
    if path is None:
        env = knobs.get_raw("COPYCAT_COMPILE_CACHE")
        if env is not None and env in ("", "0"):
            return None
        path = env or os.path.join(
            os.path.expanduser("~"), ".cache", "copycat_tpu", "xla")
    try:
        import jax

        # Never shadow a cache the operator configured through JAX's own
        # surface (env var or jax.config) — overriding it would silently
        # split their fleet-shared cache. A dir this helper itself set on
        # an earlier call may be replaced, but only by a NEW explicit
        # ``path``: the no-arg calls at the entry points (server open,
        # bench, verdict) never downgrade an earlier explicit choice to
        # the default.
        global _cache_dir_applied
        config_dir = getattr(jax.config, "jax_compilation_cache_dir", None)
        env_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
        if env_dir and explicit_path is None:
            # JAX's own env var is operator config too — but only a
            # no-arg call defers to it; an explicit ``path`` argument is
            # the stronger, in-process operator statement and wins.
            return env_dir
        if config_dir:
            if config_dir != _cache_dir_applied:
                return config_dir            # operator-set: theirs
            if explicit_path is None:
                return config_dir            # ours; no-arg call keeps it
        os.makedirs(path, exist_ok=True)

        _trim_cache_dir(path)

        # The engine step takes seconds to compile, far above the 1 s
        # default threshold — but tests/small drivers compile many tiny
        # programs too; cache everything non-trivial. NOTE: the directory
        # is bounded by _trim_cache_dir above, NOT by jax's
        # ``jax_compilation_cache_max_size`` — that knob turns on
        # per-entry atime bookkeeping plus a directory-wide eviction scan
        # under a lock file, and with several concurrent processes on one
        # dir it produced both write-failure warnings (atime files racing
        # the eviction) and multi-minute stalls of child processes on
        # this machine. The cache dir itself is set LAST so a failure on
        # any knob leaves the cache fully disabled and the None return
        # truthful.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_compilation_cache_dir", path)
        _cache_dir_applied = path
        return path
    except Exception:  # noqa: BLE001 — cache is an optimization only
        return None


#: XLA persistent-cache entry names carry a 64-hex program hash
#: (e.g. ``jit__foo-<64 hex>-cache``); the trim below refuses to touch
#: anything else, so a misconfigured cache path (someone's $HOME) can
#: never lose user files.
_CACHE_ENTRY_RE = re.compile(r".*-[0-9a-f]{64}(-cache|-atime)?$")


def _trim_cache_dir(path: str, max_bytes: int = 1 << 30) -> None:
    """Best-effort size bound for the cache dir: drop least-recently
    used entries (max of atime/mtime — atime advances on cache hits
    under relatime) until under ``max_bytes``. Runs once per process at
    enable time — no locks, no bookkeeping files; a concurrently-deleted
    file is simply skipped. Only files shaped like XLA cache entries are
    ever removed, and a removed entry only costs its owner a recompile."""
    try:
        entries = []
        with os.scandir(path) as it:
            for e in it:
                try:
                    if not e.is_file() or not _CACHE_ENTRY_RE.match(e.name):
                        continue
                    st = e.stat()
                except OSError:
                    continue  # concurrently deleted mid-scan
                entries.append((max(st.st_atime, st.st_mtime),
                                st.st_size, e.path))
        total = sum(s for _, s, _ in entries)
        if total <= max_bytes:
            return
        entries.sort()  # least recently used first
        for _, size, p in entries:
            try:
                os.remove(p)
            except OSError:
                continue
            total -= size
            if total <= max_bytes:
                return
    except OSError:
        return


#: Set after one successful require_devices verification (per process).
_devices_verified: bool = False


# Run by subprocess probes: mirrors the parent's platform selection
# (honor_jax_platforms_env) so the probe enumerates the same backends the
# parent is about to.
_PROBE_CODE = """
import os
if os.environ.get("JAX_PLATFORMS"):
    import jax
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax
print(jax.devices(), flush=True)
"""


def require_devices(env: str = "COPYCAT_DEVICE_TIMEOUT",
                    default_s: float = 120.0,
                    probes_env: str = "COPYCAT_DEVICE_PROBES",
                    default_probes: int = 5,
                    retry_wait_s: float = 60.0) -> None:
    """Fail fast (exit 2) when the accelerator is unreachable — with retries.

    Device enumeration through a tunneled TPU backend can hang
    indefinitely when the tunnel is down (observed: ``jax.devices()``
    blocks forever), which wedges any pipeline that runs an entry point
    and waits on it. The tunnel's outages are usually *transient* (round-3
    post-mortem: a single dead window at snapshot time zeroed out a whole
    round's benchmark evidence), so a single fail-fast probe is too
    brittle: this probes in SUBPROCESSES — a hung child is killed without
    poisoning this process's backend lock — up to ``default_probes`` times
    (``probes_env``), each bounded by ``default_s`` seconds (``env``),
    waiting ``retry_wait_s`` between attempts. Only after a probe succeeds
    does the parent enumerate in-process (still under a thread-timeout
    guard, in case the tunnel dies in the gap). Call at the top of
    device-touching entry points, before any other backend use.
    """
    import subprocess
    import sys
    import threading
    import time

    # One successful verification per process is enough — entry points
    # can layer guards (e.g. __graft_entry__'s __main__ probes, then
    # entry() self-guards) without paying repeated subprocess probes.
    # And a process pinned to CPU-only platforms cannot hang on an
    # accelerator at all: skip the probe outright.
    global _devices_verified
    if _devices_verified:
        return
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if platforms and all(
            p.strip() == "cpu" for p in platforms.split(",") if p.strip()):
        _devices_verified = True
        return

    timeout_s = knobs.get_float(env, default=default_s)
    n_probes = max(1, knobs.get_int(probes_env, default=default_probes))
    err = sys.stderr

    for attempt in range(1, n_probes + 1):
        try:
            out = subprocess.run(
                [sys.executable, "-c", _PROBE_CODE],
                capture_output=True, text=True, timeout=timeout_s)
            if out.returncode == 0 and out.stdout.strip():
                print(f"devices (probe {attempt}/{n_probes}): "
                      f"{out.stdout.strip()}", file=err, flush=True)
                break
            detail = (out.stderr or out.stdout).strip()[-500:]
            print(f"probe {attempt}/{n_probes}: enumeration failed "
                  f"(rc={out.returncode}): {detail}", file=err, flush=True)
        except subprocess.TimeoutExpired:
            print(f"probe {attempt}/{n_probes}: no response within "
                  f"{timeout_s:.0f}s — accelerator/tunnel unreachable",
                  file=err, flush=True)
        if attempt < n_probes:
            print(f"retrying in {retry_wait_s:.0f}s...", file=err, flush=True)
            time.sleep(retry_wait_s)
    else:
        print(f"FATAL: accelerator unreachable after {n_probes} probes",
              file=err, flush=True)
        raise SystemExit(2)

    # The probe proved the backend healthy moments ago; now bind it
    # in-process. Keep a thread-timeout guard for the race where the
    # tunnel dies between probe and bind.
    import jax

    result: dict = {}

    def bind() -> None:
        try:
            result["devices"] = jax.devices()
        except Exception as e:  # noqa: BLE001 — report any backend error
            result["error"] = e

    t = threading.Thread(target=bind, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        print(f"FATAL: in-process device bind hung within {timeout_s:.0f}s "
              "of a healthy probe — tunnel died in the gap",
              file=err, flush=True)
        os._exit(2)  # the bind thread holds the backend lock — hard exit
    if "error" in result:
        print(f"FATAL: device enumeration failed: {result['error']!r}",
              file=err, flush=True)
        raise SystemExit(2)
    _devices_verified = True
