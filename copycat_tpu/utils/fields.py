"""Compiled per-class ``__init__`` for field-list serialization bases.

Protocol messages and log entries are constructed per op on the session
hot path; the generic ``for name in _fields: setattr(self, name,
kwargs.get(name))`` loop was a measured share of both construct paths
(PERF.md round 6). This compiles a NamedTuple-style ``__init__`` —
direct attribute assignments, every field defaulting to None — shared by
``protocol.messages.Message`` and ``server.log.Entry``.
"""

from __future__ import annotations


def compile_field_init(cls: type, fields: tuple,
                       head: str = "", body_head: str = "") -> None:
    """Attach a compiled ``__init__(self[, <head>][, f1=None, ...])``.

    ``head`` is extra parameter source inserted after ``self`` (fixed
    leading parameters, e.g. ``", term=0, timestamp=0.0"``);
    ``body_head`` is indented source run before the field assignments
    (e.g. ``"    self.index = 0\\n"``). Field names come from the
    class's own ``_fields`` declaration, never caller input.
    """
    args = "".join(f", {n}=None" for n in fields)
    body = "".join(f"    self.{n} = {n}\n" for n in fields)
    if not (body_head or body):
        body = "    pass\n"
    ns: dict = {}
    exec(f"def __init__(self{head}{args}):\n{body_head}{body}",  # noqa: S102
         ns)
    ns["__init__"].__qualname__ = f"{cls.__qualname__}.__init__"
    cls.__init__ = ns["__init__"]
