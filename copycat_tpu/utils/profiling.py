"""XLA profiler integration (SURVEY.md §5.1 build obligation).

The reference has no tracing beyond SLF4J loggers (§5.1 names the XLA
profiler hook a "free win on TPU"). :func:`xla_trace` wraps any step-loop
region in a ``jax.profiler`` trace whose artifacts open in
TensorBoard/XProf (or parse with ``xprof.convert.raw_to_tool_data`` when no
UI is available — that is how the one-hot rewrite in ``ops/consensus.py``
was found; see PERF.md).

Usage::

    from copycat_tpu.utils.profiling import xla_trace

    with xla_trace("/tmp/copycat-trace"):   # no-op when dir is falsy
        for _ in range(5):
            rg.step_round()
"""

from __future__ import annotations

import contextlib
from typing import Iterator


@contextlib.contextmanager
def xla_trace(trace_dir: str | None) -> Iterator[None]:
    """Trace the enclosed region with ``jax.profiler`` (no-op if falsy)."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(str(trace_dir)):
        yield


def summarize_trace(trace_dir: str, top: int = 15) -> list[tuple[str, float, int]]:
    """Aggregate device-op time from the NEWEST captured trace session.

    Returns ``[(op_name, total_ms, count), ...]`` sorted by time — enough
    to find the hot op without a TensorBoard UI. Only events on device
    (TPU/accelerator) lanes are counted, so host-side spans and module
    wrappers don't drown the per-op numbers. Requires the ``xprof``
    package (present in the image).
    """
    import collections
    import glob
    import json
    import os

    from xprof.convert import raw_to_tool_data as rtd

    # jax.profiler.trace writes one timestamped session subdir per capture;
    # summarize only the newest so reused trace dirs don't merge runs.
    sessions = sorted(glob.glob(f"{trace_dir}/plugins/profile/*/"))
    if not sessions:
        raise FileNotFoundError(f"no profile sessions under {trace_dir}")
    files = glob.glob(os.path.join(sessions[-1], "*.xplane.pb"))
    data, _ = rtd.xspace_to_tool_data(files, "trace_viewer", {})
    trace = json.loads(data.decode() if isinstance(data, bytes) else data)
    events = trace["traceEvents"]

    # Map pid -> process name from metadata events; keep device lanes only.
    proc: dict = {}
    for event in events:
        if event.get("ph") == "M" and event.get("name") == "process_name":
            proc[event.get("pid")] = event.get("args", {}).get("name", "")
    device_pids = {pid for pid, name in proc.items()
                   if any(t in name for t in ("TPU", "GPU", "/device",
                                              "Device", "XLA Op"))}

    agg: collections.Counter = collections.Counter()
    cnt: collections.Counter = collections.Counter()
    for event in events:
        if event.get("ph") != "X" or event.get("pid") not in device_pids:
            continue
        name = event.get("name", "")
        agg[name] += event.get("dur", 0)
        cnt[name] += 1
    return [(name, dur / 1e3, cnt[name]) for name, dur in agg.most_common(top)]
