"""XLA profiler integration (SURVEY.md §5.1 build obligation).

The reference has no tracing beyond SLF4J loggers (§5.1 names the XLA
profiler hook a "free win on TPU"). :func:`xla_trace` wraps any step-loop
region in a ``jax.profiler`` trace whose artifacts open in
TensorBoard/XProf (or parse with ``xprof.convert.raw_to_tool_data`` when no
UI is available — that is how the one-hot rewrite in ``ops/consensus.py``
was found; see PERF.md).

Usage::

    from copycat_tpu.utils.profiling import xla_trace

    with xla_trace("/tmp/copycat-trace"):   # no-op when dir is falsy
        for _ in range(5):
            rg.step_round()
"""

from __future__ import annotations

import contextlib
from typing import Iterator


@contextlib.contextmanager
def xla_trace(trace_dir: str | None) -> Iterator[None]:
    """Trace the enclosed region with ``jax.profiler`` (no-op if falsy)."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(str(trace_dir)):
        yield


def find_xplane_files(trace_dir: str) -> list[str]:
    """The ``*.xplane.pb`` files of the NEWEST capture session under
    ``trace_dir``.

    The standard jax layout is one timestamped subdir per capture under
    ``plugins/profile/``; some jax/tensorboard-plugin versions nest
    differently, so when that glob comes up empty the whole tree is
    scanned and the files are grouped by parent directory (newest
    mtime wins) — only the newest session is summarized either way, so
    reused trace dirs don't merge runs.
    """
    import glob
    import os

    sessions = sorted(glob.glob(f"{trace_dir}/plugins/profile/*/"))
    if sessions:
        files = glob.glob(os.path.join(sessions[-1], "*.xplane.pb"))
        if files:
            return files
    # Layout fallback: find xplane files anywhere below, newest
    # session-dir (by mtime) only.
    by_dir: dict[str, list[str]] = {}
    for root, _dirs, names in os.walk(trace_dir):
        for name in names:
            if name.endswith(".xplane.pb"):
                by_dir.setdefault(root, []).append(os.path.join(root, name))
    if not by_dir:
        raise FileNotFoundError(
            f"no profile sessions under {trace_dir}: expected "
            f"plugins/profile/<session>/*.xplane.pb (or any *.xplane.pb "
            f"below it) — did the traced region actually run?")
    newest = max(by_dir, key=lambda d: os.path.getmtime(d))
    return sorted(by_dir[newest])


def aggregate_trace_events(events: list[dict],
                           top: int = 15) -> list[tuple[str, float, int]]:
    """Aggregate device-lane op time from trace-viewer JSON events.

    Returns ``[(op_name, total_ms, count), ...]`` sorted by time. Only
    events on device (TPU/accelerator) lanes are counted, so host-side
    spans and module wrappers don't drown the per-op numbers. Split out
    of :func:`summarize_trace` so the aggregation is testable against a
    canned trace JSON without ``xprof`` or a TPU.
    """
    import collections

    # Map pid -> process name from metadata events; keep device lanes only.
    proc: dict = {}
    for event in events:
        if event.get("ph") == "M" and event.get("name") == "process_name":
            proc[event.get("pid")] = event.get("args", {}).get("name", "")
    device_pids = {pid for pid, name in proc.items()
                   if any(t in name for t in ("TPU", "GPU", "/device",
                                              "Device", "XLA Op"))}

    agg: collections.Counter = collections.Counter()
    cnt: collections.Counter = collections.Counter()
    for event in events:
        if event.get("ph") != "X" or event.get("pid") not in device_pids:
            continue
        name = event.get("name", "")
        agg[name] += event.get("dur", 0)
        cnt[name] += 1
    return [(name, dur / 1e3, cnt[name]) for name, dur in agg.most_common(top)]


def summarize_trace(trace_dir: str, top: int = 15) -> list[tuple[str, float, int]]:
    """Aggregate device-op time from the NEWEST captured trace session.

    Returns ``[(op_name, total_ms, count), ...]`` sorted by time — enough
    to find the hot op without a TensorBoard UI. Requires the ``xprof``
    package to parse the raw ``.xplane.pb`` capture; without it the
    error says so instead of surfacing an opaque import chain.
    """
    import json

    try:
        from xprof.convert import raw_to_tool_data as rtd
    except ImportError as exc:
        raise RuntimeError(
            "summarize_trace needs the 'xprof' package to parse raw "
            ".xplane.pb captures (pip install xprof, or open the trace "
            f"dir in TensorBoard instead): {exc}") from exc

    files = find_xplane_files(trace_dir)
    data, _ = rtd.xspace_to_tool_data(files, "trace_viewer", {})
    trace = json.loads(data.decode() if isinstance(data, bytes) else data)
    return aggregate_trace_events(trace["traceEvents"], top)
