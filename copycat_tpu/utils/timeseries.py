"""Retrospective telemetry: on-member time-series retention and the
cluster-merged timeline (docs/OBSERVABILITY.md "Retrospective
telemetry").

The observability stack before this module judged the *present* —
``/stats`` is a point-in-time snapshot, ``--watch`` computes deltas only
while an operator is looking, and the health detectors keep a short
window of private evidence. The moment an incident ends, the data that
explains it is gone: ``doctor`` can say "group 0 is stalled *now*" but
not "fsync latency started climbing 40 s before the stall". This module
is the retention tier, three pieces:

- **:class:`SeriesStore`** — a bounded, delta-encoded ring of periodic
  metric-registry samples: counters are stored as per-interval deltas
  (the rate signal an operator actually wants), gauges are sampled
  as-is, histograms sample their running p50/p99 plus a delta-encoded
  count. On members the store is driven off the existing
  :class:`~copycat_tpu.utils.health.HealthMonitor` cadence — no new
  task is spawned; the ingress tier runs one tiny repeating timer and
  the supervisor samples inside its existing health watch.
  ``COPYCAT_SERIES_INTERVAL_S`` / ``COPYCAT_SERIES_WINDOW`` bound the
  retention; ``COPYCAT_SERIES=0`` removes the plane — no store, no
  ``series.*`` keys, no ``/series`` route — restoring the pre-series
  server bit-identically (the standing A/B discipline).
- **Timeline assembly** — :func:`assemble_timeline` /
  :func:`render_timeline`: pure functions merging every member's
  ``/series`` + ``/flight`` + ``/health`` payloads into one cluster
  timeline: per-member metric sparklines time-aligned on a common
  grid, with flight-recorder faults, black-box crash tails, health
  findings and elections/restarts as event marks. Unreachable members
  mark the assembly ``incomplete=true`` with reasons — the trace
  assembly's semantics: partial timelines render, never drop.
- **Live dashboard** — :func:`render_top`: one ``copycat-tpu top``
  frame (per-group role/term/commit rate, lane mix, replication
  in-flight, worst health verdict) from the same ``/stats`` +
  ``/health`` payloads, refreshed in place by the CLI.

Retrospective onset detection for ``doctor --last N`` lives here too
(:func:`series_onsets`): "which retained series started breaching, and
when" — the time-correlation the present-tense findings cannot make.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Iterable

from . import knobs

#: eight-level unicode sparkline ramp (min → max over the rendered row)
SPARK = "▁▂▃▄▅▆▇█"

#: the curated series the timeline renders when ``--names`` is not
#: given: commit progress, election activity, backlog, and the health +
#: SLO verdict gauges — the signals every incident review starts from
DEFAULT_TIMELINE_PREFIXES = (
    "raft_commit_index", "raft_elections_started", "raft_commit_lag",
    "health.status", "slo.",
)


def series_sort_key(key: str) -> tuple:
    """Label-aware ordering: ``name{label}`` variants sort WITH their
    family (name first, then label set, then any histogram sub-key),
    not after every unlabeled name — ASCII ``{`` > letters, so a plain
    sort scatters per-group (``group=``) series away from their
    siblings. Numeric label values compare numerically (``group=2``
    before ``group=10``), so a wide multi-group render stays in shard
    order instead of lexicographic order."""
    brace = key.find("{")
    if brace < 0:
        return (key, (), "")
    end = key.find("}", brace)
    if end < 0:
        return (key, (), "")
    labels = []
    for part in key[brace + 1:end].split(","):
        name, _, value = part.partition("=")
        labels.append((name, (0, int(value)) if value.isdigit()
                       else (1, value)))
    return (key[:brace], tuple(labels), key[end + 1:])


def flatten_registry(snap: dict) -> tuple[dict, set]:
    """Flatten one metric-registry snapshot (``MetricsRegistry.
    snapshot()``) into numeric series, returning ``(values,
    gauge_keys)``. Histogram summaries expand to ``<name>.p50`` /
    ``<name>.p99`` (sampled like gauges) plus ``<name>.count``
    (cumulative, delta-encoded like a counter); the ``_gauge_keys``
    hint and ``uptime_s`` are dropped (wall time is the sample axis,
    not a series)."""
    gauges = set(snap.get("_gauge_keys", ()))
    values: dict = {}
    gauge_keys: set = set()
    for key, v in snap.items():
        if key in ("_gauge_keys", "uptime_s"):
            continue
        if isinstance(v, dict):
            if "count" in v and "mean" in v:  # histogram summary
                for q in ("p50", "p99"):
                    if q in v:
                        values[f"{key}.{q}"] = v[q]
                        gauge_keys.add(f"{key}.{q}")
                values[f"{key}.count"] = v["count"]
            continue
        if isinstance(v, bool):
            v = int(v)
        if isinstance(v, (int, float)):
            values[key] = v
            if key in gauges:
                gauge_keys.add(key)
    return values, gauge_keys


class SeriesStore:
    """The bounded, delta-encoded ring of periodic metric samples.

    One store per process role (member / ingress / supervisor), fed by
    that role's existing cadence via :meth:`maybe_sample` — the store
    itself never spawns a task. Counters land as per-interval deltas
    (sample N holds "how much this counter moved since sample N-1"),
    gauges as sampled values; eviction is oldest-first at
    ``COPYCAT_SERIES_WINDOW`` samples, so memory is bounded by
    ``window x live-series-count`` regardless of uptime."""

    def __init__(self, node: Any = "", role: str = "member",
                 interval_s: float | None = None,
                 window: int | None = None,
                 metrics: Any = None) -> None:
        self.node = str(node)
        self.role = role
        self.interval_s = max(0.05, interval_s if interval_s is not None
                              else knobs.get_float(
                                  "COPYCAT_SERIES_INTERVAL_S"))
        self.window = max(2, window if window is not None
                          else knobs.get_int("COPYCAT_SERIES_WINDOW"))
        self._samples: deque = deque(maxlen=self.window)
        self._prev_raw: dict = {}
        # next-due monotonic deadline: tolerant of the driving cadence's
        # jitter (a tick landing 1 ms early must not halve the rate)
        self._next_due = 0.0
        self.samples_taken = 0
        self.evictions = 0
        self._m_samples = self._m_evictions = self._m_names = None
        if metrics is not None:
            # the series.* self-family rides the host registry — and is
            # therefore itself sampled into the ring, like every family
            self._m_samples = metrics.counter("series.samples")
            self._m_evictions = metrics.counter("series.evictions")
            self._m_names = metrics.gauge("series.names")

    def maybe_sample(self, snap_fn: Callable[[], dict]) -> bool:
        """Called from the host's cadence (the health monitor tick, the
        ingress timer, the supervisor watch): takes a sample when
        ``interval_s`` has elapsed since the last one, else no-ops.
        ``snap_fn`` is only invoked when a sample is due — a store on a
        faster cadence than its interval pays nothing on skipped
        ticks."""
        now = time.monotonic()
        if now < self._next_due:
            return False
        # re-anchor on the schedule, not on `now`: drift-free when the
        # driving cadence matches interval_s, catch-up-free when the
        # host stalled for many intervals
        self._next_due = max(self._next_due + self.interval_s,
                             now + self.interval_s / 2)
        try:
            snap = snap_fn()
        except Exception:  # noqa: BLE001 - observability must never wound
            return False
        self.ingest(snap)
        return True

    def ingest(self, snap: dict, t: float | None = None) -> None:
        """Delta-encode one registry snapshot into the ring (exposed
        for tests and for bench, which samples at scenario boundaries
        rather than on a timer)."""
        flat, gauge_keys = flatten_registry(snap)
        values: dict = {}
        prev = self._prev_raw
        raw: dict = {}
        for key, v in flat.items():
            if key in gauge_keys:
                values[key] = v
            else:
                # counter: per-interval delta; a counter first seen this
                # sample contributes 0 (its history starts now)
                values[key] = v - prev.get(key, v)
                raw[key] = v
        self._prev_raw = raw
        if len(self._samples) == self._samples.maxlen:
            self.evictions += 1
            if self._m_evictions is not None:
                self._m_evictions.inc()
        self._samples.append(
            (round(time.time() if t is None else t, 3), values))
        self.samples_taken += 1
        if self._m_samples is not None:
            self._m_samples.inc()
        if self._m_names is not None:
            self._m_names.set(len(values))

    # -- query side --------------------------------------------------------

    def rows(self) -> list[tuple[float, dict]]:
        """The retained ``(wall_t, values)`` rows oldest-first — the
        in-process read the SLO detector judges without paying the JSON
        payload shape."""
        return list(self._samples)

    def payload(self, since: float | None = None,
                names: Iterable[str] | None = None) -> dict:
        """The ``/series`` JSON payload: retained samples, optionally
        windowed to ``t > since`` (wall seconds) and filtered to series
        whose flat name starts with any ``names`` prefix (labels
        included in the match, so ``raft_commit_index`` matches every
        ``raft_commit_index{group=}`` variant)."""
        prefixes = tuple(p for p in (names or ()) if p)
        rows = []
        for t, values in self._samples:
            if since is not None and t <= since:
                continue
            if prefixes:
                values = {k: v for k, v in values.items()
                          if any(k.startswith(p) for p in prefixes)}
            rows.append({"t": t, "values": values})
        return {
            "node": self.node,
            "role": self.role,
            "interval_s": self.interval_s,
            "window": self.window,
            "now": round(time.time(), 3),
            "samples_taken": self.samples_taken,
            "evictions": self.evictions,
            "samples": rows,
        }

    def render_text(self, since: float | None = None,
                    names: Iterable[str] | None = None) -> str:
        """The ``/series.txt`` human rendering: one sparkline row per
        retained series, family-sorted."""
        payload = self.payload(since=since, names=names)
        rows = payload["samples"]
        header = (f"{self.role} {self.node}: {len(rows)} sample(s), "
                  f"interval {self.interval_s}s, window {self.window}")
        if not rows:
            return header + "\n(no samples retained)\n"
        keys = sorted({k for r in rows for k in r["values"]},
                      key=series_sort_key)
        lines = [header]
        for key in keys:
            vals = [r["values"].get(key) for r in rows]
            present = [v for v in vals if v is not None]
            lines.append(f"{key:<52} {sparkline(vals):<{self.window}} "
                         f"min {min(present):g} max {max(present):g}")
        return "\n".join(lines) + "\n"


def sparkline(values: list) -> str:
    """Scale a row of samples onto the eight-level ramp (``None`` =
    a gap, rendered as a space). A flat row renders at the floor — the
    interesting signal is variation, not magnitude."""
    present = [v for v in values if v is not None]
    if not present:
        return ""
    lo, hi = min(present), max(present)
    span = hi - lo
    out = []
    for v in values:
        if v is None:
            out.append(" ")
        elif span <= 0:
            out.append(SPARK[0])
        else:
            out.append(SPARK[int((v - lo) / span * (len(SPARK) - 1))])
    return "".join(out)


def resample(samples: list[dict], key: str, t0: float, t1: float,
             buckets: int) -> list:
    """Bucket one member's retained series onto a common time grid
    (mean per bucket, ``None`` for empty buckets) — what time-aligns
    sparklines across members whose sample clocks are not in phase."""
    if buckets <= 0 or t1 <= t0:
        return []
    sums = [0.0] * buckets
    counts = [0] * buckets
    width = (t1 - t0) / buckets
    for row in samples:
        t = row.get("t", 0.0)
        v = row.get("values", {}).get(key)
        if v is None or t < t0 or t > t1:
            continue
        i = min(buckets - 1, int((t - t0) / width))
        sums[i] += v
        counts[i] += 1
    return [sums[i] / counts[i] if counts[i] else None
            for i in range(buckets)]


# ---------------------------------------------------------------------------
# the cluster-merged timeline
# ---------------------------------------------------------------------------

#: flight/black-box kinds the timeline renders as event marks (anything
#: else — raw telemetry notes — would drown the marks that matter)
_EVENT_KINDS = ("fault", "boot", "health", "invariant_violation",
                "slow_trace", "loop_stall")


def _event_detail(ev: dict) -> str:
    return " ".join(f"{k}={v}" for k, v in ev.items()
                    if k not in ("seq", "t", "round", "kind", "recovered"))


def _member_events(member: str, payload: dict) -> list[dict]:
    """Event marks for one member: flight-ring events, black-box events
    (the crash-surviving superset — recovered tails included), and
    election spikes derived from the retained series (any interval
    where the elections counter moved)."""
    events: list[dict] = []
    seen: set = set()
    flight = payload.get("flight") or {}
    blackbox = flight.get("blackbox") or {}
    for ev in list(flight.get("events") or ()) \
            + list(blackbox.get("events") or ()):
        kind = ev.get("kind", "")
        if kind not in _EVENT_KINDS:
            continue
        detail = _event_detail(ev)
        dedup = (ev.get("t"), kind, detail)
        if dedup in seen:  # ring events spill into the black-box too
            continue
        seen.add(dedup)
        events.append({"t": ev.get("t", 0.0), "member": member,
                       "kind": kind, "detail": detail,
                       "recovered": bool(ev.get("recovered"))})
    series = payload.get("series") or {}
    for row in series.get("samples", ()):
        for key, v in row.get("values", {}).items():
            if key.startswith("raft_elections_started") and v:
                events.append({"t": row["t"], "member": member,
                               "kind": "election",
                               "detail": f"+{int(v)} election(s)"
                               + (key[key.find("{"):]
                                  if "{" in key else "")})
    return events


def assemble_timeline(members: dict[str, dict],
                      failed_members: Iterable[str] = (),
                      last_s: float = 60.0,
                      names: Iterable[str] | None = None,
                      buckets: int = 60) -> dict:
    """Merge per-member ``/series`` + ``/flight`` + ``/health``
    payloads into one cluster timeline.

    ``members`` maps a member address to ``{"series": <//series JSON>,
    "flight": <//flight JSON>, "health": <//health JSON>}`` (any value
    may be ``None`` when that route failed); addresses whose fan-out
    failed entirely go in ``failed_members``. Either kind of gap marks
    the timeline ``incomplete=true`` with reasons — the trace
    assembly's semantics: partial timelines render, never drop."""
    failed = sorted(set(failed_members))
    incomplete_why = [f"member {m} unreachable" for m in failed]
    prefixes = tuple(p for p in (names or DEFAULT_TIMELINE_PREFIXES) if p)
    # the grid end: the freshest clock any member reported (their
    # /series `now`), so a quiet cluster still renders a full window
    t1 = 0.0
    for payload in members.values():
        series = (payload or {}).get("series") or {}
        t1 = max(t1, series.get("now", 0.0))
        for row in series.get("samples", ()):
            t1 = max(t1, row.get("t", 0.0))
    if t1 <= 0.0:
        t1 = time.time()
    t0 = t1 - max(1.0, last_s)
    buckets = max(4, min(int(buckets), 240))

    events: list[dict] = []
    member_series: dict[str, dict] = {}
    member_roles: dict[str, str] = {}
    for addr in sorted(members):
        payload = members[addr] or {}
        series = payload.get("series")
        health = payload.get("health") or {}
        member = series.get("node") if series else None
        member = member or health.get("node") or addr
        member_roles[member] = (health.get("role")
                                or (series or {}).get("role") or "?")
        if series is None:
            incomplete_why.append(
                f"member {member} serves no /series "
                f"(COPYCAT_SERIES=0 or a pre-series build)")
        rows = [r for r in (series or {}).get("samples", ())
                if t0 <= r.get("t", 0.0) <= t1]
        keys = sorted(
            {k for r in rows for k in r["values"]
             if any(k.startswith(p) for p in prefixes)},
            key=series_sort_key)
        member_series[member] = {
            key: resample(rows, key, t0, t1, buckets) for key in keys}
        events.extend(e for e in _member_events(member, payload)
                      if t0 <= e["t"] <= t1 or e.get("recovered"))
    events.sort(key=lambda e: (e["t"], e["member"], e["kind"]))
    return {
        "window_s": round(t1 - t0, 3),
        "t0": round(t0, 3),
        "t1": round(t1, 3),
        "buckets": buckets,
        "members": sorted(member_series),
        "roles": member_roles,
        "incomplete": bool(incomplete_why),
        "incomplete_why": incomplete_why,
        "series": member_series,
        "events": events,
    }


def render_timeline(timeline: dict) -> str:
    """The human rendering: a window banner, per-member time-aligned
    sparklines (one common grid — column K is the same instant on every
    row), then the merged event marks in time order. Incomplete
    timelines carry a loud banner — rendered, never dropped."""
    t0, t1 = timeline["t0"], timeline["t1"]
    lines = [f"cluster timeline: {len(timeline['members'])} member(s), "
             f"window {timeline['window_s']:.0f}s "
             f"({time.strftime('%H:%M:%S', time.localtime(t0))} -> "
             f"{time.strftime('%H:%M:%S', time.localtime(t1))})"]
    if timeline["incomplete"]:
        lines.append("!! INCOMPLETE: "
                     + "; ".join(timeline["incomplete_why"]))
    for member in timeline["members"]:
        role = timeline.get("roles", {}).get(member, "?")
        lines.append(f"{member} [{role}]")
        rows = timeline["series"].get(member, {})
        if not rows:
            lines.append("  (no series retained in the window)")
        for key in sorted(rows, key=series_sort_key):
            vals = rows[key]
            present = [v for v in vals if v is not None]
            span = (f"min {min(present):g} max {max(present):g}"
                    if present else "no data")
            lines.append(f"  {key:<36} {sparkline(vals)}  {span}")
    lines.append(f"events ({len(timeline['events'])}):")
    if not timeline["events"]:
        lines.append("  (none in the window)")
    for ev in timeline["events"]:
        mark = time.strftime("%H:%M:%S", time.localtime(ev["t"]))
        rec = " (recovered)" if ev.get("recovered") else ""
        lines.append(f"  {mark} +{max(0.0, ev['t'] - t0):6.1f}s "
                     f"{ev['member']:<22} {ev['kind']:<10} "
                     f"{ev['detail']}{rec}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# the live dashboard (`copycat-tpu top`)
# ---------------------------------------------------------------------------


def _rate(flat: dict, prev: dict | None, prefix: str, dt: float) -> float:
    """Aggregate delta/sec across every flat key in one family —
    ``prefix`` matches the unlabeled key AND its ``{group=}`` labeled
    variants, so the same arithmetic serves single- and multi-group
    members."""
    if not prev or dt <= 0:
        return 0.0
    total = 0.0
    for key, v in flat.items():
        if key.startswith(prefix) and key in prev:
            total += (v - prev[key]) / dt
    return total


def _lane_mix(flat: dict, prev: dict | None, dt: float) -> str:
    fast = _rate(flat, prev, "raft.commands_fast_lane", dt)
    general = _rate(flat, prev, "raft.commands_general_lane", dt)
    single = _rate(flat, prev, "raft.commands_single_lane", dt)
    total = fast + general + single
    if total <= 0:
        return "-"
    return (f"{100 * fast / total:.0f}/{100 * general / total:.0f}"
            f"/{100 * single / total:.0f}%")


def render_top(members: dict[str, dict], failed: Iterable[str] = (),
               prev: dict | None = None, dt: float = 0.0
               ) -> tuple[str, dict]:
    """One ``copycat-tpu top`` frame from per-member ``/stats`` +
    ``/health`` payloads: cluster banner (worst health verdict first —
    the one line an operator reads), then one row per member with
    per-group role/term/commit rate, the command lane mix
    (fast/general/single %), and replication in-flight. Returns
    ``(frame, state)`` where ``state`` feeds the next frame's rates.
    Unreachable members render as rows, never drop."""
    from ..cli import _flatten_numeric  # the stats flattening, one home

    statuses = []
    state: dict = {}
    rows: list[str] = []
    for addr in sorted(members):
        payload = members[addr] or {}
        stats = payload.get("stats") or {}
        health = payload.get("health") or {}
        status = health.get("status", "unknown")
        statuses.append(status)
        flat = _flatten_numeric(stats)
        state[addr] = flat
        mprev = (prev or {}).get(addr)
        node = stats.get("node", addr)
        groups = stats.get("groups") or {}
        inflight = sum(v for k, v in flat.items()
                       if k.startswith("raft.repl.windows_inflight"))
        if groups:
            led = sum(1 for g in groups.values()
                      if g.get("role") == "leader")
            role = f"{led}/{len(groups)} led"
        else:
            role = stats.get("role", "?")
        # rates need two polls — the first frame says so instead of
        # rendering a misleading 0.0/s
        if mprev and dt > 0:
            r = _rate(flat, mprev, "raft.raft_commit_index", dt)
            commit = f"{r:>9.1f}/s"
        else:
            commit = f"{'-':>11}"
        rows.append(f"  {node:<22} {role:<10} t{stats.get('term', 0):<5} "
                    f"{commit}  "
                    f"{_lane_mix(flat, mprev, dt):<12} "
                    f"infl {inflight:<5} {status}")
        for gid in sorted(groups, key=lambda s: int(s)):
            g = groups[gid]
            g_rate = _rate(flat, mprev, f"groups.{gid}.commit_index", dt)
            rows.append(f"    group {gid}: {g.get('role', '?'):<9} "
                        f"t{g.get('term', 0):<5} "
                        f"commit {g.get('commit_index', 0)} "
                        f"({g_rate:+.1f}/s) lag "
                        f"{g.get('log_last_index', 0) - g.get('commit_index', 0)}")
    for addr in sorted(set(failed)):
        statuses.append("unreachable")
        rows.append(f"  {addr:<22} UNREACHABLE")
    verdict = "unknown"
    for s in ("critical", "warn", "unreachable", "ok"):
        if s in statuses:
            verdict = s
            break
    banner = (f"=== cluster top {time.strftime('%H:%M:%S')} — "
              f"{len(members)}/{len(members) + len(set(failed))} "
              f"member(s) up, worst health: {verdict.upper()} ===")
    header = (f"  {'member':<22} {'role':<10} {'term':<6} "
              f"{'commit/s':>9}  {'lanes f/g/s':<12} {'repl':<10} health")
    return "\n".join([banner, header] + rows), state


def top_payload(members: dict[str, dict], failed: Iterable[str] = (),
                prev: dict | None = None, dt: float = 0.0
                ) -> tuple[dict, dict]:
    """The machine-readable sibling of :func:`render_top` (parity with
    ``timeline --json``): one frame as JSON — per-member role/term/
    health/commit-rate plus per-group cursors — for the CI smoke and
    any scripted poll, so nobody scrapes the text dashboard. Returns
    ``(payload, state)``; rates need two polls, so a first frame (no
    ``prev``) carries ``commit_rate: null``, never a misleading 0.0.
    Unreachable members land in ``failed`` as rows of their own —
    reported, never dropped."""
    from ..cli import _flatten_numeric  # the stats flattening, one home

    statuses: list[str] = []
    state: dict = {}
    out_members: dict = {}
    for addr in sorted(members):
        payload = members[addr] or {}
        stats = payload.get("stats") or {}
        health = payload.get("health") or {}
        status = health.get("status", "unknown")
        statuses.append(status)
        flat = _flatten_numeric(stats)
        state[addr] = flat
        mprev = (prev or {}).get(addr)
        node = str(stats.get("node", addr))
        groups = stats.get("groups") or {}
        have_rates = bool(mprev) and dt > 0
        row: dict = {
            "role": stats.get("role", "?"),
            "term": stats.get("term", 0),
            "health": status,
            "inflight": sum(v for k, v in flat.items()
                            if k.startswith("raft.repl.windows_inflight")),
            "commit_rate": round(_rate(flat, mprev,
                                       "raft.raft_commit_index", dt), 3)
            if have_rates else None,
            "groups": {},
        }
        if groups:
            row["groups_led"] = sum(1 for g in groups.values()
                                    if g.get("role") == "leader")
        for gid in sorted(groups, key=lambda s: int(s)):
            g = groups[gid]
            row["groups"][gid] = {
                "role": g.get("role", "?"),
                "term": g.get("term", 0),
                "commit_index": g.get("commit_index", 0),
                "lag": (g.get("log_last_index", 0)
                        - g.get("commit_index", 0)),
                "commit_rate": round(_rate(flat, mprev,
                                           f"groups.{gid}.commit_index",
                                           dt), 3)
                if have_rates else None,
            }
        out_members[node] = row
    failed_rows = sorted(set(failed))
    statuses += ["unreachable"] * len(failed_rows)
    verdict = "unknown"
    for s in ("critical", "warn", "unreachable", "ok"):
        if s in statuses:
            verdict = s
            break
    return ({"now": round(time.time(), 3),
             "members": out_members,
             "failed": failed_rows,
             "worst_health": verdict}, state)


# ---------------------------------------------------------------------------
# retrospective onset detection (`doctor --last N`)
# ---------------------------------------------------------------------------


def series_onsets(series_payload: dict, prefixes: Iterable[str],
                  factor: float = 3.0, cap: int = 8) -> list[dict]:
    """Scan one member's retained window for series that *started
    breaching*: the earliest sample where a series exceeded ``factor``
    x its window median (or simply became non-zero when the median is
    zero — the election/violation counters' shape). Returns rows of
    ``{key, t, ago_s, value, median}``, newest-breach last, at most
    ``cap`` — what lets ``doctor --last N`` say "fsync latency started
    climbing 40 s before the stall" instead of only grading the
    present."""
    rows = (series_payload or {}).get("samples") or []
    now = (series_payload or {}).get("now") or time.time()
    prefixes = tuple(prefixes)
    by_key: dict[str, list] = {}
    for row in rows:
        for key, v in row.get("values", {}).items():
            if any(key.startswith(p) for p in prefixes):
                by_key.setdefault(key, []).append((row["t"], v))
    onsets = []
    for key, points in by_key.items():
        values = sorted(v for _, v in points)
        median = values[len(values) // 2]
        threshold = factor * median if median > 0 else 0
        onset = None
        for t, v in points:
            if v > threshold:
                onset = (t, v)
                break
        if onset is None:
            continue
        # a series ALWAYS above threshold has no onset in the window —
        # it was already breaching when retention began; say so rather
        # than claiming the window's first sample is the start
        began = onset[0] > points[0][0]
        onsets.append({"key": key, "t": onset[0],
                       "ago_s": round(max(0.0, now - onset[0]), 1),
                       "value": onset[1], "median": median,
                       "from_window_start": not began})
    onsets.sort(key=lambda o: o["t"])
    return onsets[:cap]


__all__ = [
    "SeriesStore", "assemble_timeline", "render_timeline", "render_top",
    "top_payload", "series_onsets", "series_sort_key", "sparkline",
    "flatten_registry", "resample", "DEFAULT_TIMELINE_PREFIXES",
]
