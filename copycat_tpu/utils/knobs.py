"""Central registry for every ``COPYCAT_*`` environment knob.

Every env knob the tree reads is declared HERE, once, with a typed
default and a one-line doc — and read through the typed getters below.
Two gates keep that true:

- the ``knob-registry`` copycheck rule (``copycat_tpu/analysis``) flags
  any direct ``os.environ`` / ``os.getenv`` read of a ``COPYCAT_*``
  name outside this module, and any ``knobs.get_*`` call naming an
  unregistered knob;
- ``tests/test_knobs.py`` asserts the README's *Knob reference* section
  is byte-identical to :func:`render_markdown` (regenerate with
  ``python -m copycat_tpu.utils.knobs``).

Getters read ``os.environ`` live (no caching): tests and benches
monkeypatch knobs mid-process and expect the next server/client built
to see the change — exactly what the raw reads they replace did.

Call sites whose default is computed (e.g. ``COPYCAT_SNAPSHOT_RETAIN``
defaults to ``max(64, repl max-inflight)``) pass ``default=`` at the
call; the registry carries a ``default_doc`` string so the README table
still documents the rule. Boolean knobs normalize: ``0 / false / off /
no / none`` and the empty string are off, anything else set is on.

This module is import-light on purpose (stdlib ``os`` only): the lint
CLI, the README generator, and the analysis rules all load it without
touching jax.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any

_FALSY = ("", "0", "false", "off", "no", "none")


@dataclass(frozen=True)
class Knob:
    name: str
    kind: str  # "int" | "float" | "str" | "bool" | "raw"
    default: Any  # typed default; None = computed at the call site / unset
    doc: str  # one-line effect (the README table cell)
    section: str
    default_doc: str | None = None  # README text for computed defaults
    choices: tuple[str, ...] | None = None

    def default_text(self) -> str:
        if self.default_doc is not None:
            return self.default_doc
        if self.default is None:
            return "unset"
        if self.kind == "bool":
            return "1" if self.default else "0"
        return str(self.default) or "(empty)"


REGISTRY: dict[str, Knob] = {}

# README section order; every knob names one of these.
SECTIONS = (
    ("server", "Server planes (vector + read pump)"),
    ("replication", "Replication pipeline"),
    ("deploy", "Deployment plane (`copycat-tpu cluster`)"),
    ("durability", "Snapshots & durability"),
    ("observability", "Observability & invariants"),
    ("client", "Client"),
    ("platform", "Platform & device probing"),
    ("bench", "Bench scenarios (`bench.py`)"),
    ("scaling", "Multichip scaling driver"),
    ("verdict", "Linearizability verdict runner"),
)
_SECTION_KEYS = tuple(key for key, _ in SECTIONS)


def _knob(name: str, kind: str, default: Any, doc: str, *, section: str,
          default_doc: str | None = None,
          choices: tuple[str, ...] | None = None) -> None:
    assert name not in REGISTRY, f"duplicate knob {name}"
    assert section in _SECTION_KEYS, f"unknown section {section!r} ({name})"
    REGISTRY[name] = Knob(name, kind, default, doc, section, default_doc,
                          choices)


# --- server planes ---------------------------------------------------------
_knob("COPYCAT_GROUPS", "int", 1,
      "Raft groups per server (keyspace shards, docs/SHARDING.md); >1 "
      "spreads leadership and routes resources by hash", section="server")
_knob("COPYCAT_MULTI_GROUP", "bool", True,
      "`0` forces the single-group plane regardless of `COPYCAT_GROUPS` "
      "(the sharding A/B)", section="server")
_knob("COPYCAT_SERVER_VECTOR_PUMP", "bool", True,
      "`0` restores the per-op command apply lane (the spi A/B)",
      section="server")
_knob("COPYCAT_SERVER_READ_PUMP", "bool", True,
      "`0` restores the per-op read lane (the readmix A/B)",
      section="server")
_knob("COPYCAT_PARALLEL_APPLY", "bool", True,
      "`0` restores the contiguous-run vector classifier — runs no "
      "longer span ineligible entries on disjoint keys (the "
      "dependency-classified parallel-apply A/B, docs/SHARDING.md)",
      section="server")
_knob("COPYCAT_APPLY_FUSE", "bool", True,
      "`0` restores one engine dispatch per group per run — staged "
      "vector runs no longer fuse across groups into one device round "
      "per server turn (the cross-group fusion A/B)", section="server")

# --- replication -----------------------------------------------------------
_knob("COPYCAT_REPL_PIPELINE", "bool", True,
      "`0` restores stop-and-wait replication (the A/B lane)",
      section="replication")
_knob("COPYCAT_REPL_WINDOW", "int", 64,
      "append window size: pipeline initial/ceiling AND the stop-and-wait "
      "window", section="replication")
_knob("COPYCAT_REPL_DEPTH", "int", 8,
      "max append windows in flight per peer", section="replication")
_knob("COPYCAT_REPL_MAX_INFLIGHT", "int", None, default_doc="window×depth",
      doc="max entries in flight per peer (slow-follower memory bound)",
      section="replication")

# --- deployment plane ------------------------------------------------------
_knob("COPYCAT_INGRESS_TIER", "bool", True,
      "`0` removes the standalone ingress/proxy tier: members refuse "
      "ingress-kind proxy traffic (single-group servers register no "
      "ProxyRequest handler), topologies/benches deploy no ingress "
      "processes — the in-server ingress path bit-identically "
      "(docs/DEPLOYMENT.md)", section="deploy")
_knob("COPYCAT_DEPLOY_HEALTH_INTERVAL_S", "float", 1.0,
      "supervisor `/healthz` poll cadence per child process",
      section="deploy")
_knob("COPYCAT_DEPLOY_RESTART_BACKOFF_S", "float", 0.5,
      "initial restart backoff after a child crash (doubles per "
      "consecutive crash)", section="deploy")
_knob("COPYCAT_DEPLOY_RESTART_MAX_S", "float", 8.0,
      "restart backoff ceiling", section="deploy")
_knob("COPYCAT_DEPLOY_GRACE_S", "float", 5.0,
      "seconds between SIGTERM and SIGKILL at teardown",
      section="deploy")

# --- durability ------------------------------------------------------------
_knob("COPYCAT_SNAPSHOTS", "bool", True,
      "`0` restores replay-only recovery bit-identically (the A/B lane)",
      section="durability")
_knob("COPYCAT_SNAPSHOT_ENTRIES", "int", 1024,
      "applied entries between snapshots (bounds recovery replay)",
      section="durability")
_knob("COPYCAT_SNAPSHOT_RETAIN", "int", None,
      default_doc="max(64, repl max-inflight)",
      doc="entries kept behind the snapshot so lagging-but-healthy "
          "followers avoid an install", section="durability")
_knob("COPYCAT_SNAP_CHUNK", "int", 262144,
      "install-stream chunk bytes", section="durability")

# --- observability ---------------------------------------------------------
_knob("COPYCAT_TRACE", "bool", False,
      "per-request tracing (`utils/tracing.py`); zero-cost when off",
      section="observability")
_knob("COPYCAT_TRACE_CAPACITY", "int", 512,
      "traces held in the per-process ring before oldest-first eviction "
      "(evicted ids are tombstoned, never resurrected)",
      section="observability")
_knob("COPYCAT_TRACE_SLOW_MS", "float", 100.0,
      "traced requests slower than this land a `slow_trace` exemplar in "
      "the device-plane flight recorder", section="observability")
_knob("COPYCAT_TELEMETRY", "bool", False,
      "compile the device telemetry block into engines whose `Config` "
      "left it off", section="observability")
_knob("COPYCAT_INVARIANTS", "str", None, default_doc="unset (= observe)",
      choices=("observe", "strict", "off"),
      doc="invariant monitors, device + server: `observe` counts "
          "violations, `strict` raises, `off` skips checks; setting any "
          "mode also enables device telemetry", section="observability")
_knob("COPYCAT_INVARIANT_LEADERLESS_MAX", "float", 1.0,
      "max leaderless-group fraction per fetched round before the "
      "monitor trips", section="observability")
_knob("COPYCAT_HEALTH", "bool", True,
      "`0` disables the health plane (online anomaly detectors, the "
      "`/health` verdict, the durable black-box spill) — the A/B knob "
      "restoring the pre-health plane bit-identically",
      section="observability")
_knob("COPYCAT_HEALTH_INTERVAL_S", "float", 1.0,
      "detector cadence: seconds between health-monitor samples",
      section="observability")
_knob("COPYCAT_HEALTH_WINDOW", "int", 30,
      "samples retained per evidence series (the detector lookback "
      "window)", section="observability")
_knob("COPYCAT_HEALTH_CHURN_WARN", "int", 3,
      "elections + leader transitions per window that grade "
      "leader-churn `warn` (2x grades `critical`)",
      section="observability")
_knob("COPYCAT_HEALTH_STALL_S", "float", 2.0,
      "seconds the commit index may sit frozen behind the log tail "
      "before commit-stall grades `warn` (growing lag grades "
      "`critical`)", section="observability")
_knob("COPYCAT_HEALTH_FSYNC_FACTOR", "float", 4.0,
      "fsync latency vs the pre-window EWMA baseline that grades "
      "fsync-spike `warn` (3x the factor grades `critical`)",
      section="observability")
_knob("COPYCAT_HEALTH_QUEUE_WARN", "int", 64,
      "ingress/event backlog depth that grades ingress-backlog `warn` "
      "when still growing (4x grades `critical`)",
      section="observability")
_knob("COPYCAT_HEALTH_EXPIRY_WARN", "int", 3,
      "session expiries per window that grade expiry-storm `warn` "
      "(3x grades `critical`)", section="observability")
_knob("COPYCAT_BLACKBOX_BYTES", "int", 262144,
      "black-box spill bytes per generation (two generations kept; "
      "the crash-surviving flight-recorder ring on disk)",
      section="observability")
_knob("COPYCAT_SERIES", "bool", True,
      "`0` disables the retrospective-telemetry plane (the on-member "
      "time-series ring, the `/series` routes, the `series.*`/`slo.*` "
      "families) — the A/B knob restoring the pre-series plane "
      "bit-identically; on members the ring rides the health-monitor "
      "cadence, so `COPYCAT_HEALTH=0` also removes it",
      section="observability")
_knob("COPYCAT_SERIES_INTERVAL_S", "float", 1.0,
      "seconds between retained metric samples (`utils/timeseries.py`; "
      "sampling piggybacks the host cadence, so the effective interval "
      "is at least the health/watch cadence)", section="observability")
_knob("COPYCAT_SERIES_WINDOW", "int", 300,
      "samples retained per process before oldest-first eviction — "
      "the `/series` lookback is `interval x window` seconds",
      section="observability")
_knob("COPYCAT_SLO_P99_MS", "float", None,
      default_doc="unset (= no latency objective)",
      doc="commit-latency p99 objective in ms: the `slo_burn` detector "
          "grades intervals whose sampled `latency.commit_ms` p99 "
          "exceeds it (needs tracing on — the histogram only advances "
          "for traced requests)", section="observability")
_knob("COPYCAT_SLO_AVAIL", "float", None,
      default_doc="unset (= no availability objective)",
      doc="availability objective as a fraction (e.g. `0.999`): an "
          "interval counts unavailable when a group's commit sat "
          "frozen behind its log tail; the `slo_burn` detector grades "
          "the error-budget burn rate over the retained window",
      section="observability")
_knob("COPYCAT_PROFILE", "bool", True,
      "`0` disables the continuous profiling plane (the process-wide "
      "wall-stack sampler, event-loop hold attribution, the "
      "`/profile` routes, the `profile.*` family and the `loop_stall` "
      "detector) — the A/B knob restoring the pre-profiler process "
      "bit-identically: no sampler thread, no keys, no routes",
      section="observability")
_knob("COPYCAT_PROFILE_HZ", "float", 19.0,
      "wall-stack samples per second (`utils/profiler.py`; "
      "deliberately off-cadence from the 1 Hz health/series timers so "
      "samples don't alias the periodic work they profile)",
      section="observability")
_knob("COPYCAT_PROFILE_HOLD_MS", "float", 100.0,
      "event-loop hold threshold in ms: a callback/task step holding "
      "the loop at least this long records a hold (the `loop_stall` "
      "evidence, a flight-recorder stall note, and the "
      "`profile.hold_*` series); 5x grades `critical`",
      section="observability")
_knob("COPYCAT_PROFILE_WINDOW_S", "int", 120,
      "seconds of folded-stack aggregate retained in the profile ring "
      "before oldest-first eviction — the `/profile` lookback "
      "(`?since=` windows within it)", section="observability")

# --- client ----------------------------------------------------------------
_knob("COPYCAT_CLIENT_FOLLOWER_READS", "bool", True,
      "`0` pins sub-linearizable reads back to the leader connection",
      section="client")
_knob("COPYCAT_EDGE_READS", "bool", True,
      "`0` removes the edge read tier (client-local CRDT replicas "
      "serving CAUSAL/SEQUENTIAL reads; docs/EDGE_READS.md) — every "
      "read pays the server round-trip, bit-identically to the "
      "pre-edge plane", section="client")
_knob("COPYCAT_EDGE_MAX_RESOURCES", "int", 1024,
      "client-side edge replica cap (LRU eviction back to server "
      "reads; evicted instances unsubscribe via the next keep-alive)",
      section="client")
_knob("COPYCAT_EDGE_TTL_S", "float", 5.0,
      "edge staleness gate: a replica entry older than this (no delta, "
      "no re-seed) stops serving locally and the next read re-seeds "
      "from the server", section="client")
_knob("COPYCAT_EDGE_FLUSH_MS", "float", 10.0,
      "server-side delta-publication coalescing interval: dirty "
      "resources batch for this long before one push per subscriber "
      "(state-based merge makes coalescing free); `0` flushes every "
      "event-loop turn", section="client")

# --- platform --------------------------------------------------------------
_knob("COPYCAT_COMPILE_CACHE", "raw", None,
      default_doc="`~/.cache/copycat_tpu/xla`",
      doc="XLA compile-cache directory; `0` or empty disables",
      section="platform")
_knob("COPYCAT_DEVICE_TIMEOUT", "float", 120.0,
      "seconds per device-enumeration probe before declaring the "
      "accelerator unreachable", section="platform")
_knob("COPYCAT_DEVICE_PROBES", "int", None,
      default_doc="5 (entry dryrun: 2)",
      doc="device-enumeration probe attempts before failing",
      section="platform")
_knob("COPYCAT_ENTRY_DEVICE_TIMEOUT", "float", 120.0,
      "probe timeout for the `__graft_entry__` multichip dryrun",
      section="platform")
_knob("COPYCAT_BENCH_DEVICE_TIMEOUT", "float", 120.0,
      "probe timeout for bench runs (failed probes fall back to CPU "
      "unless `COPYCAT_BENCH_NO_CPU_FALLBACK=1`)", section="platform")
_knob("COPYCAT_VERDICT_DEVICE_TIMEOUT", "float", 120.0,
      "probe timeout for the verdict runner", section="platform")

# --- bench -----------------------------------------------------------------
_knob("COPYCAT_BENCH_SCENARIO", "str", "counter",
      "scenario: `counter`/`election`/`map`/`map_read`/`lock`/`mixed`/"
      "`host`/`host_read`/`session`/`spi`/`readmix`/`cluster`/`sharded`/"
      "`apply`/`recovery`/`compartment`/`fanout`",
      section="bench")
_knob("COPYCAT_BENCH_GROUPS", "int", None,
      default_doc="10000 (election: 1000)",
      doc="Raft groups in the engine tensor", section="bench")
_knob("COPYCAT_BENCH_PEERS", "int", 3, "peer lanes per group",
      section="bench")
_knob("COPYCAT_BENCH_LOG_SLOTS", "int", None,
      default_doc="64 (mixed: 32)",
      doc="log-ring slots per group", section="bench")
_knob("COPYCAT_BENCH_ROUNDS", "int", 200, "engine rounds per repetition",
      section="bench")
_knob("COPYCAT_BENCH_REPEATS", "int", 5,
      "best-of-N repetitions recorded", section="bench")
_knob("COPYCAT_BENCH_SUBMIT_SLOTS", "int", 16,
      "submit slots per group (append window / applies-per-round floor)",
      section="bench")
_knob("COPYCAT_BENCH_PALLAS", "raw", None,
      default_doc="auto (TPU: on, CPU: off)",
      doc="`1` forces the Pallas quorum-tally kernel, any other set "
          "value forces the jnp path", section="bench")
_knob("COPYCAT_BENCH_POOL_BUDGETS", "str", None,
      default_doc="per-scenario",
      doc="comma-separated per-pool apply budgets "
          "(value,map,set,queue,lock,election,multimap,topic); empty = "
          "single sequential scan", section="bench")
_knob("COPYCAT_BENCH_PROFILE", "str", "",
      "directory for an XLA profiler trace of the first timed repetition",
      section="bench")
_knob("COPYCAT_BENCH_TELEMETRY", "bool", False,
      "compile device telemetry into the measured step (the round-8 "
      "on-cost A/B)", section="bench")
_knob("COPYCAT_BENCH_TIMER_MIN", "int", None,
      default_doc="4 (mixed: 2)",
      doc="election timer lower bound, rounds", section="bench")
_knob("COPYCAT_BENCH_TIMER_MAX", "int", None,
      default_doc="9 (mixed: 4)",
      doc="election timer upper bound, rounds", section="bench")
_knob("COPYCAT_BENCH_HOST_MODE", "str", "deep",
      choices=("deep", "deepscan", "bulk", "queued"),
      doc="host-scenario driver lane", section="bench")
_knob("COPYCAT_BENCH_HOST_BURST", "int", None,
      default_doc="submit_slots×8 (queued: ×1)",
      doc="ops per group per burst for the host/host_read scenarios",
      section="bench")
_knob("COPYCAT_BENCH_SESSIONS", "int", 16,
      "sessions per group for the session scenario", section="bench")
_knob("COPYCAT_BENCH_SESSION_SCAN", "bool", False,
      "`1` drives the session scenario through the fused deep_scan",
      section="bench")
_knob("COPYCAT_BENCH_SPI_INSTANCES", "int", 1000,
      "resource instances (sessions) for the spi/readmix scenarios",
      section="bench")
_knob("COPYCAT_BENCH_SPI_BURSTS", "int", 5,
      "bursts per repetition for the spi/readmix scenarios",
      section="bench")
_knob("COPYCAT_BENCH_SPI_PAYLOAD", "str", "int", choices=("int", "str"),
      doc="`int` = device-resident counters, `str` = host-shadow map "
          "cliff", section="bench")
_knob("COPYCAT_BENCH_SPI_POOLS", "str", None,
      default_doc="counters (str payload: all)",
      choices=("counters", "all"),
      doc="engine pool provisioning for the spi scenario", section="bench")
_knob("COPYCAT_BENCH_SPI_WAVES", "int", 1,
      "client pipelining depth (commands in flight per instance)",
      section="bench")
_knob("COPYCAT_BENCH_SPI_TRANSPORT", "str", "local",
      choices=("local", "tcp", "native"),
      doc="transport under the spi scenario", section="bench")
_knob("COPYCAT_BENCH_SPI_LOG_SLOTS", "int", 16,
      "engine log-ring slots for the spi/readmix scenarios",
      section="bench")
_knob("COPYCAT_BENCH_READMIX_READS", "int", 9,
      "reads per write in the readmix scenario", section="bench")
_knob("COPYCAT_BENCH_READMIX_LEVEL", "str", "atomic",
      choices=("atomic", "sequential", "none", "linearizable"),
      doc="read consistency the readmix scenario requests", section="bench")
_knob("COPYCAT_BENCH_READ_LEVEL", "str", "sequential",
      choices=("sequential", "atomic"),
      doc="read consistency for the map_read/host_read scenarios",
      section="bench")
_knob("COPYCAT_BENCH_CLUSTER_STORAGE", "str", "memory",
      choices=("memory", "mapped", "disk"),
      doc="log storage level for the cluster scenario (the durability "
          "A/B; `bench.py --storage` sets it)", section="bench")
_knob("COPYCAT_BENCH_CLUSTER_MEMBERS", "int", 3,
      "cluster scenario member count", section="bench")
_knob("COPYCAT_BENCH_CLUSTER_CLIENTS", "int", 4,
      "concurrent clients in the cluster scenario", section="bench")
_knob("COPYCAT_BENCH_CLUSTER_OPS", "int", 1500,
      "ops per client per burst in the cluster scenario", section="bench")
_knob("COPYCAT_BENCH_CLUSTER_BURSTS", "int", 5,
      "bursts (best-of) in the cluster scenario", section="bench")
_knob("COPYCAT_BENCH_CLUSTER_DELAY_MS", "float", 2.0,
      "nemesis wire latency per leg, ms", section="bench")
_knob("COPYCAT_BENCH_SHARDED_GROUPS", "int", 4,
      "Raft groups in the sharded scenario (1 = the single-group A/B "
      "baseline)", section="bench")
_knob("COPYCAT_BENCH_SHARDED_CLIENTS", "int", 12,
      "concurrent public-API clients in the sharded scenario",
      section="bench")
_knob("COPYCAT_BENCH_SHARDED_OPS", "int", 1200,
      "commands per client per burst in the sharded scenario",
      section="bench")
_knob("COPYCAT_BENCH_SHARDED_BURSTS", "int", 5,
      "measured bursts (best-of) in the sharded scenario",
      section="bench")
_knob("COPYCAT_BENCH_SHARDED_KEYS", "int", 1024,
      "zipfian keyspace size in the sharded scenario", section="bench")
_knob("COPYCAT_BENCH_SHARDED_ZIPF", "float", 0.9,
      "zipf skew exponent for the sharded scenario's key draw",
      section="bench")
_knob("COPYCAT_BENCH_SHARDED_TRACE", "bool", False,
      "`1` drives one traced client wave after the timed bursts and "
      "embeds the assembled cross-member waterfall + `latency.*` phase "
      "histograms in the `--metrics-json` artifact", section="bench")
_knob("COPYCAT_BENCH_SHARDED_DELAY_MS", "float", 100.0,
      "nemesis wire latency per leg, ms (cross-region shape: the "
      "bounded replication window caps a single ordered log at "
      "max-inflight/RTT — the cap sharding multiplies)",
      section="bench")
_knob("COPYCAT_BENCH_RECOVERY_OPS", "int", 6000,
      "committed entries before the recovery scenario's catch-up",
      section="bench")
_knob("COPYCAT_BENCH_RECOVERY_STORAGE", "str", "disk",
      choices=("memory", "mapped", "disk"),
      doc="log storage level for the recovery scenario", section="bench")
_knob("COPYCAT_BENCH_RECOVERY_SNAP_ENTRIES", "int", 512,
      "snapshot cadence the recovery scenario pins", section="bench")
_knob("COPYCAT_BENCH_RECOVERY_CLIENTS", "int", 4,
      "concurrent clients in the recovery scenario", section="bench")
_knob("COPYCAT_BENCH_APPLY_GROUPS", "int", 4,
      "Raft groups in the apply scenario (`bench.py --groups` sets it; "
      "1 = the single-group shape)", section="bench")
_knob("COPYCAT_BENCH_APPLY_SESSIONS", "int", 24,
      "client sessions in the apply scenario", section="bench")
_knob("COPYCAT_BENCH_APPLY_OPS", "int", 48,
      "commands per session per burst in the apply scenario",
      section="bench")
_knob("COPYCAT_BENCH_APPLY_BURSTS", "int", 5,
      "measured bursts (best-of) in the apply scenario", section="bench")
_knob("COPYCAT_BENCH_APPLY_KEYS", "int", 256,
      "device counters in the apply scenario's hot/cold zipfian keyspace "
      "(sized so the engine round dominates the apply path — the "
      "apply-limited regime)", section="bench")
_knob("COPYCAT_BENCH_APPLY_ZIPF", "float", 0.9,
      "zipf skew exponent for the apply scenario's key draw",
      section="bench")
_knob("COPYCAT_BENCH_APPLY_INELIGIBLE", "float", 0.25,
      "fraction of sessions streaming ineligible (host-shadow string) "
      "ops — their log entries interleave with the device sessions' "
      "rows, the shape that collapses the contiguous classifier toward "
      "the per-entry path", section="bench")
_knob("COPYCAT_BENCH_COMPARTMENT_MEMBERS", "int", 3,
      "Raft member processes in the compartment scenario",
      section="bench")
_knob("COPYCAT_BENCH_COMPARTMENT_TIERS", "str", "1,2,4",
      "comma-separated ingress-tier widths the compartment scenario "
      "sweeps (processes per width)", section="bench")
_knob("COPYCAT_BENCH_COMPARTMENT_GROUPS", "int", 4,
      "Raft groups in the compartment scenario (`bench.py --groups` "
      "sets it)", section="bench")
_knob("COPYCAT_BENCH_COMPARTMENT_CLIENTS", "int", 8,
      "concurrent TCP clients in the compartment scenario",
      section="bench")
_knob("COPYCAT_BENCH_COMPARTMENT_OPS", "int", 600,
      "commands per client per burst in the compartment scenario",
      section="bench")
_knob("COPYCAT_BENCH_COMPARTMENT_BURSTS", "int", 3,
      "measured bursts (best-of) per tier width", section="bench")
_knob("COPYCAT_BENCH_COMPARTMENT_KEYS", "int", 1_000_000,
      "zipfian keyspace size in the compartment scenario (the "
      "million-key shape)", section="bench")
_knob("COPYCAT_BENCH_COMPARTMENT_ZIPF", "float", 0.9,
      "zipf skew exponent for the compartment scenario's key draw",
      section="bench")
_knob("COPYCAT_BENCH_COMPARTMENT_STORAGE", "str", "disk",
      choices=("memory", "mapped", "disk"),
      doc="member log storage level in the compartment scenario (real "
          "fsync by default)", section="bench")
_knob("COPYCAT_BENCH_COMPARTMENT_NEMESIS", "bool", True,
      "`0` skips the process-level nemesis phase (kill -9 a member + "
      "an ingress proxy mid-load, zero lost acknowledged writes)",
      section="bench")
_knob("COPYCAT_BENCH_FANOUT_READERS", "str", "8,32,128",
      "comma-separated reader-session counts the fanout scenario "
      "sweeps", section="bench")
_knob("COPYCAT_BENCH_FANOUT_WRITERS", "int", 2,
      "writer sessions in the fanout scenario", section="bench")
_knob("COPYCAT_BENCH_FANOUT_KEYS", "int", 16,
      "counter resources the fanout scenario reads/writes",
      section="bench")
_knob("COPYCAT_BENCH_FANOUT_READS", "int", 50,
      "reads per reader session per burst in the fanout scenario",
      section="bench")
_knob("COPYCAT_BENCH_FANOUT_BURSTS", "int", 3,
      "measured bursts (best-of) per reader count", section="bench")
_knob("COPYCAT_BENCH_FANOUT_ZIPF", "float", 0.9,
      "zipf skew exponent for the fanout scenario's key draw",
      section="bench")
_knob("COPYCAT_BENCH_NO_CPU_FALLBACK", "bool", False,
      "`1` makes an unreachable accelerator FATAL instead of a degraded "
      "CPU fallback", section="bench")

# --- scaling ---------------------------------------------------------------
_knob("COPYCAT_SCALING_GROUPS", "int", 4096,
      "groups per bulk row in the multichip scaling driver",
      section="scaling")
_knob("COPYCAT_SCALING_ROUNDS", "int", 30,
      "rounds per scaling measurement", section="scaling")

# --- verdict ---------------------------------------------------------------
_knob("COPYCAT_VERDICT_GROUPS", "int", 10000,
      "groups in the verdict engine", section="verdict")
_knob("COPYCAT_VERDICT_SAMPLE", "int", 99,
      "groups whose histories are recorded and checked", section="verdict")
_knob("COPYCAT_VERDICT_ROUNDS", "int", 1000,
      "engine rounds driven under nemesis", section="verdict")
_knob("COPYCAT_VERDICT_SEED", "int", 42, "workload/nemesis RNG seed",
      section="verdict")
_knob("COPYCAT_VERDICT_OP_EVERY", "int", 1,
      "rounds between recorded ops per sampled group", section="verdict")
_knob("COPYCAT_VERDICT_INFLIGHT", "int", 4,
      "bounded client concurrency per sampled group", section="verdict")
_knob("COPYCAT_VERDICT_CHURN", "bool", True,
      "`0` disables membership churn during recording", section="verdict")
_knob("COPYCAT_VERDICT_DEEP", "bool", True,
      "`0` skips the deep-plane (monotone-tag pipelined) block",
      section="verdict")
_knob("COPYCAT_VERDICT_DEEP_GROUPS", "int", 2000,
      "groups in the deep-plane block", section="verdict")
_knob("COPYCAT_VERDICT_DEEP_SAMPLE", "int", 48,
      "sampled groups in the deep-plane block", section="verdict")
_knob("COPYCAT_VERDICT_DEEP_EPOCHS", "int", 40,
      "fault epochs in the deep-plane block", section="verdict")
_knob("COPYCAT_VERDICT_ARTIFACT", "bool", True,
      "`0` skips rewriting LINEARIZABILITY.md (CI/smoke runs must not "
      "clobber the bench-scale artifact)", section="verdict")


# --- typed getters ---------------------------------------------------------


def _lookup(name: str) -> Knob:
    try:
        return REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"{name} is not a registered knob — declare it in "
            f"copycat_tpu/utils/knobs.py (the knob-registry lint rule "
            f"and the README table both feed off the registry)") from None


def get_raw(name: str) -> str | None:
    """The raw env value, or ``None`` when unset. For tri-state knobs
    where *set at all* is meaningful (``COPYCAT_INVARIANTS``,
    ``COPYCAT_BENCH_PALLAS``, ``COPYCAT_COMPILE_CACHE``)."""
    _lookup(name)
    return os.environ.get(name)


def get_str(name: str, default: str | None = None) -> str:
    knob = _lookup(name)
    value = os.environ.get(name)
    if value is None:
        value = default if default is not None else knob.default
    if value is None:
        raise ValueError(f"{name} has no registered default; pass default=")
    return value


def get_int(name: str, default: int | None = None) -> int:
    knob = _lookup(name)
    value = os.environ.get(name)
    if value is not None:
        return int(value)
    if default is not None:
        return default
    if knob.default is None:
        raise ValueError(f"{name} has no registered default; pass default=")
    return int(knob.default)


def get_float(name: str, default: float | None = None) -> float:
    knob = _lookup(name)
    value = os.environ.get(name)
    if value is not None:
        return float(value)
    if default is not None:
        return default
    if knob.default is None:
        raise ValueError(f"{name} has no registered default; pass default=")
    return float(knob.default)


def get_bool(name: str, default: bool | None = None) -> bool:
    knob = _lookup(name)
    value = os.environ.get(name)
    if value is None:
        if default is not None:
            return default
        if knob.default is None:
            raise ValueError(
                f"{name} has no registered default; pass default=")
        return bool(knob.default)
    return value.strip().lower() not in _FALSY


def overrides() -> dict[str, str]:
    """Every registered knob explicitly set in the environment, with its
    raw value — the scenario knob snapshot ``bench.py --metrics-json``
    embeds so artifacts from different runs are comparable (an artifact
    whose knobs differ is a different experiment, not a regression)."""
    return {name: os.environ[name] for name in sorted(REGISTRY)
            if name in os.environ}


# --- README generation -----------------------------------------------------

README_BEGIN = "<!-- knobs:begin (generated by python -m copycat_tpu.utils.knobs; do not edit by hand) -->"
README_END = "<!-- knobs:end -->"


def render_markdown() -> str:
    """The full *Knob reference* body between the README markers —
    one table per section, straight from the registry."""
    lines: list[str] = []
    for key, title in SECTIONS:
        knobs = [k for k in REGISTRY.values() if k.section == key]
        if not knobs:
            continue
        lines.append(f"### {title}")
        lines.append("")
        lines.append("| knob | default | effect |")
        lines.append("|---|---|---|")
        for k in knobs:  # registration order == doc order
            doc = k.doc
            if k.choices:
                doc += " (" + "/".join(f"`{c}`" for c in k.choices) + ")"
            lines.append(f"| `{k.name}` | `{k.default_text()}` | {doc} |")
        lines.append("")
    return "\n".join(lines).rstrip() + "\n"


def readme_section(readme_text: str) -> str | None:
    """Extract the generated section from README text, or ``None`` when
    the markers are missing."""
    try:
        start = readme_text.index(README_BEGIN) + len(README_BEGIN)
        end = readme_text.index(README_END)
    except ValueError:
        return None
    return readme_text[start:end].strip("\n") + "\n"


def main() -> None:
    print(render_markdown(), end="")


if __name__ == "__main__":
    main()
