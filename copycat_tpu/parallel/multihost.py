"""Multi-host (multi-process) execution of the batched consensus engine.

The reference scales by running one server process per machine over
Netty/TCP (SURVEY.md §5.8). The TPU-native equivalent: ONE SPMD program
over a global ``jax.sharding.Mesh`` spanning every process's devices —
`jax.distributed` wires the processes (gRPC coordination over DCN), XLA
inserts the cross-process collectives for the peer-axis tallies, and
each process keeps the CLIENT side (queues, harvest, sessions, retry
protocol) for the groups whose shards it hosts. Client traffic is
host-local; replica traffic is ICI/DCN inside the compiled step —
exactly the split SURVEY.md §5.8 prescribes.

Usage (same program on every process — SPMD):

    from copycat_tpu.parallel import multihost
    multihost.initialize("host0:9100", num_processes=4, process_id=i)
    rg = multihost.MultiHostRaftGroups(groups_per_process=2500)
    rg.wait_for_leaders()            # lockstep-coordinated
    tag = rg.submit(local_group, OP_LONG_ADD, 1)   # local group index
    rg.run_until([tag])              # lockstep-coordinated

LOCKSTEP CONTRACT: ``step_round`` launches a collective program, so all
processes must call it the same number of times. Every stop/branch
decision in the driver loops (`run_until`, `wait_for_leaders`,
`serve_query`, the serve-queries gate inside `step_round`) flows through
the `_agree`/`_any_across` hooks, which allgather here — so the standard
`RaftGroups` API is lockstep-safe as long as each process calls the same
methods (with its own local arguments; `run_until([])` when idle).
Verified end-to-end by ``tests/test_multihost.py`` (two real processes
over a loopback coordinator on the CPU backend).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from functools import partial

from ..models.raft_groups import RaftGroups
from ..ops.consensus import (
    Config,
    Submits,
    init_state,
    install_snapshots,
    query_step,
    step,
)
from .mesh import raft_specs


def initialize(coordinator_address: str, num_processes: int,
               process_id: int, platform: str | None = None) -> None:
    """Wire this process into the cluster (``jax.distributed``). Call
    before any other JAX use; every process must call it with the same
    coordinator (process 0's address)."""
    if platform:
        jax.config.update("jax_platforms", platform)
    # CPU multiprocess needs an explicit collectives backend: jaxlib
    # builds that default jax_cpu_collectives_implementation to "none"
    # refuse every cross-process program outright ("Multiprocess
    # computations aren't implemented on the CPU backend" — the round-9
    # tier-1 drift). Gloo ships in jaxlib; selecting it restores the
    # CPU-mesh lockstep tests and is inert for TPU meshes (the knob only
    # picks the CPU backend's collectives transport). Older jax without
    # the knob already wires CPU collectives — skip quietly there.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 — knob absent: nothing to select
        pass
    jax.distributed.initialize(coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def global_mesh() -> Mesh:
    """1D ``('groups',)`` mesh over ALL processes' devices, ordered so
    each process's devices form one contiguous block of the group axis
    (jax.devices() orders by process index)."""
    return Mesh(np.asarray(jax.devices()), ("groups",))


class MultiHostRaftGroups(RaftGroups):
    """``RaftGroups`` over a process-spanning mesh: the consensus state
    is ONE global sharded pytree, the step is one collective XLA
    program, and THIS process's host runtime (submit queues, harvest,
    results, events, sessions, exactly-once retry) covers the
    ``groups_per_process`` groups whose shards live on its devices.
    Group indices in the public API are process-LOCAL (0..Gp-1); the
    global group id is ``group + group_offset``."""

    def __init__(self, groups_per_process: int, num_peers: int = 3,
                 log_slots: int = 64, submit_slots: int = 4,
                 config: Config | None = None, seed: int = 0,
                 voters: int | None = None) -> None:
        if jax.process_count() < 2:
            raise RuntimeError(
                "MultiHostRaftGroups needs jax.distributed to be "
                "initialized across >=2 processes (multihost.initialize)")
        self.process_index = jax.process_index()
        self.process_count = jax.process_count()
        self.global_groups = groups_per_process * self.process_count
        self.group_offset = groups_per_process * self.process_index
        # Base init sizes ALL host bookkeeping to the local block (its
        # num_groups); _build_state=False (subclass protocol, not public
        # API) skips the locally shaped state/deliver/jit wrappers that
        # this __init__ replaces with global sharded versions below.
        super().__init__(groups_per_process, num_peers, log_slots,
                         submit_slots, config, seed, voters=voters,
                         _build_state=False)
        self.mesh = global_mesh()
        self._sub_sharding = NamedSharding(self.mesh, P("groups", None))
        self._dl_sharding = NamedSharding(self.mesh, P("groups", None, None))

        # Global replicated-construction state: every process builds the
        # SAME full-size host arrays (same seed -> identical), then each
        # contributes only the shards its devices own.
        key = jax.random.PRNGKey(seed)
        _, init_key = jax.random.split(key)
        members = None
        if voters is not None and voters < num_peers:
            members = np.arange(num_peers) < voters
        full = init_state(self.global_groups, num_peers, log_slots,
                          init_key, self.config, members=members)
        specs = raft_specs(self.mesh, full)
        is_spec = lambda x: isinstance(x, P)
        state_sh = jax.tree.map(lambda s: NamedSharding(self.mesh, s),
                                specs, is_leaf=is_spec)
        self.state = jax.tree.map(
            lambda x, s: jax.make_array_from_callback(
                x.shape, s, lambda idx, x=x: np.asarray(x)[idx]),
            full, state_sh)
        self.deliver = self._stage_deliver(
            np.ones((groups_per_process, num_peers, num_peers), bool))
        # Output shardings are PINNED to group-sharded (leading dim on
        # the mesh, rest replicated): the shard-concat fetch below relies
        # on every output leaf being split by groups, and without the pin
        # the compiler is free to replicate an output.
        out_sh = NamedSharding(self.mesh, P("groups"))
        self._step = jax.jit(partial(step, config=self.config),
                             out_shardings=(state_sh, out_sh))
        self._query = jax.jit(partial(query_step, config=self.config),
                              out_shardings=out_sh)
        self._install = jax.jit(partial(install_snapshots,
                                        config=self.config),
                                out_shardings=state_sh)
        self._global_any = jax.jit(jnp.any)
        self._state_sh = state_sh
        self._out_sh = out_sh
        self._deep_jit = None   # built on first deep drive (_deep_fn)

    # -- staging/fetch hooks: local block <-> global sharded arrays ------

    def _stage_submits(self, submits: Submits) -> Submits:
        return Submits(*[
            jax.make_array_from_process_local_data(
                self._sub_sharding, np.ascontiguousarray(x))
            for x in submits])

    def _stage_deliver(self, deliver: Any) -> Any:
        return jax.make_array_from_process_local_data(
            self._dl_sharding, np.ascontiguousarray(np.asarray(deliver)))

    @staticmethod
    def _local_block(x) -> np.ndarray:
        """This process's contiguous block of a group-sharded global
        array (shards ordered by their group-axis offset)."""
        shards = sorted(x.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        return np.concatenate([np.asarray(s.data) for s in shards], axis=0)

    def _fetch_outputs(self, raw):
        # overlap the D2H transfers (same rationale as the base hook:
        # lazy per-array fetches each pay a full round-trip), then
        # assemble each leaf's local block
        for leaf in jax.tree.leaves(raw):
            for s in leaf.addressable_shards:
                s.data.copy_to_host_async()
        return jax.tree.map(self._local_block, raw)

    def _stale_any(self, raw, out) -> bool:
        # the install decision must be GLOBALLY consistent (install runs
        # a collective program): reduce over the global array — the
        # replicated scalar is addressable on every process
        return bool(np.asarray(self._global_any(raw.stale)))

    def _run_query(self, sub: Submits, atomic):
        g_atomic = jax.make_array_from_process_local_data(
            self._sub_sharding, np.ascontiguousarray(atomic))
        results, served = self._query(self.state, self._stage_submits(sub),
                                      g_atomic)
        return self._local_block(results), self._local_block(served)

    # -- deep-plane hooks (models/bulk.py _drive_deep) --------------------
    # The deep drive stages submits through _stage_submits (above) and
    # everything else through these: accumulators become GLOBAL
    # group-sharded arrays assembled from each process's local block,
    # fetches return only addressable shards, and the deep program pins
    # its output shardings (an unpinned output is free to replicate,
    # which would break the shard-concat fetch).

    def _global_max_int(self, v: int) -> int:
        from jax.experimental import multihost_utils
        return int(np.asarray(multihost_utils.process_allgather(
            np.asarray(v, np.int64))).max())

    def _stage_acc(self, arr: np.ndarray):
        spec = P("groups", *([None] * (arr.ndim - 1)))
        return jax.make_array_from_process_local_data(
            NamedSharding(self.mesh, spec), np.ascontiguousarray(arr))

    def _fetch_acc(self, arrays):
        for leaf in jax.tree.leaves(arrays):
            for s in leaf.addressable_shards:
                s.data.copy_to_host_async()
        return jax.tree.map(self._local_block, arrays)

    def _deep_fn(self):
        if self._deep_jit is None:
            from ..ops.consensus import deep_step
            acc2 = NamedSharding(self.mesh, P("groups", None))
            acc1 = NamedSharding(self.mesh, P("groups"))
            # donation mirrors the single-host deep program: state +
            # accumulators are handed back to XLA for in-place reuse on
            # accelerators (saves a full sharded-state copy per round);
            # unimplemented on CPU, where it would only warn
            donate = ((0, 1, 2, 3, 4)
                      if jax.default_backend() != "cpu" else ())
            self._deep_jit = jax.jit(
                partial(deep_step, config=self.config, onehot=True),
                donate_argnums=donate,
                out_shardings=(self._state_sh, acc2, acc2, acc2, acc1,
                               self._out_sh))
        return self._deep_jit

    # -- lockstep agreement primitives -------------------------------------
    # The base driver loops (run_until, wait_for_leaders, serve_query,
    # the serve-queries gate in step_round) decide through these, so the
    # control flow lives in ONE place; here they allgather so every
    # process takes the same branch around every collective program.

    @staticmethod
    def _gather_flags(mine: bool) -> np.ndarray:
        from jax.experimental import multihost_utils
        return np.asarray(
            multihost_utils.process_allgather(np.asarray(mine, bool)))

    def _agree(self, mine: bool) -> bool:
        return bool(self._gather_flags(mine).all())

    def _any_across(self, mine: bool) -> bool:
        return bool(self._gather_flags(mine).any())

    # -- device-plane telemetry (models/telemetry.py) ---------------------

    def merged_device_snapshot(self) -> dict:
        """Cluster-wide ``device.*`` family: allgather each process's
        local snapshot (the hub eagerly registers every key, so the key
        sets agree) and fold with ``merge_snapshots`` — counters sum
        across shards, gauges take the max except the per-shard-additive
        ones (``ADDITIVE_GAUGES``: commit total, leaderless count),
        which sum. COLLECTIVE: every process must call it together
        (same lockstep contract as step_round)."""
        from jax.experimental import multihost_utils

        from ..utils.metrics import merge_snapshots

        local = self.device_snapshot()
        # The enablement decision must itself be COLLECTIVE: telemetry
        # is a per-process choice (env opt-in), and a telemetry-off
        # process returning early while its peers enter the value
        # allgather would hang the cluster. Every process first agrees
        # whether ALL of them have the family; if any lacks it, all
        # return {} together.
        have = np.asarray(
            multihost_utils.process_allgather(np.asarray(bool(local))))
        if not have.all():
            return {}
        gauge_keys = local.get("_gauge_keys", [])
        keys = sorted(k for k, v in local.items()
                      if k != "_gauge_keys" and not isinstance(v, dict))
        vals = np.asarray([float(local[k]) for k in keys], np.float64)
        gathered = np.asarray(multihost_utils.process_allgather(vals))
        snaps = []
        for p in range(gathered.shape[0]):
            snap: dict = {k: gathered[p, i] for i, k in enumerate(keys)}
            snap["_gauge_keys"] = list(gauge_keys)
            snaps.append(snap)
        out = merge_snapshots(snaps)
        # gauges that are sums over each process's DISJOINT group block
        # (commit total, leaderless count) add across shards; the
        # merge_snapshots gauge default (max) would report only the
        # worst shard
        from ..models.telemetry import ADDITIVE_GAUGES
        for k in ADDITIVE_GAUGES:
            if k in keys:
                out[k] = float(gathered[:, keys.index(k)].sum())
        return out

    # -- local views -------------------------------------------------------

    def leader(self, group: int) -> int:
        """Leader lane of LOCAL ``group`` (reads this process's shard)."""
        role = self._local_block(self.state.role)[group]
        term = self._local_block(self.state.term)[group]
        leaders = np.nonzero(role == 2)[0]
        if len(leaders) == 0:
            return -1
        return int(leaders[np.argmax(term[leaders])])

    def value(self, group: int, peer: int = 0) -> int:
        return int(self._local_block(self.state.resources.value)
                   [group, peer])

    def voting_members(self, group: int) -> list[int]:
        # same lane-selection rule as the base class (_config_mask), over
        # this process's local block of the sharded state
        mask = self._config_mask(
            self._local_block(self.state.member)[group],
            self._local_block(self.state.applied_index)[group],
            self._local_block(self.state.term)[group],
            self._local_block(self.state.role)[group])
        return [p for p in range(self.num_peers) if (mask >> p) & 1]
