"""Sharded-step scaling measurement over a virtual CPU device mesh.

Round-2 review: "no artifact shows the step's scaling behavior across the
virtual mesh — even a CPU-mesh walltime table would expose a
collective-placement pathology before real multi-chip hardware arrives."
This runner produces that artifact: the SAME consensus step (fixed total
work) jitted over 1/2/4/8-device meshes, group axis sharded, walltime per
round measured after warm-up. CPU devices share host cores, so the point
is not speedup — it is that walltime stays ~flat (no superlinear blow-up
from XLA inserting pathological collectives or resharding on the step's
dataflow) and that the compiled program report shows the expected
communication pattern.

Run: ``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
python -m copycat_tpu.parallel.scaling`` → one JSON line + MULTICHIP_SCALING.md.
"""

from __future__ import annotations

import json
import os
import time

# must land before the first backend init
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax
import numpy as np

from ..utils import knobs
from ..utils.platform import honor_jax_platforms_env

# JAX_PLATFORMS=cpu must WIN over plugin site config, or backend
# discovery dials the (possibly dead) accelerator tunnel and hangs —
# the same hazard the driver-graded entry points guard against.
honor_jax_platforms_env()

GROUPS = knobs.get_int("COPYCAT_SCALING_GROUPS")
PEERS = 3
ROUNDS = knobs.get_int("COPYCAT_SCALING_ROUNDS")


COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "collective-permute", "all-to-all")
# The census compiles its own (small) module: AOT lower().compile() and
# the jit call cache do not share executables, so running the census at
# measurement size would pay a redundant full compile per device count.
# Collective structure depends only on the sharding pattern, not G.
CENSUS_GROUPS = 256


def _census_text(txt: str) -> dict:
    """Tally cross-device collective ops in compiled-module text."""
    import re

    return {op: n for op in COLLECTIVE_OPS
            if (n := len(re.findall(rf"\b{op}\b", txt)))}


def _collective_census(n_devices: int, devices) -> dict:
    """Count cross-device collective ops in the compiled module — the
    direct witness for (non-)resharding: a purely group-sharded step is
    embarrassingly parallel and must compile to ZERO collectives."""
    import re
    from functools import partial

    from jax.sharding import Mesh

    from ..ops.consensus import (
        Config, full_delivery, init_state, make_submits, step)
    from ..parallel.mesh import shard_state, shard_step_inputs

    mesh = Mesh(np.asarray(devices[:n_devices]), ("groups",))
    config = Config()
    key = jax.random.PRNGKey(0)
    key, init_key = jax.random.split(key)
    state = init_state(CENSUS_GROUPS, PEERS, 32, init_key, config)
    submits = make_submits(CENSUS_GROUPS, 4)
    deliver = full_delivery(CENSUS_GROUPS, PEERS)
    state = shard_state(state, mesh)
    submits, deliver = shard_step_inputs(submits, deliver, mesh)
    fn = jax.jit(partial(step, config=config))
    return _census_text(
        fn.lower(state, submits, deliver, key).compile().as_text())


def _query_census(n_devices: int, devices) -> dict:
    """Census the READ plane: the ``query_step`` program (round-9 batched
    read pump's device leg) compiled over the sharded mesh. Reads are
    leader-lane selects + one fused apply pass per group — group-local by
    construction — so the correct compilation target is the same ZERO
    cross-device collectives the step holds."""
    from functools import partial

    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ..ops.consensus import (
        Config, init_state, make_submits, query_step)
    from ..parallel.mesh import shard_state, shard_step_inputs

    mesh = Mesh(np.asarray(devices[:n_devices]), ("groups",))
    config = Config()
    key = jax.random.PRNGKey(0)
    key, init_key = jax.random.split(key)
    state = shard_state(
        init_state(CENSUS_GROUPS, PEERS, 32, init_key, config), mesh)
    queries = make_submits(CENSUS_GROUPS, 4)
    queries, _ = shard_step_inputs(
        queries, jnp.ones((CENSUS_GROUPS, PEERS, PEERS), bool), mesh)
    atomic = jax.device_put(jnp.zeros((CENSUS_GROUPS, 4), bool),
                            NamedSharding(mesh, P("groups", None)))
    fn = jax.jit(partial(query_step, config=config))
    return _census_text(
        fn.lower(state, queries, atomic).compile().as_text())


def _measure_bulk(n_devices: int, devices) -> dict:
    """Client-visible deep-drive throughput on the sharded mesh (round-4
    addition): the FULL bulk plane — blind pipelined dispatch, on-device
    [G,B] accumulators, one harvest — runs over group-sharded engines,
    so the client data path scales with devices, not just the raw step.
    Also censuses the deep_step module for cross-device collectives."""
    from jax.sharding import Mesh

    from ..models.bulk import BulkDriver
    from ..models.raft_groups import RaftGroups
    from ..ops import apply as ap
    from ..ops.consensus import Config
    from ..utils.metrics import merge_snapshots

    mesh = Mesh(np.asarray(devices[:n_devices]), ("groups",))
    # telemetry ON here on purpose: the deep_step/deep_scan censuses
    # below then also verify the round-8 telemetry block compiles
    # without cross-device collectives (its reductions are per-group)
    config = Config(append_window=8, applies_per_round=8,
                    monotone_tag_accept=True, telemetry=True)
    rg = RaftGroups(GROUPS, PEERS, log_slots=32, submit_slots=8,
                    mesh=mesh, config=config)
    rg.wait_for_leaders()
    drv = BulkDriver(rg)
    g = np.repeat(np.arange(GROUPS), 32)
    t0 = time.perf_counter()
    drv.drive(g, ap.OP_LONG_ADD, 1)  # warm (compile + first transfers)
    warm_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    res = drv.drive(g, ap.OP_LONG_ADD, 1)
    dt = time.perf_counter() - t0

    collectives = _deep_census(n_devices, devices, config)
    # round 5: the fused scan program is a distinct compiled module —
    # its zero-collective property is verified separately, not inherited
    scan_collectives = _deep_scan_census(n_devices, devices, config)
    # Per-DEVICE telemetry attribution (round 8): the hub's per-group
    # cumulative arrays split into each device's contiguous group block
    # — elections / leader changes / commit advance per shard — and the
    # shard snapshots fold back into one cluster view with the same
    # merge_snapshots the multihost roll-up uses.
    shard_snaps = rg.telemetry.shard_snapshots(n_devices)
    merged = merge_snapshots(
        [{k: v for k, v in s.items() if k.startswith("device.")}
         for s in shard_snaps])
    return {"devices": n_devices,
            "client_visible_ops_per_sec": round(g.size / dt),
            "drive_rounds": res.rounds,
            "warmup_s": round(warm_s, 1),
            "collectives": collectives,
            "scan_collectives": scan_collectives,
            "telemetry_per_shard": shard_snaps,
            "telemetry_merged": merged}


def _deep_census(n_devices: int, devices, config) -> dict:
    from functools import partial

    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ..ops.consensus import (
        Submits, deep_step, full_delivery, init_state)
    from ..parallel.mesh import shard_state

    mesh = Mesh(np.asarray(devices[:n_devices]), ("groups",))
    key = jax.random.PRNGKey(0)
    key, init_key = jax.random.split(key)
    state = shard_state(
        init_state(CENSUS_GROUPS, PEERS, 32, init_key, config), mesh)
    sh2 = NamedSharding(mesh, P("groups", None))
    sh1 = NamedSharding(mesh, P("groups"))
    resbuf = jax.device_put(jnp.zeros((CENSUS_GROUPS, 32), jnp.int32), sh2)
    valbuf = jax.device_put(jnp.zeros((CENSUS_GROUPS, 32), bool), sh2)
    rndbuf = jax.device_put(
        jnp.full((CENSUS_GROUPS, 32), np.int32(2**30), jnp.int32), sh2)
    # evflag matches production exactly: a [G] group-sharded vector
    # (a replicated scalar here would census a DIFFERENT program)
    evflag = jax.device_put(jnp.zeros(CENSUS_GROUPS, bool), sh1)
    base = jax.device_put(jnp.zeros(CENSUS_GROUPS, jnp.int32), sh1)
    sub = Submits(opcode=np.int32(5), a=np.int32(1), b=np.int32(0),
                  c=np.int32(0),
                  tag=np.zeros((CENSUS_GROUPS, 1), np.int32),
                  valid=np.zeros((CENSUS_GROUPS, 8), bool))
    deliver = jax.device_put(
        full_delivery(CENSUS_GROUPS, PEERS),
        NamedSharding(mesh, P("groups", None, None)))
    fn = jax.jit(partial(deep_step, config=config, onehot=True))
    return _census_text(
        fn.lower(state, resbuf, valbuf, rndbuf, evflag, base,
                 np.int32(0), sub, deliver, key).compile().as_text())


def _deep_scan_census(n_devices: int, devices, config,
                      W: int = 4) -> dict:
    """Census the round-5 ``deep_scan`` program (the whole blind phase
    as one lax.scan) — a new compiled module, so the zero-collective
    property must be re-verified, not inherited from deep_step."""
    from functools import partial

    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ..ops.consensus import (
        Submits, deep_scan, full_delivery, init_state)
    from ..parallel.mesh import shard_state

    mesh = Mesh(np.asarray(devices[:n_devices]), ("groups",))
    key = jax.random.PRNGKey(0)
    key, init_key = jax.random.split(key)
    state = shard_state(
        init_state(CENSUS_GROUPS, PEERS, 32, init_key, config), mesh)
    sh2 = NamedSharding(mesh, P("groups", None))
    sh1 = NamedSharding(mesh, P("groups"))
    resbuf = jax.device_put(jnp.zeros((CENSUS_GROUPS, 32), jnp.int32), sh2)
    valbuf = jax.device_put(jnp.zeros((CENSUS_GROUPS, 32), bool), sh2)
    rndbuf = jax.device_put(
        jnp.full((CENSUS_GROUPS, 32), np.int32(2**30), jnp.int32), sh2)
    evflag = jax.device_put(jnp.zeros(CENSUS_GROUPS, bool), sh1)
    base = jax.device_put(jnp.zeros(CENSUS_GROUPS, jnp.int32), sh1)
    sub_w = Submits(
        opcode=np.zeros((W, CENSUS_GROUPS, 8), np.int32),
        a=np.zeros((W, CENSUS_GROUPS, 8), np.int32),
        b=np.zeros((W, CENSUS_GROUPS, 8), np.int32),
        c=np.zeros((W, CENSUS_GROUPS, 8), np.int32),
        tag=np.zeros((W, CENSUS_GROUPS, 1), np.int32),
        valid=np.zeros((W, CENSUS_GROUPS, 8), bool))
    deliver = jax.device_put(
        full_delivery(CENSUS_GROUPS, PEERS),
        NamedSharding(mesh, P("groups", None, None)))
    fn = jax.jit(partial(deep_scan, config=config, onehot=True))
    return _census_text(
        fn.lower(state, resbuf, valbuf, rndbuf, evflag, base, sub_w,
                 deliver, key).compile().as_text())


def _measure(n_devices: int, devices) -> dict:
    from functools import partial

    from jax.sharding import Mesh

    from ..ops.consensus import (
        Config, full_delivery, init_state, make_submits, step)
    from ..parallel.mesh import shard_state, shard_step_inputs

    mesh = Mesh(np.asarray(devices[:n_devices]), ("groups",))
    config = Config()
    key = jax.random.PRNGKey(0)
    key, init_key = jax.random.split(key)
    state = init_state(GROUPS, PEERS, 32, init_key, config)
    submits = make_submits(GROUPS, 4)
    deliver = full_delivery(GROUPS, PEERS)
    state = shard_state(state, mesh)
    submits, deliver = shard_step_inputs(submits, deliver, mesh)
    fn = jax.jit(partial(step, config=config))
    collectives = _collective_census(n_devices, devices)
    query_collectives = _query_census(n_devices, devices)

    t0 = time.perf_counter()
    for _ in range(3):  # warm-up (includes compile)
        key, k = jax.random.split(key)
        state, out = fn(state, submits, deliver, k)
    jax.block_until_ready(state)
    compile_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    for _ in range(ROUNDS):
        key, k = jax.random.split(key)
        state, out = fn(state, submits, deliver, k)
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0
    return {"devices": n_devices,
            "ms_per_round": round(dt / ROUNDS * 1e3, 2),
            "warmup_s": round(compile_s, 1),
            "collectives": collectives,
            "query_collectives": query_collectives}


def main() -> None:
    devices = jax.devices("cpu")
    if len(devices) < 8:
        raise SystemExit("need 8 virtual CPU devices (set XLA_FLAGS before "
                         "any jax import)")
    host_cores = (len(os.sched_getaffinity(0))
                  if hasattr(os, "sched_getaffinity") else os.cpu_count())
    rows = [_measure(n, devices) for n in (1, 2, 4, 8)]
    base = rows[0]["ms_per_round"]
    for row in rows:
        row["vs_1dev"] = round(row["ms_per_round"] / base, 2)
    no_collectives = all(not row["collectives"] for row in rows)
    query_no_coll = all(not row["query_collectives"] for row in rows)
    bulk_rows = [_measure_bulk(n, devices) for n in (1, 2, 4, 8)]
    bulk_no_coll = all(not row["collectives"] for row in bulk_rows)
    scan_no_coll = all(not row["scan_collectives"] for row in bulk_rows)
    result = {"groups": GROUPS, "peers": PEERS, "rounds": ROUNDS,
              "mesh_axis": "groups", "host_cores": host_cores,
              "no_cross_device_collectives": no_collectives,
              "query_no_cross_device_collectives": query_no_coll,
              "bulk_no_cross_device_collectives": bulk_no_coll,
              "deep_scan_no_cross_device_collectives": scan_no_coll,
              "table": rows, "bulk_table": bulk_rows}

    lines = [
        "# MULTICHIP_SCALING — sharded step over the virtual mesh",
        "",
        f"Fixed total work ({GROUPS} groups × {PEERS} peers, full default",
        "pools) jitted over 1/2/4/8 virtual CPU devices, group axis",
        "sharded (`copycat_tpu/parallel/mesh.py`), measured with",
        "`python -m copycat_tpu.parallel.scaling`.",
        "",
        "## Pass criterion (round 4): no cross-device collectives",
        "",
        "The compiled module of the sharded step is inspected per device",
        "count. A purely group-sharded step is embarrassingly parallel —",
        "groups are independent Raft worlds — so the correct compilation",
        "target is ZERO cross-device collectives (no all-reduce /",
        "all-gather / reduce-scatter / collective-permute / all-to-all),",
        "which is the direct witness that XLA inserts no resharding on",
        "the step's dataflow. Measured:",
        "",
        f"- cross-device collectives at 1/2/4/8 devices: "
        + ("**none** ✓" if no_collectives else "**FOUND** ✗ (see JSON)"),
        f"- query_step (round-9 read plane) cross-device collectives at "
        f"1/2/4/8 devices: "
        + ("**none** ✓" if query_no_coll else "**FOUND** ✗ (see JSON)"),
        f"- host cores available to this process: **{host_cores}**",
        "",
        "Walltime on the virtual mesh is diagnostic only: virtual CPU",
        "devices share host cores, so with fewer cores than devices the",
        "per-round time grows with device count from pure host",
        "oversubscription (program launch + inter-device rendezvous on a",
        "shared core), not from communication — the round-3 8-device",
        "\"regression\" reproduced exactly this on a 1-core host while",
        "the compiled modules contain no collectives at all. On real",
        "multi-chip hardware each shard owns a chip and the same program",
        "runs with no cross-chip traffic in the step.",
        "",
        "| devices | ms/round | vs 1 device | collectives |",
        "|---|---|---|---|",
    ]
    for row in rows:
        cl = row["collectives"] or "none"
        lines.append(f"| {row['devices']} | {row['ms_per_round']} "
                     f"| {row['vs_1dev']}× | {cl} |")
    lines += [
        "",
        "The peer axis stays replicated here (P=3 quorum tallies are",
        "cheap reductions); `__graft_entry__.dryrun_multichip` separately",
        "proves the 2D ('groups','peers') sharding compiles and elects",
        "across the mesh every round.",
        "",
        "## The CLIENT data path over the sharded mesh (round 4)",
        "",
        "The deep bulk pipeline (`models/bulk.py` — device-enforced FIFO,",
        "on-device [G,B] result accumulators, one harvest per drive) runs",
        "unchanged over group-sharded engines: the accumulators shard with",
        "the state, the scatter stays shard-local, and the `deep_step`",
        "compiled module is censused for collectives the same way:",
        "",
        f"- deep_step cross-device collectives at 1/2/4/8 devices: "
        + ("**none** ✓" if bulk_no_coll else "**FOUND** ✗ (see JSON)"),
        f"- deep_scan (round 5 — the whole blind phase as one lax.scan"
        f" program) cross-device collectives at 1/2/4/8 devices: "
        + ("**none** ✓" if scan_no_coll else "**FOUND** ✗ (see JSON)"),
        "",
        "| devices | client-visible ops/sec | drive rounds | collectives |",
        "|---|---|---|---|",
    ] + [
        f"| {row['devices']} | {row['client_visible_ops_per_sec']:,} "
        f"| {row['drive_rounds']} | {row['collectives'] or 'none'} |"
        for row in bulk_rows
    ] + [
        "",
        "(Same oversubscription caveat: virtual devices share this host's",
        "core, so ops/sec across device counts measures scheduler overhead",
        "only; zero collectives is the portable witness.)",
        "",
        "The bulk rows run with the round-8 device telemetry block ON",
        "(`Config(telemetry=True)`), so the deep_step/deep_scan censuses",
        "above also witness that the telemetry reductions stay per-group",
        "(zero collectives), and each row's JSON carries",
        "`telemetry_per_shard` — elections / leader changes / commit",
        "advance attributed to every device's group block — plus",
        "`telemetry_merged`, the same shards folded back through",
        "`merge_snapshots` (the multihost roll-up idiom).",
        "",
    ]
    with open("MULTICHIP_SCALING.md", "w") as f:
        f.write("\n".join(lines))
    print(json.dumps(result))


if __name__ == "__main__":
    main()
