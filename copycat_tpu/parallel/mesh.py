"""Mesh construction and sharding specs for ``RaftState``.

The step function in ``ops.consensus`` is written as pure array ops over
``[G, P, ...]`` tensors; sharding is applied by *placement only* —
``jax.device_put`` with ``NamedSharding`` on the inputs — and XLA inserts
the ICI collectives (all-gathers for the ``[G,P,P]`` vote/ack contractions,
reductions for quorum tallies) from the annotations. No hand-written
collectives: the compiler owns the schedule.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.consensus import RaftState, Submits


def make_mesh(groups: int | None = None, peers: int | None = None,
              devices: list | None = None) -> Mesh:
    """Build a 1D ``('groups',)`` or 2D ``('groups','peers')`` mesh."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if peers is None:
        groups = groups or n
        return Mesh(np.asarray(devices[:groups]), ("groups",))
    groups = groups or n // peers
    if groups * peers > n:
        raise ValueError(f"mesh {groups}x{peers} needs {groups * peers} devices, have {n}")
    dev = np.asarray(devices[: groups * peers]).reshape(groups, peers)
    return Mesh(dev, ("groups", "peers"))


def raft_specs(mesh: Mesh, state: RaftState) -> RaftState:
    """Per-leaf PartitionSpecs: group axis sharded, peer axis sharded when
    the mesh has a ``peers`` axis, log/ring/pool axes replicated.

    Every ``RaftState`` leaf (including all resource pools and the event
    ring) is laid out ``[G, P, ...]``, so one rule covers the whole tree."""
    g = "groups" if "groups" in mesh.axis_names else None
    p = "peers" if "peers" in mesh.axis_names else None
    return jax.tree.map(
        lambda x: P(g, p, *([None] * (x.ndim - 2))), state)


def shard_state(state: RaftState, mesh: Mesh) -> RaftState:
    specs = raft_specs(mesh, state)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), state, specs)


def shard_step_inputs(submits: Submits, deliver: Any, mesh: Mesh
                      ) -> tuple[Submits, Any]:
    g = "groups" if "groups" in mesh.axis_names else None
    p = "peers" if "peers" in mesh.axis_names else None
    sub = jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P(g, None))), submits)
    dl = jax.device_put(deliver, NamedSharding(mesh, P(g, p, None)))
    return sub, dl
