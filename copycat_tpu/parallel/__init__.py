"""Device-mesh sharding for the batched consensus engine.

Two scaling axes (SURVEY.md §2.2):

- ``groups``: shard the group batch across chips when G exceeds one chip
  (the reference's "many resources over one log" multiplexing axis,
  ``ResourceManager.java:56``, turned into a data-parallel dimension);
- ``peers``: place each Raft replica on its own chip — real distributed
  consensus where quorum tallies (sums over the peer axis) become XLA
  collectives over ICI, replacing the reference's Netty server↔server
  traffic (``AtomixReplica.java:358-363``).
"""

from .mesh import make_mesh, raft_specs, shard_state, shard_step_inputs  # noqa: F401
from . import multihost  # noqa: F401  (multi-process: one SPMD step over DCN)
