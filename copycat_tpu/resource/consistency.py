"""User-facing consistency levels (reference ``Consistency.java:45-176``).

Each level maps to a (command consistency, query consistency) pair exactly as
the reference documents:

- NONE       -> (NONE, CAUSAL): fastest; events async, reads may be stale
- PROCESS    -> (SEQUENTIAL, CAUSAL): per-process sequential events
- SEQUENTIAL -> (SEQUENTIAL, SEQUENTIAL): global sequential order
- ATOMIC     -> (LINEARIZABLE, BOUNDED_LINEARIZABLE): linearizable writes with
  events delivered before the command response completes; leases bound reads
"""

from __future__ import annotations

import enum

from ..protocol.operations import CommandConsistency, QueryConsistency


class Consistency(enum.Enum):
    NONE = "none"
    PROCESS = "process"
    SEQUENTIAL = "sequential"
    ATOMIC = "atomic"

    def write_consistency(self) -> CommandConsistency:
        return _WRITE[self]

    def read_consistency(self) -> QueryConsistency:
        return _READ[self]


_WRITE = {
    Consistency.NONE: CommandConsistency.NONE,
    Consistency.PROCESS: CommandConsistency.SEQUENTIAL,
    Consistency.SEQUENTIAL: CommandConsistency.SEQUENTIAL,
    Consistency.ATOMIC: CommandConsistency.LINEARIZABLE,
}

_READ = {
    Consistency.NONE: QueryConsistency.CAUSAL,
    Consistency.PROCESS: QueryConsistency.CAUSAL,
    Consistency.SEQUENTIAL: QueryConsistency.SEQUENTIAL,
    Consistency.ATOMIC: QueryConsistency.BOUNDED_LINEARIZABLE,
}
