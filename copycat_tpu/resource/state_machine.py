"""Server-side resource state machine base (reference
``ResourceStateMachine.java:30``, ``ResourceStateMachineExecutor.java:41``,
``ResourceCommit.java:33``).

``ResourceStateMachine.init`` wraps the parent executor so envelope commits
(ResourceCommand/ResourceQuery) are unwrapped and dispatched to the handler
registered for the INNER operation type; subclass handlers are auto-registered
by their ``Commit[Op]`` annotations, exactly like the reference's reflection.
"""

from __future__ import annotations

from typing import Any, Callable

from ..server.state_machine import Commit, StateMachine, StateMachineExecutor
from .operations import DeleteCommand, ResourceOperation


class ResourceCommit(Commit):
    """A commit view exposing the INNER operation while delegating index/
    session/time/clean/close to the wrapping commit (``ResourceCommit.java``)."""

    __slots__ = ("_parent",)

    def __init__(self, parent: Commit, operation: Any):
        super().__init__(parent.index, parent.session, parent.time, operation, None)
        self._parent = parent

    def clean(self) -> None:
        self._parent.clean()

    def close(self) -> None:
        self._parent.close()


class ResourceStateMachineExecutor(StateMachineExecutor):
    """Unwraps envelope commits and dispatches by inner operation type."""

    def __init__(self, parent: StateMachineExecutor | None = None) -> None:
        super().__init__(context=parent.context if parent else None,
                         log=parent._log if parent else None)
        self._parent = parent

    def execute(self, commit: Commit) -> Any:
        operation = commit.operation
        if isinstance(operation, ResourceOperation):
            commit = ResourceCommit(commit, operation.operation)
        fn = self.callback_for(type(commit.operation))
        if fn is None:
            raise ValueError(
                f"no handler registered for {type(commit.operation).__name__}")
        return fn(commit)

    def schedule(self, delay: float, callback: Callable[[], None], interval=None):
        if self._parent is not None:
            return self._parent.schedule(delay, callback, interval)
        return super().schedule(delay, callback, interval)


class ResourceStateMachine(StateMachine):
    """Base server-side resource state machine.

    Subclasses define handlers annotated ``Commit[SomeOp]``; ``delete()`` is
    the cleanup hook (cancel timers, clean retained commits) invoked by the
    replicated DeleteCommand (reference ``ResourceStateMachine.init:33-42``).
    """

    def init(self, executor: StateMachineExecutor) -> None:
        if not isinstance(executor, ResourceStateMachineExecutor):
            executor = ResourceStateMachineExecutor(executor)
        self.executor = executor
        executor.register(DeleteCommand, self._on_delete)
        self.configure(executor)
        self._auto_register(executor)

    def _on_delete(self, commit: Commit) -> None:
        try:
            self.delete()
        finally:
            commit.clean()

    def edge_state(self) -> Any:
        """Full replicated state for the edge read tier
        (docs/EDGE_READS.md), as a ``(tag, payload)`` pair the client's
        type-agnostic evaluators understand (``"val"``/``"map"``/
        ``"set"``). Tagged states versioned by the applied log index
        form a join-semilattice (merge = max version), which is what
        makes the client replica safe under duplicated/reordered/
        re-delivered delta delivery. ``NotImplemented`` (the default)
        means this machine's reads are never edge-servable."""
        return NotImplemented

    def delete(self) -> None:
        """Release all replicated state (subclass hook)."""
