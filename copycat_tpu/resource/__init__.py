"""Resource SPI (reference ``resource/`` module, SURVEY.md §2.1).

The contract for a distributed object:

- client side: :class:`Resource`/:class:`AbstractResource` wrap every operation
  in a :class:`ResourceCommand`/:class:`ResourceQuery` carrying the configured
  :class:`Consistency` (reference ``AbstractResource.java:73,88``)
- server side: :class:`ResourceStateMachine` + executor unwrap those envelopes
  and dispatch the inner operation (reference ``ResourceStateMachineExecutor.java``)
- ``@resource_info(state_machine=...)`` binds a client resource class to its
  replicated state machine (reference ``ResourceInfo.java:31``)
"""

from .consistency import Consistency
from .operations import DeleteCommand, ResourceCommand, ResourceOperation, ResourceQuery
from .resource import AbstractResource, Resource, resource_info, resource_state_machine_of
from .state_machine import ResourceCommit, ResourceStateMachine, ResourceStateMachineExecutor

__all__ = [
    "Consistency",
    "ResourceCommand",
    "ResourceQuery",
    "ResourceOperation",
    "DeleteCommand",
    "Resource",
    "AbstractResource",
    "resource_info",
    "resource_state_machine_of",
    "ResourceStateMachine",
    "ResourceStateMachineExecutor",
    "ResourceCommit",
]
