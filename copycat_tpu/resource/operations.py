"""Operation envelopes (reference ``ResourceCommand``/``ResourceQuery``/
``ResourceOperation``, serializer ids 28/29/33; ``DeleteCommand`` from
``ResourceStateMachine.java:53``).

The wrapper carries (inner operation, consistency).  The inner operation's own
consistency — when it declares one by overriding ``consistency()`` to a
non-None value — overrides the wrapper's (reference ``ResourceCommand.java:40``).
"""

from __future__ import annotations

from typing import Any

from ..io.serializer import serialize_with
from ..protocol.messages import Message
from ..protocol.operations import Command, CommandConsistency, Persistence, Query, QueryConsistency


class ResourceOperation(Message):
    """Mixin for envelope ops: (operation, consistency-value).

    Serialization is the generic field list (byte-identical to the
    former hand-written write/read) so the native codec walks the
    envelope in C instead of calling back into Python per wrapped op —
    the envelope wraps EVERY resource op, so this is the difference
    between the codec fast path covering ~all of a command batch or
    almost none of it."""

    _fields = ("operation", "_consistency")

    def __init__(self, operation: Any = None, consistency: str | None = None) -> None:
        self.operation = operation
        self._consistency = consistency


@serialize_with(28)
class ResourceCommand(ResourceOperation, Command):
    """Wraps a resource command with the resource's write consistency."""

    def consistency(self) -> CommandConsistency:
        # An inner op that OVERRIDES consistency() declares its own level
        # (the reference's "non-null overrides the wrapper" rule).
        if isinstance(self.operation, Command) \
                and type(self.operation).consistency is not Command.consistency:
            inner = self.operation.consistency()
            if inner is not None:
                return inner
        if self._consistency is not None:
            return CommandConsistency(self._consistency)
        return CommandConsistency.LINEARIZABLE

    def persistence(self) -> Persistence:
        if isinstance(self.operation, Command):
            return self.operation.persistence()
        return Persistence.PERSISTENT


@serialize_with(29)
class ResourceQuery(ResourceOperation, Query):
    """Wraps a resource query with the resource's read consistency."""

    def consistency(self) -> QueryConsistency:
        if isinstance(self.operation, Query) \
                and type(self.operation).consistency is not Query.consistency:
            inner = self.operation.consistency()
            if inner is not None:
                return inner
        if self._consistency is not None:
            return QueryConsistency(self._consistency)
        return QueryConsistency.LINEARIZABLE


@serialize_with(34)
class DeleteCommand(Message, Command):
    """Deletes the resource's replicated state (reference
    ``ResourceStateMachine.java:53``)."""

    _fields = ()
