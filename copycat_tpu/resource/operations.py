"""Operation envelopes (reference ``ResourceCommand``/``ResourceQuery``/
``ResourceOperation``, serializer ids 28/29/33; ``DeleteCommand`` from
``ResourceStateMachine.java:53``).

The wrapper carries (inner operation, consistency).  The inner operation's own
consistency — when it declares one by overriding ``consistency()`` to a
non-None value — overrides the wrapper's (reference ``ResourceCommand.java:40``).
"""

from __future__ import annotations

from typing import Any

from ..io.buffer import BufferInput, BufferOutput
from ..io.serializer import Serializer, serialize_with
from ..protocol.operations import Command, CommandConsistency, Persistence, Query, QueryConsistency


class ResourceOperation:
    """Mixin for envelope ops: (operation, consistency-value)."""

    def __init__(self, operation: Any = None, consistency: str | None = None) -> None:
        self.operation = operation
        self._consistency = consistency

    def write_object(self, buf: BufferOutput, serializer: Serializer) -> None:
        serializer.write_object(self.operation, buf)
        serializer.write_object(self._consistency, buf)

    def read_object(self, buf: BufferInput, serializer: Serializer) -> None:
        self.operation = serializer.read_object(buf)
        self._consistency = serializer.read_object(buf)


@serialize_with(28)
class ResourceCommand(ResourceOperation, Command):
    """Wraps a resource command with the resource's write consistency."""

    def consistency(self) -> CommandConsistency:
        # An inner op that OVERRIDES consistency() declares its own level
        # (the reference's "non-null overrides the wrapper" rule).
        if isinstance(self.operation, Command) \
                and type(self.operation).consistency is not Command.consistency:
            inner = self.operation.consistency()
            if inner is not None:
                return inner
        if self._consistency is not None:
            return CommandConsistency(self._consistency)
        return CommandConsistency.LINEARIZABLE

    def persistence(self) -> Persistence:
        if isinstance(self.operation, Command):
            return self.operation.persistence()
        return Persistence.PERSISTENT


@serialize_with(29)
class ResourceQuery(ResourceOperation, Query):
    """Wraps a resource query with the resource's read consistency."""

    def consistency(self) -> QueryConsistency:
        if isinstance(self.operation, Query) \
                and type(self.operation).consistency is not Query.consistency:
            inner = self.operation.consistency()
            if inner is not None:
                return inner
        if self._consistency is not None:
            return QueryConsistency(self._consistency)
        return QueryConsistency.LINEARIZABLE


@serialize_with(34)
class DeleteCommand(Command):
    """Deletes the resource's replicated state (reference
    ``ResourceStateMachine.java:53``)."""

    def write_object(self, buf: BufferOutput, serializer: Serializer) -> None:
        pass

    def read_object(self, buf: BufferInput, serializer: Serializer) -> None:
        pass
