"""Client-side resource base classes (reference ``Resource.java:41``,
``AbstractResource.java:42``, ``ResourceInfo.java:31``, ``Resources.java:27``).
"""

from __future__ import annotations

from typing import Any, Callable, TypeVar

from ..protocol.operations import Command, Operation, Query
from .consistency import Consistency
from .operations import DeleteCommand, ResourceCommand, ResourceQuery

R = TypeVar("R", bound="Resource")


def resource_info(state_machine: type) -> Callable[[type], type]:
    """Binds a resource class to its server state machine class (the
    reference's ``@ResourceInfo(stateMachine=...)`` annotation)."""

    def bind(cls: type) -> type:
        cls.__resource_state_machine__ = state_machine
        return cls

    return bind


def resource_state_machine_of(resource_type: type) -> type:
    """Walks the MRO for the bound state machine (``Resources.getInfo``)."""
    for cls in resource_type.__mro__:
        machine = cls.__dict__.get("__resource_state_machine__")
        if machine is not None:
            return machine
    raise ValueError(f"{resource_type.__qualname__} has no @resource_info binding")


class Resource:
    """A distributed object replicated via the cluster (reference
    ``Resource.java:41-78``): consistency config, session identity, delete."""

    def __init__(self, client: Any) -> None:
        # ``client`` is a RaftClient-shaped object - normally an InstanceClient
        # (manager layer) so every op is routed to this resource's instance.
        self.client = client
        self._consistency = Consistency.ATOMIC
        # wire-level consistency strings, cached per facade: the enum
        # mapping lookups are per-op costs on the submit hot path
        self._write_cl = self._consistency.write_consistency().value
        self._read_cl = self._consistency.read_consistency().value

    def with_consistency(self, consistency: Consistency) -> "Resource":
        self._consistency = consistency
        self._write_cl = consistency.write_consistency().value
        self._read_cl = consistency.read_consistency().value
        return self

    @property
    def consistency(self) -> Consistency:
        return self._consistency

    def session(self) -> Any:
        return self.client.session()

    async def delete(self) -> None:
        """Delete the resource's replicated state."""
        await self.client.submit(DeleteCommand())


class AbstractResource(Resource):
    """Wraps every submitted op in Resource{Command,Query} with the configured
    consistency (reference ``AbstractResource.submit:73,88``)."""

    async def submit(self, operation: Operation) -> Any:
        if isinstance(operation, Query):
            return await self.client.submit(
                ResourceQuery(operation, self._read_cl))
        if isinstance(operation, Command):
            return await self.client.submit(
                ResourceCommand(operation, self._write_cl))
        raise TypeError(f"not an operation: {operation!r}")

    def submit_command(self, operation: Operation) -> Any:
        """Awaitable command submit with the submit chain flattened: when
        the client exposes the future-returning fast lane
        (``submit_command_nowait``), the whole facade→instance→client
        chain runs synchronously and the caller awaits ONE future — the
        per-op coroutine frames were a measured share of the public SPI
        plane's per-core ceiling (PERF.md round 6)."""
        nowait = getattr(self.client, "submit_command_nowait", None)
        command = ResourceCommand(operation, self._write_cl)
        if nowait is None:  # custom client shims: keep the coroutine path
            return self.client.submit(command)
        return nowait(command)

    async def _tracked_listener(self, listeners: Any, callback: Callable,
                                state: dict, listen_op: Operation,
                                unlisten_op_factory: Callable[[], Operation]):
        """First-listener-registers / last-close-unregisters pattern
        (reference ``DistributedAtomicValue.onChange`` et al.): the first local
        listener submits ``listen_op`` server-side; closing the last one
        submits the unlisten op in the background."""
        import asyncio

        from ..utils.tasks import spawn

        # Serialize listen/unlisten transitions: without the lock a failed
        # Listen would leave ``listening`` wedged True, and a background
        # Unlisten could race a new Listen submitted right after last-close.
        gate: asyncio.Lock = state.setdefault("gate", asyncio.Lock())
        # Register the local callback BEFORE submitting Listen: with ATOMIC
        # consistency the first event can arrive before the Listen response
        # (events-before-response, reference Consistency.java:157-176).
        listener = listeners.add(callback)
        try:
            async with gate:
                if not state.get("listening"):
                    await self.submit(listen_op)  # flag flips only on success
                    state["listening"] = True
        except BaseException:
            listener.close()  # roll back so a retry re-submits
            raise
        original_close = listener.close

        async def unlisten_if_idle() -> None:
            async with gate:
                if len(listeners) == 0 and state.get("listening"):
                    await self.submit(unlisten_op_factory())
                    state["listening"] = False

        def close_and_maybe_unlisten() -> None:
            original_close()
            if len(listeners) == 0 and state.get("listening"):
                spawn(unlisten_if_idle(), name="resource-unlisten")

        listener.close = close_and_maybe_unlisten  # type: ignore[method-assign]
        return listener
