"""Async distributed set (reference ``DistributedSet.java:35``)."""

from __future__ import annotations

from typing import Any

from ..resource.resource import AbstractResource, resource_info
from . import commands as c
from .state import SetState


@resource_info(state_machine=SetState)
class DistributedSet(AbstractResource):
    async def add(self, value: Any, ttl: float | None = None) -> bool:
        return bool(await self.submit(c.SetAdd(value=value, ttl=ttl)))

    async def remove(self, value: Any) -> bool:
        return bool(await self.submit(c.SetRemove(value=value)))

    async def contains(self, value: Any) -> bool:
        return bool(await self.submit(c.SetContains(value=value)))

    async def is_empty(self) -> bool:
        return bool(await self.submit(c.SetIsEmpty()))

    async def size(self) -> int:
        return int(await self.submit(c.SetSize()))

    async def clear(self) -> None:
        await self.submit(c.SetClear())
