"""Async distributed map (reference ``DistributedMap.java:54``): the full
Map surface incl. TTL variants of every write."""

from __future__ import annotations

from typing import Any

from ..resource.resource import AbstractResource, resource_info
from . import commands as c
from .state import MapState


@resource_info(state_machine=MapState)
class DistributedMap(AbstractResource):
    async def is_empty(self) -> bool:
        return bool(await self.submit(c.MapIsEmpty()))

    async def size(self) -> int:
        return int(await self.submit(c.MapSize()))

    async def contains_key(self, key: Any) -> bool:
        return bool(await self.submit(c.MapContainsKey(key=key)))

    async def contains_value(self, value: Any) -> bool:
        return bool(await self.submit(c.MapContainsValue(value=value)))

    async def get(self, key: Any) -> Any:
        return await self.submit(c.MapGet(key=key))

    async def get_or_default(self, key: Any, default: Any) -> Any:
        return await self.submit(c.MapGetOrDefault(key=key, default=default))

    async def put(self, key: Any, value: Any, ttl: float | None = None) -> Any:
        return await self.submit(c.MapPut(key=key, value=value, ttl=ttl))

    async def put_if_absent(self, key: Any, value: Any, ttl: float | None = None) -> Any:
        return await self.submit(c.MapPutIfAbsent(key=key, value=value, ttl=ttl))

    async def remove(self, key: Any) -> Any:
        return await self.submit(c.MapRemove(key=key))

    async def remove_if_present(self, key: Any, value: Any) -> bool:
        return bool(await self.submit(c.MapRemoveIfPresent(key=key, value=value)))

    async def replace(self, key: Any, value: Any, ttl: float | None = None) -> Any:
        return await self.submit(c.MapReplace(key=key, value=value, ttl=ttl))

    async def replace_if_present(self, key: Any, expect: Any, value: Any,
                                 ttl: float | None = None) -> bool:
        return bool(await self.submit(
            c.MapReplaceIfPresent(key=key, expect=expect, value=value, ttl=ttl)))

    async def clear(self) -> None:
        await self.submit(c.MapClear())
