"""Async distributed multimap (reference ``DistributedMultiMap.java:35``):
key -> set of values."""

from __future__ import annotations

from typing import Any

from ..resource.resource import AbstractResource, resource_info
from . import commands as c
from .state import MultiMapState


@resource_info(state_machine=MultiMapState)
class DistributedMultiMap(AbstractResource):
    async def is_empty(self) -> bool:
        return bool(await self.submit(c.MultiMapIsEmpty()))

    async def size(self, key: Any = None) -> int:
        """Global size, or per-key value count when ``key`` given
        (reference ``MultiMapState.java:169-185``)."""
        return int(await self.submit(c.MultiMapSize(key=key)))

    async def contains_key(self, key: Any) -> bool:
        return bool(await self.submit(c.MultiMapContainsKey(key=key)))

    async def contains_entry(self, key: Any, value: Any) -> bool:
        return bool(await self.submit(c.MultiMapContainsEntry(key=key, value=value)))

    async def contains_value(self, value: Any) -> bool:
        return bool(await self.submit(c.MultiMapContainsValue(value=value)))

    async def put(self, key: Any, value: Any, ttl: float | None = None) -> bool:
        return bool(await self.submit(c.MultiMapPut(key=key, value=value, ttl=ttl)))

    async def get(self, key: Any) -> list:
        return list(await self.submit(c.MultiMapGet(key=key)))

    async def remove(self, key: Any, value: Any = None) -> Any:
        if value is None:
            return await self.submit(c.MultiMapRemove(key=key))
        return bool(await self.submit(c.MultiMapRemoveEntry(key=key, value=value)))

    async def clear(self) -> None:
        await self.submit(c.MultiMapClear())
