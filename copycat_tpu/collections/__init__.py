"""Distributed collections (reference ``collections/`` module, SURVEY.md §2.1):
map, multimap, set, queue — each a client class + replicated state machine +
operation catalog with the reference's TTL and log-cleaning discipline."""

from .map import DistributedMap
from .multimap import DistributedMultiMap
from .set import DistributedSet
from .queue import DistributedQueue
from .state import MapState, MultiMapState, QueueState, SetState

__all__ = [
    "DistributedMap",
    "DistributedMultiMap",
    "DistributedSet",
    "DistributedQueue",
    "MapState",
    "MultiMapState",
    "SetState",
    "QueueState",
]
