"""Collection operation catalogs.

Serializer id blocks follow the reference exactly (SURVEY.md §2.1):
map 60-72 (``MapCommands.java``), multimap 75-84 (``MultiMapCommands.java``),
queue 90-99 (``QueueCommands.java``), set 100-105 (``SetCommands.java``).

``TtlCommand.persistence()`` is PERSISTENT iff ttl>0; removals and clears are
always PERSISTENT (they are tombstones until compaction).
"""

from __future__ import annotations

from ..io.serializer import serialize_with
from ..protocol.messages import Message
from ..protocol.operations import Command, Persistence, Query


class TtlCommand(Message, Command):
    def persistence(self) -> Persistence:
        return Persistence.PERSISTENT if getattr(self, "ttl", None) else Persistence.EPHEMERAL


class Tombstone(Message, Command):
    def persistence(self) -> Persistence:
        return Persistence.PERSISTENT


# ---------------------------------------------------------------------------
# map (60-72)
# ---------------------------------------------------------------------------


@serialize_with(60)
class MapContainsKey(Message, Query):
    _fields = ("key",)


@serialize_with(61)
class MapContainsValue(Message, Query):
    _fields = ("value",)


@serialize_with(62)
class MapPut(TtlCommand):
    _fields = ("key", "value", "ttl")


@serialize_with(63)
class MapPutIfAbsent(TtlCommand):
    _fields = ("key", "value", "ttl")


@serialize_with(64)
class MapGet(Message, Query):
    _fields = ("key",)


@serialize_with(65)
class MapGetOrDefault(Message, Query):
    _fields = ("key", "default")


@serialize_with(66)
class MapRemove(Tombstone):
    _fields = ("key",)


@serialize_with(67)
class MapRemoveIfPresent(Tombstone):
    _fields = ("key", "value")


@serialize_with(68)
class MapReplace(TtlCommand):
    _fields = ("key", "value", "ttl")


@serialize_with(69)
class MapReplaceIfPresent(TtlCommand):
    _fields = ("key", "expect", "value", "ttl")


@serialize_with(70)
class MapIsEmpty(Message, Query):
    _fields = ()


@serialize_with(71)
class MapSize(Message, Query):
    _fields = ()


@serialize_with(72)
class MapClear(Tombstone):
    _fields = ()


# ---------------------------------------------------------------------------
# multimap (75-84)
# ---------------------------------------------------------------------------


@serialize_with(75)
class MultiMapContainsKey(Message, Query):
    _fields = ("key",)


@serialize_with(76)
class MultiMapContainsEntry(Message, Query):
    _fields = ("key", "value")


@serialize_with(77)
class MultiMapContainsValue(Message, Query):
    _fields = ("value",)


@serialize_with(78)
class MultiMapPut(TtlCommand):
    _fields = ("key", "value", "ttl")


@serialize_with(79)
class MultiMapGet(Message, Query):
    _fields = ("key",)


@serialize_with(80)
class MultiMapRemove(Tombstone):
    _fields = ("key",)


@serialize_with(81)
class MultiMapRemoveEntry(Tombstone):
    _fields = ("key", "value")


@serialize_with(82)
class MultiMapIsEmpty(Message, Query):
    _fields = ()


@serialize_with(83)
class MultiMapSize(Message, Query):
    _fields = ("key",)  # None = global size (MultiMapState.java:169-185)


@serialize_with(84)
class MultiMapClear(Tombstone):
    _fields = ()


# ---------------------------------------------------------------------------
# queue (90-99)
# ---------------------------------------------------------------------------


@serialize_with(90)
class QueueAdd(Message, Command):
    _fields = ("value",)


@serialize_with(91)
class QueueOffer(Message, Command):
    _fields = ("value",)


@serialize_with(92)
class QueuePeek(Message, Query):
    _fields = ()


@serialize_with(93)
class QueuePoll(Tombstone):
    # Mutates (dequeues + cleans) - a Command despite being a "read"
    # (reference QueueCommands: Peek is a Query but Poll/Element are Commands).
    _fields = ()


@serialize_with(94)
class QueueElement(Tombstone):
    _fields = ()


@serialize_with(95)
class QueueRemove(Tombstone):
    _fields = ("value",)  # value None = remove head


@serialize_with(96)
class QueueContains(Message, Query):
    _fields = ("value",)


@serialize_with(97)
class QueueIsEmpty(Message, Query):
    _fields = ()


@serialize_with(98)
class QueueSize(Message, Query):
    _fields = ()


@serialize_with(99)
class QueueClear(Tombstone):
    _fields = ()


# ---------------------------------------------------------------------------
# set (100-105)
# ---------------------------------------------------------------------------


@serialize_with(100)
class SetAdd(TtlCommand):
    _fields = ("value", "ttl")


@serialize_with(101)
class SetRemove(Tombstone):
    _fields = ("value",)


@serialize_with(102)
class SetContains(Message, Query):
    _fields = ("value",)


@serialize_with(103)
class SetIsEmpty(Message, Query):
    _fields = ()


@serialize_with(104)
class SetSize(Message, Query):
    _fields = ()


@serialize_with(105)
class SetClear(Tombstone):
    _fields = ()
