"""Collection state machines (reference ``MapState.java:32``,
``MultiMapState.java:30``, ``QueueState.java:30``, ``SetState.java:32``).

Live state is *retained commits*: each stored value keeps the commit that
created it and cleans it exactly when the effect is superseded (replaced,
removed, expired, cleared) — the log-cleaning discipline that makes
compaction correct (SURVEY.md §5.4)."""

from __future__ import annotations

from collections import deque
from typing import Any

from ..io.serializer import serialize_with
from ..resource.state_machine import ResourceStateMachine
from ..server.state_machine import Commit
from . import commands as c


class _Held:
    """A stored value + its originating commit + optional TTL timer."""

    __slots__ = ("value", "commit", "timer")

    def __init__(self, value: Any, commit: Commit, timer: Any = None):
        self.value = value
        self.commit = commit
        self.timer = timer

    def discard(self) -> None:
        if self.timer is not None:
            self.timer.cancel()
            self.timer = None
        self.commit.clean()


@serialize_with(73)
class MapState(ResourceStateMachine):
    def __init__(self) -> None:
        super().__init__()
        self._map: dict[Any, _Held] = {}

    # -- helpers -----------------------------------------------------------

    def _store(self, key: Any, value: Any, commit: Commit, ttl: float | None) -> None:
        held = _Held(value, commit)
        if ttl:
            def expire() -> None:
                current = self._map.get(key)
                if current is held:
                    del self._map[key]
                    held.commit.clean()

            held.timer = self.executor.schedule(ttl, expire)
        previous = self._map.get(key)
        if previous is not None:
            previous.discard()
        self._map[key] = held

    # -- queries -----------------------------------------------------------

    def contains_key(self, commit: Commit[c.MapContainsKey]) -> bool:
        try:
            return commit.operation.key in self._map
        finally:
            commit.close()

    def contains_value(self, commit: Commit[c.MapContainsValue]) -> bool:
        try:
            return any(h.value == commit.operation.value for h in self._map.values())
        finally:
            commit.close()

    def get(self, commit: Commit[c.MapGet]) -> Any:
        try:
            held = self._map.get(commit.operation.key)
            return held.value if held is not None else None
        finally:
            commit.close()

    def get_or_default(self, commit: Commit[c.MapGetOrDefault]) -> Any:
        try:
            held = self._map.get(commit.operation.key)
            return held.value if held is not None else commit.operation.default
        finally:
            commit.close()

    def is_empty(self, commit: Commit[c.MapIsEmpty]) -> bool:
        try:
            return not self._map
        finally:
            commit.close()

    def size(self, commit: Commit[c.MapSize]) -> int:
        try:
            return len(self._map)
        finally:
            commit.close()

    # -- commands ----------------------------------------------------------

    def put(self, commit: Commit[c.MapPut]) -> Any:
        op = commit.operation
        previous = self._map.get(op.key)
        result = previous.value if previous is not None else None
        self._store(op.key, op.value, commit, op.ttl)
        return result

    def put_if_absent(self, commit: Commit[c.MapPutIfAbsent]) -> Any:
        op = commit.operation
        previous = self._map.get(op.key)
        if previous is not None:
            commit.clean()
            return previous.value
        self._store(op.key, op.value, commit, op.ttl)
        return None

    def remove(self, commit: Commit[c.MapRemove]) -> Any:
        held = self._map.pop(commit.operation.key, None)
        commit.clean()
        if held is None:
            return None
        held.discard()
        return held.value

    def remove_if_present(self, commit: Commit[c.MapRemoveIfPresent]) -> bool:
        op = commit.operation
        held = self._map.get(op.key)
        commit.clean()
        if held is None or held.value != op.value:
            return False
        del self._map[op.key]
        held.discard()
        return True

    def replace(self, commit: Commit[c.MapReplace]) -> Any:
        op = commit.operation
        previous = self._map.get(op.key)
        if previous is None:
            commit.clean()
            return None
        self._store(op.key, op.value, commit, op.ttl)
        return previous.value

    def replace_if_present(self, commit: Commit[c.MapReplaceIfPresent]) -> bool:
        op = commit.operation
        previous = self._map.get(op.key)
        if previous is None or previous.value != op.expect:
            commit.clean()
            return False
        self._store(op.key, op.value, commit, op.ttl)
        return True

    def clear(self, commit: Commit[c.MapClear]) -> None:
        for held in self._map.values():
            held.discard()
        self._map.clear()
        commit.clean()

    def edge_state(self) -> Any:
        # full-state delta (docs/EDGE_READS.md): the v1 granularity is
        # the whole map per delta — the state-based-CRDT model exactly;
        # per-key delta states are the documented future refinement.
        # Armed TTL timers expire outside the apply path (invisible to
        # the delta plane's dirty marking): opt out, like snapshots.
        if any(h.timer is not None for h in self._map.values()):
            return NotImplemented
        return ("map", {k: h.value for k, h in self._map.items()})

    def delete(self) -> None:
        for held in self._map.values():
            held.discard()
        self._map.clear()


@serialize_with(74)
class MultiMapState(ResourceStateMachine):
    """key -> {value -> held} (reference nested Map<Object,Map<Object,Commit>>)."""

    def __init__(self) -> None:
        super().__init__()
        self._map: dict[Any, dict[Any, _Held]] = {}

    def contains_key(self, commit: Commit[c.MultiMapContainsKey]) -> bool:
        try:
            return commit.operation.key in self._map
        finally:
            commit.close()

    def contains_entry(self, commit: Commit[c.MultiMapContainsEntry]) -> bool:
        try:
            values = self._map.get(commit.operation.key)
            return values is not None and commit.operation.value in values
        finally:
            commit.close()

    def contains_value(self, commit: Commit[c.MultiMapContainsValue]) -> bool:
        try:
            return any(commit.operation.value in values for values in self._map.values())
        finally:
            commit.close()

    def put(self, commit: Commit[c.MultiMapPut]) -> bool:
        op = commit.operation
        values = self._map.setdefault(op.key, {})
        if op.value in values:
            commit.clean()
            return False
        held = _Held(op.value, commit)
        if op.ttl:
            def expire() -> None:
                current = self._map.get(op.key, {})
                if current.get(op.value) is held:
                    del current[op.value]
                    if not current:
                        self._map.pop(op.key, None)
                    held.commit.clean()

            held.timer = self.executor.schedule(op.ttl, expire)
        values[op.value] = held
        return True

    def get(self, commit: Commit[c.MultiMapGet]) -> list:
        try:
            return [h.value for h in self._map.get(commit.operation.key, {}).values()]
        finally:
            commit.close()

    def remove(self, commit: Commit[c.MultiMapRemove]) -> list:
        values = self._map.pop(commit.operation.key, None)
        commit.clean()
        if values is None:
            return []
        out = []
        for held in values.values():
            out.append(held.value)
            held.discard()
        return out

    def remove_entry(self, commit: Commit[c.MultiMapRemoveEntry]) -> bool:
        op = commit.operation
        values = self._map.get(op.key)
        commit.clean()
        if values is None or op.value not in values:
            return False
        values.pop(op.value).discard()
        if not values:
            del self._map[op.key]
        return True

    def is_empty(self, commit: Commit[c.MultiMapIsEmpty]) -> bool:
        try:
            return not self._map
        finally:
            commit.close()

    def size(self, commit: Commit[c.MultiMapSize]) -> int:
        try:
            key = commit.operation.key
            if key is not None:
                return len(self._map.get(key, {}))
            return sum(len(v) for v in self._map.values())
        finally:
            commit.close()

    def clear(self, commit: Commit[c.MultiMapClear]) -> None:
        for values in self._map.values():
            for held in values.values():
                held.discard()
        self._map.clear()
        commit.clean()

    def delete(self) -> None:
        for values in self._map.values():
            for held in values.values():
                held.discard()
        self._map.clear()


@serialize_with(106)
class SetState(ResourceStateMachine):
    def __init__(self) -> None:
        super().__init__()
        self._set: dict[Any, _Held] = {}

    def add(self, commit: Commit[c.SetAdd]) -> bool:
        op = commit.operation
        if op.value in self._set:
            commit.clean()
            return False
        held = _Held(op.value, commit)
        if op.ttl:
            def expire() -> None:
                if self._set.get(op.value) is held:
                    del self._set[op.value]
                    held.commit.clean()

            held.timer = self.executor.schedule(op.ttl, expire)
        self._set[op.value] = held
        return True

    def remove(self, commit: Commit[c.SetRemove]) -> bool:
        held = self._set.pop(commit.operation.value, None)
        commit.clean()
        if held is None:
            return False
        held.discard()
        return True

    def contains(self, commit: Commit[c.SetContains]) -> bool:
        try:
            return commit.operation.value in self._set
        finally:
            commit.close()

    def is_empty(self, commit: Commit[c.SetIsEmpty]) -> bool:
        try:
            return not self._set
        finally:
            commit.close()

    def size(self, commit: Commit[c.SetSize]) -> int:
        try:
            return len(self._set)
        finally:
            commit.close()

    def clear(self, commit: Commit[c.SetClear]) -> None:
        for held in self._set.values():
            held.discard()
        self._set.clear()
        commit.clean()

    def edge_state(self) -> Any:
        # TTL'd members expire outside the apply path: opt out (see map)
        if any(h.timer is not None for h in self._set.values()):
            return NotImplemented
        return ("set", list(self._set.keys()))

    def delete(self) -> None:
        for held in self._set.values():
            held.discard()
        self._set.clear()


@serialize_with(107)
class QueueState(ResourceStateMachine):
    """FIFO queue of retained commits (reference ``QueueState.java:30``)."""

    def __init__(self) -> None:
        super().__init__()
        self._queue: deque[_Held] = deque()

    def _enqueue(self, commit: Commit, value: Any) -> bool:
        self._queue.append(_Held(value, commit))
        return True

    def add(self, commit: Commit[c.QueueAdd]) -> bool:
        return self._enqueue(commit, commit.operation.value)

    def offer(self, commit: Commit[c.QueueOffer]) -> bool:
        return self._enqueue(commit, commit.operation.value)

    def peek(self, commit: Commit[c.QueuePeek]) -> Any:
        try:
            return self._queue[0].value if self._queue else None
        finally:
            commit.close()

    def poll(self, commit: Commit[c.QueuePoll]) -> Any:
        commit.clean()
        if not self._queue:
            return None
        held = self._queue.popleft()
        held.discard()
        return held.value

    def element(self, commit: Commit[c.QueueElement]) -> Any:
        commit.clean()
        if not self._queue:
            raise ValueError("queue is empty")
        return self._queue[0].value

    def remove(self, commit: Commit[c.QueueRemove]) -> Any:
        op = commit.operation
        commit.clean()
        if op.value is None:
            if not self._queue:
                raise ValueError("queue is empty")
            held = self._queue.popleft()
            held.discard()
            return held.value
        for held in self._queue:
            if held.value == op.value:
                self._queue.remove(held)
                held.discard()
                return True
        return False

    def contains(self, commit: Commit[c.QueueContains]) -> bool:
        try:
            return any(h.value == commit.operation.value for h in self._queue)
        finally:
            commit.close()

    def is_empty(self, commit: Commit[c.QueueIsEmpty]) -> bool:
        try:
            return not self._queue
        finally:
            commit.close()

    def size(self, commit: Commit[c.QueueSize]) -> int:
        try:
            return len(self._queue)
        finally:
            commit.close()

    def clear(self, commit: Commit[c.QueueClear]) -> None:
        for held in self._queue:
            held.discard()
        self._queue.clear()
        commit.clean()

    def delete(self) -> None:
        for held in self._queue:
            held.discard()
        self._queue.clear()
