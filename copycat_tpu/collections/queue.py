"""Async distributed FIFO queue (reference ``DistributedQueue.java:34``).

Peek is a query; poll/element/remove are commands — they mutate or must clean
retained commits (reference QueueCommands note, SURVEY.md §2.1)."""

from __future__ import annotations

from typing import Any

from ..resource.resource import AbstractResource, resource_info
from . import commands as c
from .state import QueueState


@resource_info(state_machine=QueueState)
class DistributedQueue(AbstractResource):
    async def add(self, value: Any) -> bool:
        return bool(await self.submit(c.QueueAdd(value=value)))

    async def offer(self, value: Any) -> bool:
        return bool(await self.submit(c.QueueOffer(value=value)))

    async def peek(self) -> Any:
        return await self.submit(c.QueuePeek())

    async def poll(self) -> Any:
        return await self.submit(c.QueuePoll())

    async def element(self) -> Any:
        """Head of the queue; raises if empty."""
        return await self.submit(c.QueueElement())

    async def remove(self, value: Any = None) -> Any:
        """Remove head (value=None, raises if empty) or a specific value."""
        return await self.submit(c.QueueRemove(value=value))

    async def contains(self, value: Any) -> bool:
        return bool(await self.submit(c.QueueContains(value=value)))

    async def is_empty(self) -> bool:
        return bool(await self.submit(c.QueueIsEmpty()))

    async def size(self) -> int:
        return int(await self.submit(c.QueueSize()))

    async def clear(self) -> None:
        await self.submit(c.QueueClear())
