"""Host side of the device-plane flight recorder (docs/OBSERVABILITY.md).

The jitted consensus step emits a :class:`~copycat_tpu.ops.consensus.
DeviceTelemetry` block of per-group reductions when ``Config.telemetry``
is on (elections, leader changes, term bumps, leaderless rounds, commit
advance, applies by pool, ring pressure, submit rejections, vote splits,
outbox drain/drop) — fetched with the outputs the driver already
transfers. This module turns those raw deltas into the three host
surfaces:

- :class:`DeviceTelemetryHub` — a dedicated ``MetricsRegistry`` holding
  the ``device.*`` metric family (exported via ``/stats``, ``/metrics``,
  ``copycat-tpu stats`` and ``bench.py --metrics-json``), plus per-group
  cumulative arrays so multichip runs can attribute elections /
  commit-advance per shard (``parallel/scaling.py``,
  ``MultiHostRaftGroups.merged_device_snapshot``).
- :class:`FlightRecorder` — a bounded ring of timestamped events: one
  per fetch that observed protocol activity, plus every nemesis fault
  installation (``testing/nemesis.py`` writes into the same ring) and
  every invariant violation — so an election spike sits NEXT to the
  partition that caused it in one ``/flight`` dump.
- :class:`InvariantMonitor` — online safety checks on every fetch:
  commit totals and per-group commit indexes monotone, leader-term
  monotonicity at election rounds (the sound form of term-max
  monotonicity: a NEWLY ELECTED leader's term is strictly above every
  leader term its group showed before — its vote quorum intersects any
  earlier leader's. Raw lane terms are NOT the witness — a stale-lane
  snapshot install can lower a deposed candidate's inflated term — and
  between elections the max-over-lanes VIEW may regress legitimately
  when a higher-term leader steps down while a lower-term zombie stays
  visible), leaderless-fraction bound, and a sampled watch-list
  verifying ≤1 leader per (group, term). Violations increment
  ``device.invariant_violations{kind=...}``, land in the flight ring,
  and RAISE under ``COPYCAT_INVARIANTS=strict``.

``COPYCAT_INVARIANTS`` modes: unset/``observe`` — check and count;
``strict`` — check and raise :class:`InvariantViolation`; ``off``/``0``
— skip the checks entirely (telemetry metrics still flow). Setting
``COPYCAT_INVARIANTS`` (or ``COPYCAT_TELEMETRY=1``) also opt-ins
telemetry on engines whose ``Config`` left it off — how CI runs the
nemesis suite under strict invariants without touching every test.
"""

from __future__ import annotations

import json
import time
from collections import deque
from typing import Any

import numpy as np

from ..ops.apply import NUM_POOLS
from ..utils import knobs
from ..utils.metrics import MetricsRegistry

#: pool-id → label for the ``device.applies{pool=...}`` family (the
#: trailing bucket collects NoOps + config entries — POOL_NONE).
POOL_NAMES = ("value", "map", "set", "queue", "lock", "election",
              "multimap", "topic", "noop")
assert len(POOL_NAMES) == NUM_POOLS + 1

#: invariant check kinds (eagerly registered so the metric key set is
#: identical on every process — the multihost merge gathers by key)
INVARIANT_KINDS = ("commit_monotone", "term_monotone",
                   "leaderless_bound", "leader_per_term")

_COUNTERS = ("device.rounds", "device.elections_started",
             "device.leader_changes", "device.term_bumps",
             "device.leaderless_rounds", "device.commit_advance",
             "device.submit_rejections", "device.vote_splits",
             "device.events_drained", "device.events_dropped")
_GAUGES = ("device.leaderless_groups", "device.term_max",
           "device.commit_total", "device.ring_occupancy_max",
           "device.ring_occupancy_mean")

#: gauges that are SUMS over a process's own (disjoint) group block —
#: a cross-shard/cross-process fold must ADD these, not take the max
#: (merge_snapshots' gauge default). term/occupancy maxima stay max.
ADDITIVE_GAUGES = ("device.commit_total", "device.leaderless_groups")


class InvariantViolation(AssertionError):
    """A device-plane safety invariant failed under
    ``COPYCAT_INVARIANTS=strict``."""


def invariants_mode() -> str:
    """Resolve ``COPYCAT_INVARIANTS`` to ``off`` | ``observe`` |
    ``strict`` (unset defaults to ``observe``)."""
    raw = knobs.get_str("COPYCAT_INVARIANTS", default="observe").strip().lower()
    if raw in ("0", "off", "none", "disabled"):
        return "off"
    if raw == "strict":
        return "strict"
    return "observe"


def telemetry_env_enabled() -> bool:
    """True when the environment opts device telemetry IN for engines
    whose Config left it off: ``COPYCAT_TELEMETRY=1`` or an explicit
    ``COPYCAT_INVARIANTS`` mode that needs the data (observe/strict)."""
    if knobs.get_str("COPYCAT_TELEMETRY", default="").strip().lower() in (
            "1", "on", "true", "yes"):
        return True
    inv = knobs.get_raw("COPYCAT_INVARIANTS")
    if inv is None:
        return False
    return invariants_mode() != "off"


class FlightRecorder:
    """Bounded ring of device-plane events (telemetry spikes, injected
    faults, invariant violations) with host timestamps and engine round
    numbers — the correlation surface: a fault event and the election
    burst it caused sit adjacent in one dump."""

    def __init__(self, capacity: int = 256) -> None:
        self._ring: deque = deque(maxlen=max(1, capacity))
        self._seq = 0
        #: optional durable spill (``utils/health.py::BlackBox``): when
        #: the health plane wires it, every ring event is also appended
        #: to the crash-surviving on-disk black-box — so the events
        #: leading up to a SIGKILL are readable after the restart.
        #: ``None`` (the default, and the COPYCAT_HEALTH=0 plane) keeps
        #: the ring memory-only, exactly the pre-health behavior.
        self.spill = None

    def record(self, kind: str, round_no: int, **fields) -> dict:
        self._seq += 1
        event = {"seq": self._seq, "t": round(time.time(), 3),
                 "round": int(round_no), "kind": kind, **fields}
        self._ring.append(event)
        if self.spill is not None:
            try:
                self.spill(event)
            except Exception:  # noqa: BLE001 - spill must never wound
                pass
        return event

    def events(self) -> list[dict]:
        return list(self._ring)

    def render_json(self) -> str:
        return json.dumps({"events": self.events()})

    def render_text(self) -> str:
        lines = []
        for ev in self._ring:
            extra = " ".join(f"{k}={v}" for k, v in ev.items()
                             if k not in ("seq", "t", "round", "kind"))
            lines.append(f"#{ev['seq']:<5} r{ev['round']:<8} "
                         f"{ev['kind']:<10} {extra}")
        return "\n".join(lines) + ("\n" if lines else "(no events)\n")


class InvariantMonitor:
    """Online device-plane safety checks fed one fetched telemetry
    block at a time (see the module docstring for the exact invariants
    and why leader terms — not raw lane terms — witness term
    monotonicity)."""

    WATCH = 16          # sampled groups on the per-term leader watch-list
    TERMS_PER_GROUP = 128  # per watched group: term→leader memory cap

    def __init__(self, num_groups: int, metrics: MetricsRegistry,
                 flight: FlightRecorder, mode: str | None = None,
                 leaderless_max: float | None = None) -> None:
        self.mode = mode if mode is not None else invariants_mode()
        self.violations = 0
        self._metrics = metrics
        self._flight = flight
        self._G = num_groups
        if leaderless_max is None:
            leaderless_max = knobs.get_float("COPYCAT_INVARIANT_LEADERLESS_MAX")
        self.leaderless_max = leaderless_max
        # evenly spread deterministic watch-list (no RNG: every process
        # of a multihost engine watches the same local groups)
        n = min(self.WATCH, num_groups)
        self._watch = np.unique(np.linspace(
            0, max(0, num_groups - 1), num=max(1, n)).astype(np.int64))
        self._leaders: dict[int, dict[int, int]] = {
            int(g): {} for g in self._watch}
        self.reset()

    def reset(self) -> None:
        """Drop monotonicity baselines (call after restoring an older
        checkpoint into the engine — state legitimately moved backward)."""
        self._commit_total = -1
        self._last_commit = np.full(self._G, -1, np.int64)
        self._last_leader_term = np.full(self._G, -1, np.int64)
        for d in self._leaders.values():
            d.clear()

    # -- checks ------------------------------------------------------------

    def _violate(self, kind: str, round_no: int, detail: str) -> None:
        self.violations += 1
        self._metrics.counter("device.invariant_violations",
                              kind=kind).inc()
        self._flight.record("violation", round_no, check=kind,
                            detail=detail)
        if self.mode == "strict":
            raise InvariantViolation(
                f"device invariant {kind} violated at round {round_no}: "
                f"{detail}")

    def observe(self, commit_max: np.ndarray, leader_lane: np.ndarray,
                leader_term: np.ndarray, leaderless: np.ndarray,
                leader_changes: np.ndarray, round_no: int) -> None:
        """Check one fetched round's derived values ([G] each)."""
        if self.mode == "off":
            return
        commit_max = np.asarray(commit_max, np.int64)
        leader_term = np.asarray(leader_term, np.int64)
        leader_changes = np.asarray(leader_changes, np.int64)
        total = int(commit_max.sum())
        if total < self._commit_total:
            self._violate(
                "commit_monotone", round_no,
                f"commit total regressed {self._commit_total} -> {total}")
        self._commit_total = max(self._commit_total, total)
        bad = np.flatnonzero(commit_max < self._last_commit)
        if bad.size:
            g = int(bad[0])
            self._violate(
                "commit_monotone", round_no,
                f"group {g} commit regressed "
                f"{int(self._last_commit[g])} -> {int(commit_max[g])} "
                f"(+{bad.size - 1} more)")
        np.maximum(self._last_commit, commit_max, out=self._last_commit)

        # Term monotonicity is checked at ELECTION rounds only: a newly
        # elected leader's term must be strictly above every leader term
        # the group has shown before (its voters' quorum intersects any
        # earlier leader's vote quorum). Between elections the max-over-
        # lanes VIEW may legitimately regress — a higher-term leader
        # stepping down (CheckQuorum) can leave a stale lower-term
        # zombie as the only visible leader — so ungated rounds only
        # advance the baseline, never judge it.
        has = leader_term >= 0
        won = has & (leader_changes > 0)
        bad = np.flatnonzero(won & (leader_term <= self._last_leader_term))
        if bad.size:
            g = int(bad[0])
            self._violate(
                "term_monotone", round_no,
                f"group {g} elected a leader at term "
                f"{int(leader_term[g])} <= previously observed leader "
                f"term {int(self._last_leader_term[g])} "
                f"(+{bad.size - 1} more)")
        np.maximum(self._last_leader_term,
                   np.where(has, leader_term, -1),
                   out=self._last_leader_term)

        frac = float(np.asarray(leaderless).sum()) / max(1, self._G)
        if frac > self.leaderless_max + 1e-9:
            self._violate(
                "leaderless_bound", round_no,
                f"leaderless fraction {frac:.3f} > bound "
                f"{self.leaderless_max:.3f}")

        lanes = np.asarray(leader_lane, np.int64)
        for g in self._watch:
            gi = int(g)
            t, lane = int(leader_term[gi]), int(lanes[gi])
            if t < 0 or lane < 0:
                continue
            seen = self._leaders[gi]
            prev = seen.get(t)
            if prev is not None and prev != lane:
                self._violate(
                    "leader_per_term", round_no,
                    f"group {gi} term {t}: leaders {prev} and {lane}")
            elif prev is None:
                if len(seen) >= self.TERMS_PER_GROUP:
                    del seen[min(seen)]
                seen[t] = lane

    def summary(self) -> dict:
        return {"mode": self.mode, "violations": self.violations,
                "watched_groups": [int(g) for g in self._watch],
                "leaderless_max": self.leaderless_max}


class DeviceTelemetryHub:
    """Folds fetched :class:`DeviceTelemetry` deltas into the
    ``device.*`` metric family, the flight ring, and the invariant
    monitor. One hub per engine (``RaftGroups.telemetry``)."""

    #: per-group cumulative series kept for shard attribution
    PER_GROUP = ("elections_started", "leader_changes", "commit_advance",
                 "leaderless", "applies_total")

    def __init__(self, num_groups: int, flight_capacity: int = 256,
                 mode: str | None = None,
                 record_quiet: bool = False) -> None:
        self.num_groups = num_groups
        self.registry = MetricsRegistry()
        self.flight = FlightRecorder(flight_capacity)
        self.monitor = InvariantMonitor(num_groups, self.registry,
                                        self.flight, mode=mode)
        self._record_quiet = record_quiet
        self._rounds = 0
        self._occ_sum = 0.0
        self._occ_max = 0
        self.per_group = {name: np.zeros(num_groups, np.int64)
                          for name in self.PER_GROUP}
        # Eager key creation: the metric key SET must be identical on
        # every process so the multihost merge can gather by key.
        for name in _COUNTERS:
            # copycheck: ignore[metric-registry] names from _COUNTERS (each in the device.* catalog)
            self.registry.counter(name)
        for name in _GAUGES:
            # copycheck: ignore[metric-registry] names from _GAUGES (each in the device.* catalog)
            self.registry.gauge(name)
        for pool in POOL_NAMES:
            self.registry.counter("device.applies", pool=pool)
        for kind in INVARIANT_KINDS:
            self.registry.counter("device.invariant_violations", kind=kind)

    # -- ingestion ---------------------------------------------------------

    def ingest(self, tel: Any, round_no: int) -> None:
        """Fold ONE fetched round's telemetry deltas in. ``tel`` is a
        ``DeviceTelemetry`` of host (numpy) leaves — exactly what the
        drivers' output fetch hands ``RaftGroups._harvest``."""
        m = self.registry
        self._rounds += 1
        m.counter("device.rounds").inc()

        elections = np.asarray(tel.elections_started, np.int64)
        changes = np.asarray(tel.leader_changes, np.int64)
        leaderless = np.asarray(tel.leaderless, np.int64)
        advance = np.asarray(tel.commit_advance, np.int64)
        applies = np.asarray(tel.applies, np.int64)      # [G, pools]
        rejections = int(np.asarray(tel.submit_rejections,
                                    np.int64).sum())
        dropped = int(np.asarray(tel.events_dropped, np.int64).sum())

        n_elections = int(elections.sum())
        n_changes = int(changes.sum())
        n_leaderless = int(leaderless.sum())
        n_advance = int(advance.sum())
        m.counter("device.elections_started").inc(n_elections)
        m.counter("device.leader_changes").inc(n_changes)
        m.counter("device.term_bumps").inc(
            int(np.asarray(tel.term_bumps, np.int64).sum()))
        m.counter("device.leaderless_rounds").inc(n_leaderless)
        m.counter("device.commit_advance").inc(n_advance)
        m.counter("device.submit_rejections").inc(rejections)
        m.counter("device.vote_splits").inc(
            int(np.asarray(tel.vote_splits, np.int64).sum()))
        m.counter("device.events_drained").inc(
            int(np.asarray(tel.events_drained, np.int64).sum()))
        m.counter("device.events_dropped").inc(dropped)
        per_pool = applies.sum(axis=0)
        for k, pool in enumerate(POOL_NAMES):
            if per_pool[k]:
                m.counter("device.applies", pool=pool).inc(int(per_pool[k]))

        occ = int(np.asarray(tel.ring_occ_max).max(initial=0))
        self._occ_max = max(self._occ_max, occ)
        self._occ_sum += occ
        m.gauge("device.leaderless_groups").set(n_leaderless)
        m.gauge("device.term_max").set(
            int(np.asarray(tel.term_max).max(initial=0)))
        m.gauge("device.commit_total").set(
            int(np.asarray(tel.commit_max, np.int64).sum()))
        m.gauge("device.ring_occupancy_max").set(self._occ_max)
        m.gauge("device.ring_occupancy_mean").set(
            round(self._occ_sum / self._rounds, 4))

        self.per_group["elections_started"] += elections
        self.per_group["leader_changes"] += changes
        self.per_group["commit_advance"] += advance
        self.per_group["leaderless"] += leaderless
        self.per_group["applies_total"] += applies.sum(axis=1)

        if self._record_quiet or n_elections or n_changes or n_leaderless \
                or rejections or dropped:
            self.flight.record(
                "telemetry", round_no, elections=n_elections,
                leader_changes=n_changes, leaderless_groups=n_leaderless,
                commit_advance=n_advance, submit_rejections=rejections,
                events_dropped=dropped)

        self.monitor.observe(tel.commit_max, tel.leader_lane,
                             tel.leader_term, leaderless, changes,
                             round_no)

    def ingest_stacked(self, tels: Any, first_round: int) -> None:
        """Fold a fused program's stacked ``[W, G]`` telemetry (deep
        scan / harvested per-round stash) in round order."""
        w = int(np.asarray(tels.elections_started).shape[0])
        for i in range(w):
            self.ingest(
                type(tels)(*(np.asarray(leaf)[i] for leaf in tels)),
                first_round + i)

    # -- exposition --------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``device.*`` family as a mergeable snapshot dict (counters
        sum, gauges max via ``_gauge_keys`` — ``merge_snapshots``)."""
        return self.registry.snapshot()

    def per_group_totals(self) -> dict:
        """Cumulative per-group arrays (copies) — the shard-attribution
        feed for ``parallel/scaling.py`` and multihost roll-ups."""
        return {k: v.copy() for k, v in self.per_group.items()}

    def shard_snapshots(self, n_shards: int) -> list[dict]:
        """Split the per-group cumulative telemetry into ``n_shards``
        contiguous group blocks (how a 1D ``('groups',)`` mesh lays
        shards out) and return one mergeable snapshot per shard."""
        snaps = []
        for shard, idx in enumerate(
                np.array_split(np.arange(self.num_groups), n_shards)):
            snap = {f"device.{name}": int(arr[idx].sum())
                    for name, arr in self.per_group.items()}
            snap["shard"] = shard
            snap["groups"] = int(idx.size)
            snaps.append(snap)
        return snaps
