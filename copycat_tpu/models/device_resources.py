"""Typed client facades over the device consensus path.

The reference's client-side resource classes (``DistributedAtomicValue.java:38``,
``DistributedAtomicLong.java:29``, ``DistributedMap.java:54``,
``DistributedSet.java:35``, ``DistributedQueue.java:34``,
``DistributedLock.java:58``, ``DistributedLeaderElection.java:66``) wrap a
session client and submit operation objects. Here each facade binds one
*group* of a :class:`~copycat_tpu.models.raft_groups.RaftGroups` batch and
submits device opcodes; every call is a quorum-committed, linearizable
command applied by the vectorized kernels (``ops/apply.py``).

Lock grants and election notifications are delivered as *events* (the
reference pushes session events, ``LockState.java publish("lock", …)``);
facades consume the group's event stream with a private cursor.

Synchronous by design: each call drives the batch loop until its tag
resolves. Batch-parallel use (the bench path) submits raw opcodes across
many groups instead.
"""

from __future__ import annotations

from . import raft_groups
from ..ops import apply as ops

FAIL = ops.FAIL


class DeviceResourceError(RuntimeError):
    """Fixed-capacity device pool overflowed (fall back to the CPU path)."""


def _check_value(v: int) -> int:
    """Device-path payloads must avoid the INT_MIN sentinel (apply.py)."""
    if v == FAIL:
        raise ValueError(
            "INT_MIN is reserved as the device-path FAIL sentinel")
    return v


class DeviceResource:
    """Base: one facade = one group of the batch.

    ``session`` (a :class:`~copycat_tpu.models.sessions.DeviceSession`)
    binds the facade to a device-path client identity: every call
    keep-alives it, a dead session raises instead of operating, and —
    for locks/elections — the session's id is the replicated holder/
    candidate id so crash expiry can release through the log.
    """

    def __init__(self, groups: "raft_groups.RaftGroups", group: int,
                 session=None) -> None:
        self._rg = groups
        self._group = group
        self._session = session
        # Events buffered before this facade existed were addressed to
        # predecessor facades (reference semantic: session events die with
        # the session, ManagedResourceSession.java) — start the cursor past
        # them so e.g. a stale lock grant can never satisfy a new holder.
        # Recovery after restore/event-loss goes through the authoritative
        # registers instead (OP_LOCK_HOLDER / OP_ELECT_LEADER fallbacks).
        evs = groups.events.get(group, [])
        self._ev_last = evs[-1][0] if evs else -1
        # Both read levels ride the query lane (no log append): ATOMIC
        # additionally requires the leader LEASE (quorum-acked latest
        # round — BOUNDED_LINEARIZABLE, Consistency.java:157-176) and
        # escalates to a quorum-committed command when the lease is
        # absent; SEQUENTIAL serves from the leader's applied state.
        self.consistency = "atomic"

    def with_consistency(self, level: str) -> "DeviceResource":
        """Set the read consistency level ('atomic' | 'sequential');
        chainable, mirroring ``Resource.with(Consistency)``."""
        if level not in ("atomic", "sequential"):
            raise ValueError(f"unknown consistency level {level!r}")
        self.consistency = level
        return self

    def _touch(self) -> None:
        if self._session is not None:
            self._session.keep_alive()  # raises when the session is dead

    def _run_until(self, tag: int) -> int:
        """Drive the batch until ``tag`` resolves, with the caller's
        session pinned: a client blocked in its own call is alive, and
        must not be expired by the very rounds its call is driving (the
        commit could otherwise return success AFTER the registry released
        the caller's locks)."""
        registry = self._rg._sessions
        if self._session is not None and registry is not None:
            registry.pin(self._session.id)
            try:
                self._rg.run_until([tag])
            finally:
                registry.unpin(self._session.id)
        else:
            self._rg.run_until([tag])
        return self._rg.results.pop(tag)  # facade path stays bounded

    def _call(self, opcode: int, a: int = 0, b: int = 0, c: int = 0) -> int:
        self._touch()
        return self._run_until(self._rg.submit(self._group, opcode, a, b, c))

    def _read(self, opcode: int, a: int = 0, b: int = 0, c: int = 0) -> int:
        """Route a read-only op by the configured consistency level.

        ATOMIC reads ride the lease-gated query lane (no log append; the
        leader lease certifies BOUNDED_LINEARIZABLE freshness) and
        escalate to a quorum-committed command automatically when the
        lease is absent — the reference's ATOMIC read level
        (``Consistency.java:157-176``)."""
        self._touch()
        level = "atomic" if self.consistency == "atomic" else "sequential"
        return self._run_until(self._rg.submit_query(
            self._group, opcode, a, b, c, consistency=level))

    def _checked(self, *args) -> int:
        result = self._call(*args)
        if result == FAIL:
            raise DeviceResourceError(
                f"device pool overflow/absent for op {args[0]} in group "
                f"{self._group}")
        return result

    def _events(self):
        """Yield this group's events newer than the facade's cursor."""
        for ev in self._rg.events.get(self._group, []):
            if ev[0] > self._ev_last:
                self._ev_last = ev[0]
                yield ev


class DeviceValue(DeviceResource):
    """Linearizable int32 register (DistributedAtomicValue.java:38)."""

    def get(self) -> int:
        return self._read(ops.OP_VALUE_GET)

    def set(self, value: int, ttl: int = 0) -> None:
        self._call(ops.OP_VALUE_SET, value, 0, ttl)

    def compare_and_set(self, expect: int, update: int) -> bool:
        return bool(self._call(ops.OP_VALUE_CAS, expect, update))

    def get_and_set(self, value: int) -> int:
        return self._call(ops.OP_VALUE_GET_AND_SET, value)


class DeviceLong(DeviceResource):
    """Counter (DistributedAtomicLong.java:29). Unlike the reference's
    client-side CAS-retry loop, the add is a single committed command —
    the apply kernel is already atomic in log order."""

    def get(self) -> int:
        return self._read(ops.OP_VALUE_GET)

    def add_and_get(self, delta: int = 1) -> int:
        return self._call(ops.OP_LONG_ADD, delta)

    def get_and_add(self, delta: int = 1) -> int:
        return self.add_and_get(delta) - delta

    def increment_and_get(self) -> int:
        return self.add_and_get(1)

    def decrement_and_get(self) -> int:
        return self.add_and_get(-1)


class DeviceMap(DeviceResource):
    """Fixed-keyspace int32→int32 map (DistributedMap.java:54)."""

    def put(self, key: int, value: int, ttl: int = 0) -> int:
        return self._checked(ops.OP_MAP_PUT, key, _check_value(value), ttl)

    def get(self, key: int) -> int:
        return self._read(ops.OP_MAP_GET, key)

    def get_or_default(self, key: int, default: int) -> int:
        return self._read(ops.OP_MAP_GET_OR_DEFAULT, key, default)

    def put_if_absent(self, key: int, value: int, ttl: int = 0) -> bool:
        return bool(self._checked(ops.OP_MAP_PUT_IF_ABSENT, key,
                                  _check_value(value), ttl))

    def remove(self, key: int) -> int:
        return self._call(ops.OP_MAP_REMOVE, key)

    def remove_if(self, key: int, value: int) -> bool:
        return bool(self._call(ops.OP_MAP_REMOVE_IF, key, value))

    def replace(self, key: int, value: int) -> int | None:
        result = self._call(ops.OP_MAP_REPLACE, key, _check_value(value))
        return None if result == FAIL else result

    def replace_if(self, key: int, expect: int, update: int) -> bool:
        return bool(self._call(ops.OP_MAP_REPLACE_IF, key, expect,
                               _check_value(update)))

    def contains_key(self, key: int) -> bool:
        return bool(self._read(ops.OP_MAP_CONTAINS_KEY, key))

    def contains_value(self, value: int) -> bool:
        return bool(self._read(ops.OP_MAP_CONTAINS_VALUE, value))

    def size(self) -> int:
        return self._read(ops.OP_MAP_SIZE)

    def is_empty(self) -> bool:
        return bool(self._read(ops.OP_MAP_IS_EMPTY))

    def clear(self) -> None:
        self._call(ops.OP_MAP_CLEAR)


class DeviceSet(DeviceResource):
    """Fixed-capacity int32 set (DistributedSet.java:35)."""

    def add(self, value: int, ttl: int = 0) -> bool:
        return bool(self._checked(ops.OP_SET_ADD, _check_value(value), 0,
                                  ttl))

    def remove(self, value: int) -> bool:
        return bool(self._call(ops.OP_SET_REMOVE, value))

    def contains(self, value: int) -> bool:
        return bool(self._read(ops.OP_SET_CONTAINS, value))

    def size(self) -> int:
        return self._read(ops.OP_SET_SIZE)

    def is_empty(self) -> bool:
        return self.size() == 0

    def clear(self) -> None:
        self._call(ops.OP_SET_CLEAR)


class DeviceQueue(DeviceResource):
    """FIFO int32 queue ring (DistributedQueue.java:34 device subset)."""

    def offer(self, value: int) -> bool:
        return bool(self._call(ops.OP_Q_OFFER, _check_value(value)))

    def add(self, value: int) -> None:
        if not self.offer(value):
            raise DeviceResourceError("queue full")

    def poll(self) -> int | None:
        result = self._call(ops.OP_Q_POLL)
        return None if result == FAIL else result

    def peek(self) -> int | None:
        result = self._read(ops.OP_Q_PEEK)
        return None if result == FAIL else result

    def size(self) -> int:
        return self._read(ops.OP_Q_SIZE)

    def is_empty(self) -> bool:
        return self.size() == 0

    def clear(self) -> None:
        self._call(ops.OP_Q_CLEAR)


class DeviceMultiMap(DeviceResource):
    """Fixed-capacity int32 multimap keyed on (key, value) pairs
    (DistributedMultiMap.java:35 / MultiMapState.java:30)."""

    def put(self, key: int, value: int, ttl: int = 0) -> bool:
        return bool(self._checked(ops.OP_MM_PUT, key, _check_value(value),
                                  ttl))

    def remove(self, key: int) -> int:
        """Remove every entry under ``key``; returns the count removed."""
        return self._call(ops.OP_MM_REMOVE, key)

    def remove_entry(self, key: int, value: int) -> bool:
        return bool(self._call(ops.OP_MM_REMOVE_ENTRY, key, value))

    def contains_key(self, key: int) -> bool:
        return bool(self._read(ops.OP_MM_CONTAINS_KEY, key))

    def contains_entry(self, key: int, value: int) -> bool:
        return bool(self._read(ops.OP_MM_CONTAINS_ENTRY, key, value))

    def contains_value(self, value: int) -> bool:
        return bool(self._read(ops.OP_MM_CONTAINS_VALUE, value))

    def count(self, key: int) -> int:
        """Entries under ``key`` (the reference's per-key size,
        MultiMapState.java:169-185)."""
        return self._read(ops.OP_MM_COUNT, key)

    def size(self) -> int:
        return self._read(ops.OP_MM_SIZE)

    def is_empty(self) -> bool:
        return bool(self._read(ops.OP_MM_IS_EMPTY))

    def clear(self) -> None:
        self._call(ops.OP_MM_CLEAR)


class DeviceTopic(DeviceResource):
    """Pub/sub through the log (DistributedTopic.java:61 / TopicState.java:31).

    ``publish`` commits a log entry whose apply fans out ONE broadcast
    event carrying the message; subscribers poll their group's event
    stream. A subscriber receives messages published AFTER its subscribe
    committed (the subscription cursor starts at the current stream
    position) and until unsubscribe — the reference's per-session fan-out
    semantic, with the fan-out itself done client-side at batch scale.
    """

    def __init__(self, groups, group, subscriber_id: int,
                 session=None) -> None:
        super().__init__(groups, group, session)
        self.subscriber_id = subscriber_id
        self._subscribed = False

    def subscribe(self) -> None:
        if self._subscribed:
            return  # idempotent; must not re-drain undelivered messages
        # Snapshot the cursor BEFORE the listen commits: everything
        # harvested after this point is delivered. A message published in
        # the same round but logged before the listen may be delivered
        # spuriously (at-least-once edge); snapshotting AFTER would
        # instead LOSE a message logged after the listen in that round.
        evs = self._rg.events.get(self._group, [])
        if evs:
            self._ev_last = max(self._ev_last, evs[-1][0])
        self._checked(ops.OP_TOPIC_LISTEN, self.subscriber_id)
        self._subscribed = True

    def unsubscribe(self) -> None:
        self._call(ops.OP_TOPIC_UNLISTEN, self.subscriber_id)
        self._subscribed = False

    def publish(self, message: int) -> int:
        """Publish; returns the subscriber count at the publish point."""
        return self._call(ops.OP_TOPIC_PUB, _check_value(message))

    def subscriber_count(self) -> int:
        return self._read(ops.OP_TOPIC_COUNT)

    def poll_messages(self) -> list[int]:
        """Messages broadcast since the last poll (while subscribed)."""
        if not self._subscribed:
            return []
        return [arg for _, code, _t, arg in self._events()
                if code == ops.EV_TOPIC_MSG]


class DeviceLock(DeviceResource):
    """Distributed mutex; grant arrives as a session event
    (DistributedLock.java:58 — completion via event, not command response).

    ``holder_id`` identifies this client in the lock's wait queue — pass a
    ``session`` instead to use the session id (the reference's model:
    lock state keyed by client session, auto-released on session death
    via the registry's log-ordered expiry fan-out)."""

    def __init__(self, groups, group, holder_id: int | None = None,
                 session=None) -> None:
        super().__init__(groups, group, session)
        if session is not None:
            # Death cleanup releases by session.id — a different manual
            # holder_id would silently void the crash-release guarantee.
            if holder_id is not None and holder_id != session.id:
                raise ValueError(
                    "pass either holder_id or session, not both: expiry "
                    "cleanup is keyed by the session id")
            holder_id = session.id
            session.bind(group, "lock")
        elif holder_id is None:
            raise ValueError("DeviceLock needs a holder_id or a session")
        self.holder_id = holder_id
        # grants won via the cancel race (cancel result 2): the grant event
        # still arrives later and must not satisfy a future acquire attempt
        self._swallow_grants = 0

    def _next_grant(self) -> bool:
        for _, code, target, _arg in self._events():
            if code == ops.EV_LOCK_GRANT and target == self.holder_id:
                if self._swallow_grants:
                    self._swallow_grants -= 1
                    continue
                return True
        return False

    def _await_grant(self, deadline_clock: int | None,
                     max_rounds: int = 500) -> bool:
        for i in range(max_rounds):
            self._touch()  # a blocked waiter is alive, not crashed
            if self._next_grant():
                return True
            if i % 20 == 19:
                # authoritative fallback: the replicated holder register is
                # ground truth even if the grant event was lost to outbox
                # overflow; swallow the (possibly still in-flight) event
                if self._call(ops.OP_LOCK_HOLDER) == self.holder_id:
                    self._swallow_grants += 1
                    return True
            if deadline_clock is not None and self._rg.clock >= deadline_clock:
                # Timeout observed: resolve the race through the log — the
                # CANCEL commits in total order with any grant (2 = we won
                # before the cancel applied; the lock is ours).
                if self._call(ops.OP_LOCK_CANCEL, self.holder_id) == 2:
                    self._swallow_grants += 1
                    return True
                return False
            self._rg.step_round()
        raise TimeoutError("no lock grant event")

    def lock(self) -> None:
        result = self._call(ops.OP_LOCK_ACQUIRE, self.holder_id, -1)
        if result == 1:
            return
        if result == 0:  # wait queue full
            raise DeviceResourceError("lock wait queue full")
        granted = self._await_grant(None)
        if not granted:  # unreachable for an untimed wait; fail loudly
            raise DeviceResourceError("lock wait aborted without grant")

    def try_lock(self, timeout: int = 0) -> bool:
        """``timeout`` in logical clock ticks; 0 = immediate."""
        result = self._call(
            ops.OP_LOCK_ACQUIRE, self.holder_id, max(0, timeout))
        if result == 1:
            return True
        if timeout <= 0 or result == 0:
            return False
        return self._await_grant(self._rg.clock + timeout)

    def unlock(self) -> None:
        self._call(ops.OP_LOCK_RELEASE, self.holder_id)


class DeviceElection(DeviceResource):
    """Leader election with epoch fencing tokens
    (DistributedLeaderElection.java:66 — epoch = commit index of the
    winning listen; ``is_leader(epoch)`` validates before fenced actions)."""

    def __init__(self, groups, group, candidate_id: int | None = None,
                 session=None) -> None:
        super().__init__(groups, group, session)
        if session is not None:
            if candidate_id is not None and candidate_id != session.id:
                raise ValueError(
                    "pass either candidate_id or session, not both: expiry "
                    "cleanup is keyed by the session id")
            candidate_id = session.id
            session.bind(group, "election")
        elif candidate_id is None:
            raise ValueError(
                "DeviceElection needs a candidate_id or a session")
        self.candidate_id = candidate_id
        self.epoch: int | None = None
        # promotions won but resigned before ever being polled: the elect
        # event is still in flight and must not satisfy a future listen
        self._swallow_elect = 0
        self._unresolved_polls = 0

    def listen(self) -> int | None:
        """Enter the election; returns the epoch if elected immediately."""
        result = self._checked(ops.OP_ELECT_LISTEN, self.candidate_id)
        if result > 0:
            self.epoch = result
        return self.epoch

    def poll_elected(self) -> int | None:
        """Consume elect events; returns the epoch once this candidate wins."""
        for _, code, target, arg in self._events():
            if code == ops.EV_ELECT and target == self.candidate_id:
                if self._swallow_elect:
                    self._swallow_elect -= 1
                    continue
                self.epoch = arg
        if self.epoch is None:
            # The elect event can be lost to outbox-ring overflow (drop-
            # oldest) or host-buffer trimming; every 20 unresolved polls
            # consult the authoritative replicated leader register instead
            # (mirrors DeviceLock._await_grant's fallback cadence).
            self._unresolved_polls += 1
            if self._unresolved_polls % 20 == 0:
                return self.refresh()
        return self.epoch

    def refresh(self) -> int | None:
        """Authoritative leadership check through the log (survives event
        loss): updates and returns ``epoch`` if this candidate leads now."""
        if self._call(ops.OP_ELECT_LEADER) == self.candidate_id:
            epoch = self._call(ops.OP_ELECT_GET_EPOCH)
            # leader+epoch were two commands; re-verify the pair atomically
            # through the fencing check before trusting it
            if self._call(ops.OP_ELECT_IS_LEADER, self.candidate_id, epoch):
                if self.epoch is None:
                    self._swallow_elect += 1  # elect event may still arrive
                self.epoch = epoch
                return self.epoch
        return None

    def is_leader(self, epoch: int | None = None) -> bool:
        epoch = self.epoch if epoch is None else epoch
        if epoch is None:
            return False
        return bool(self._call(ops.OP_ELECT_IS_LEADER, self.candidate_id,
                               epoch))

    def resign(self) -> bool:
        was_leader = bool(self._call(ops.OP_ELECT_RESIGN, self.candidate_id))
        if was_leader and self.epoch is None:
            # we were promoted but never consumed the elect event
            self._swallow_elect += 1
        self.epoch = None
        return was_leader
