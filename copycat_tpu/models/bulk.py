"""Pipelined bulk data plane: client-visible throughput at device scale.

The queue-managed host runtime (``RaftGroups.submit``/``run_until``)
pays Python per op — deque staging, dict harvest, retry bookkeeping —
which caps client-visible throughput around ~10^5 ops/sec regardless of
device speed. This driver is the other end of the trade: a VECTORIZED
submit scheduler (numpy fancy-indexing end to end, zero per-op Python)
with DOUBLE-BUFFERED rounds — round N+1 is dispatched before round N's
outputs are fetched, so host staging/harvest overlaps device compute and
the tunnel round-trip (the round-3 residual: one serialized
submit→compute→fetch cycle per round).

Safety vs the queue-managed path:

- SAFETY is unconditional: an op is resubmitted only if its slot was NOT
  accepted into a leader log (``out.accepted``); accepted ops are never
  re-sent, so double-apply is impossible under any fault.
- LIVENESS assumes fault-free delivery (the engine's own full-delivery
  default): an accepted entry lost to a leader change would never
  resolve and ``drive`` raises after ``max_rounds``. Clients running
  under nemesis/partitions belong on the queue-managed path, whose
  provable-loss retry handles exactly that (``raft_groups._harvest``).

Reference framing: the reference's client runtime pipelines sequenced
commands per session (Copycat client, SURVEY.md §2.3); this is the
batch-scale equivalent for the north-star metric (BASELINE.md: ≥1M
client-visible linearizable ops/sec).
"""

from __future__ import annotations

import time
from typing import Any

import jax
import numpy as np


class BulkResult:
    """Results + client-observed latency percentiles for one drive."""

    __slots__ = ("results", "rounds", "wall_s", "dispatch_round",
                 "resolve_round")

    def __init__(self, results, rounds, wall_s, dispatch_round,
                 resolve_round) -> None:
        self.results = results
        self.rounds = rounds
        self.wall_s = wall_s
        self.dispatch_round = dispatch_round
        self.resolve_round = resolve_round

    def latency_rounds(self) -> np.ndarray:
        """Per-op submit→result latency in driver rounds (client view)."""
        return self.resolve_round - self.dispatch_round + 1

    def latency_percentiles_ms(self, qs=(50, 99)) -> dict:
        lat = self.latency_rounds().astype(np.float64)
        ms_per_round = self.wall_s * 1e3 / max(1, self.rounds)
        return {f"p{q}": float(np.percentile(lat, q)) * ms_per_round
                for q in qs}


class BulkDriver:
    """Vectorized pipelined driver over one :class:`RaftGroups` batch."""

    def __init__(self, rg) -> None:
        # Single-host engines only: the bulk loop feeds host numpy
        # straight into the step and fetches whole outputs, bypassing the
        # multihost staging/lockstep hooks step_round routes through.
        from .raft_groups import RaftGroups
        if (getattr(rg, "process_count", 1) > 1
                or type(rg)._stage_submits is not RaftGroups._stage_submits
                or type(rg)._fetch_outputs is not RaftGroups._fetch_outputs):
            raise NotImplementedError(
                "BulkDriver drives single-host RaftGroups only; multihost "
                "engines use the queue-managed lockstep path")
        # Device-session engines need the per-round session tick (keep-
        # alives ride the queue-managed submit path the bulk loop never
        # drains) — refuse rather than silently expire sessions.
        if rg._sessions is not None:
            raise NotImplementedError(
                "BulkDriver does not pump device sessions; use the "
                "queue-managed path (step_round) on session engines")
        self._rg = rg

    def drive(self, groups, opcode, a=0, b=0, c=0,
              max_rounds: int = 10_000) -> BulkResult:
        """Commit one op per entry of ``groups`` (scalars broadcast) and
        return all results; ops of one group keep submission order.

        Scheduling rule (FIFO-safe by construction): each round every
        group dispatches its first ≤S not-yet-ACCEPTED ops in op order —
        an op the engine rejected (backpressure, lease-refusal) is
        re-sent before any later op of its group is ever dispatched.
        The tiny per-round ``accepted`` array is fetched synchronously
        to drive that rule; the large result arrays are harvested one
        round behind (double buffer), so host staging and the bulk of
        the D2H transfer overlap device compute.
        """
        rg = self._rg
        S = rg.submit_slots
        t0 = time.perf_counter()

        g_arr = np.asarray(groups, np.int64).ravel()
        n = g_arr.size
        bc = lambda x: np.broadcast_to(
            np.asarray(x, np.int32).ravel(), (n,)).copy()
        op_a, a_a, b_a, c_a = bc(opcode), bc(a), bc(b), bc(c)

        # fixed group-stable order + segment starts for per-round ranking
        order = np.argsort(g_arr, kind="stable")
        g_sorted = g_arr[order]
        first = np.ones(n, bool)
        first[1:] = g_sorted[1:] != g_sorted[:-1]
        starts = np.flatnonzero(first)
        counts = np.diff(np.append(starts, n))

        # tags are a RESERVED contiguous block off the engine's counter,
        # so bulk tags can never collide with queue-path tags or an
        # earlier drive's re-reported entries
        tag0 = rg._next_tag
        rg._next_tag += n
        results = np.zeros(n, np.int64)
        resolved = np.zeros(n, bool)
        accepted_ops = np.zeros(n, bool)
        dispatched = np.zeros(n, bool)
        dispatch_round = np.zeros(n, np.int64)
        resolve_round = np.zeros(n, np.int64)

        def build(r: int):
            """First ≤S unaccepted ops per group, in op order."""
            mask = ~accepted_ops[order]
            mi = mask.astype(np.int64)
            excl = np.cumsum(mi) - mi          # exclusive prefix count
            base = np.repeat(excl[starts], counts)
            rank = excl - base                 # unaccepted-rank in group
            sel = mask & (rank < S)
            idx = order[sel]
            slots = rank[sel]
            sub = rg._empty_submits()
            gi = g_arr[idx]
            sub.opcode[gi, slots] = op_a[idx]
            sub.a[gi, slots] = a_a[idx]
            sub.b[gi, slots] = b_a[idx]
            sub.c[gi, slots] = c_a[idx]
            sub.tag[gi, slots] = (tag0 + idx).astype(np.int32)
            sub.valid[gi, slots] = True
            fresh = ~dispatched[idx]
            dispatch_round[idx[fresh]] = r
            dispatched[idx] = True
            return sub, idx, gi, slots

        def harvest(r: int, raw) -> None:
            for leaf in (raw.out_valid, raw.out_tag, raw.out_result):
                leaf.copy_to_host_async()
            ov = np.asarray(raw.out_valid)
            if ov.any():
                tags = np.asarray(raw.out_tag)[ov]
                vals = np.asarray(raw.out_result)[ov]
                keep = (tags >= tag0) & (tags < tag0 + n)
                t = tags[keep] - tag0
                results[t] = vals[keep]
                newly = ~resolved[t]
                resolve_round[t[newly]] = r
                resolved[t] = True
                # entries reported once: a queue-managed op that applied
                # during this drive must resolve into rg.results, not
                # vanish behind the bulk tag filter
                for tg, vl in zip(tags[~keep].tolist(),
                                  vals[~keep].tolist()):
                    if tg in rg._inflight:
                        rg._inflight.pop(tg)
                        rg._inflight_ops.pop(tg, None)
                        placed = rg._tag_index.pop(tg, None)
                        if placed is not None:
                            rg._drop_placement(placed[0], placed[1])
                        rg.results[tg] = vl
            # session events drained by these rounds must reach the host
            # buffer (the device pops its ring as it drains)
            rg._ingest_events(raw)

        deliver = rg.deliver
        inflight: list[tuple[int, Any]] = []
        r = 0
        while not resolved.all():
            if r > max_rounds:
                missing = int(n - resolved.sum())
                raise TimeoutError(
                    f"bulk drive: {missing} ops unresolved after "
                    f"{max_rounds} rounds (fault-free liveness assumption"
                    f" violated? use the queue-managed path under faults)")
            sub, idx, gi, slots = build(r)
            rg._key, key = jax.random.split(rg._key)
            rg.state, raw = rg._step(rg.state, sub, deliver, key)
            # small synchronous fetch: acceptance gates the NEXT round's
            # dispatch window (FIFO safety)
            if idx.size:
                acc = np.asarray(raw.accepted)
                accepted_ops[idx[acc[gi, slots]]] = True
            # big outputs: one round behind (double buffer)
            inflight.append((r, raw))
            if len(inflight) > 1:
                pr, praw = inflight.pop(0)
                harvest(pr, praw)
            r += 1
            if resolved.all():
                break
            # drain the pipe when nothing is left to dispatch so the
            # last round's results are seen without an extra device step
            if accepted_ops.all() and inflight:
                pr, praw = inflight.pop(0)
                harvest(pr, praw)
        while inflight:
            pr, praw = inflight.pop(0)
            harvest(pr, praw)
        if not resolved.all():  # pragma: no cover - defensive
            missing = int(n - resolved.sum())
            raise TimeoutError(f"bulk drive: {missing} ops unresolved")
        rg.rounds += r
        rg.metrics.counter("ops_committed").inc(n)
        return BulkResult(results=results, rounds=r,
                          wall_s=time.perf_counter() - t0,
                          dispatch_round=dispatch_round,
                          resolve_round=resolve_round)


def drive_batch(rg, groups, opcode, a=0, b=0, c=0,
                max_rounds: int = 10_000) -> BulkResult:
    """Module-level convenience: ``BulkDriver(rg).drive(...)``."""
    return BulkDriver(rg).drive(groups, opcode, a, b, c,
                                max_rounds=max_rounds)
