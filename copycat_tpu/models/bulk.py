"""Pipelined bulk data plane: client-visible throughput at device scale.

The queue-managed host runtime (``RaftGroups.submit``/``run_until``)
pays Python per op — deque staging, dict harvest, retry bookkeeping —
which caps client-visible throughput around ~10^5 ops/sec regardless of
device speed. This driver is the other end of the trade: a VECTORIZED
submit scheduler (numpy fancy-indexing end to end, zero per-op Python)
with DOUBLE-BUFFERED rounds — round N+1 is dispatched before round N's
outputs are fetched, so host staging/harvest overlaps device compute and
the tunnel round-trip (the round-3 residual: one serialized
submit→compute→fetch cycle per round).

Safety vs the queue-managed path:

- SAFETY is unconditional: an op is resubmitted only if its slot was NOT
  accepted into a leader log (``out.accepted``); accepted ops are never
  re-sent, so double-apply is impossible under any fault.
- LIVENESS assumes fault-free delivery (the engine's own full-delivery
  default): an accepted entry lost to a leader change would never
  resolve and ``drive`` raises after ``max_rounds``. Clients running
  under nemesis/partitions belong on the queue-managed path, whose
  provable-loss retry handles exactly that (``raft_groups._harvest``).

Reference framing: the reference's client runtime pipelines sequenced
commands per session (Copycat client, SURVEY.md §2.3); this is the
batch-scale equivalent for the north-star metric (BASELINE.md: ≥1M
client-visible linearizable ops/sec).

Two dispatch modes, chosen by the engine's Config:

- CLASSIC (default engines): FIFO safety is host-enforced — a small
  synchronous ``accepted`` fetch per round gates the next window. One
  blocking device round-trip per round; correct under any engine.
- DEEP (``Config.monotone_tag_accept`` engines): FIFO + dedup are
  DEVICE-enforced by the monotone tag gate, so the host dispatches
  blindly with zero blocking fetches and collects results from
  on-device ``[G, B]`` accumulators in ONE fetch per drive
  (``ops/consensus.deep_step``). Through a tunneled TPU this removes
  the per-round round-trip that dominated the round-4 profile
  (~65 ms/round → amortized to ~one transfer per drive).
"""

from __future__ import annotations

import time
from functools import lru_cache, partial
from typing import Any

import jax
import numpy as np

from ..ops.consensus import Submits, deep_scan, deep_step


def _scatter(G: int, S: int, gi, slots, vals) -> np.ndarray:
    arr = np.zeros((G, S), np.int32)
    arr[gi, slots] = vals
    return arr


def stream_count_from_state(state, fetch=jax.device_get) -> np.ndarray:
    """[G] max live-ring stream tag per group, from the most-advanced
    lane's log — the device-authoritative value of the monotone stream
    cursor (``RaftGroups._stream_count``). Used to resync after an
    abandoned drive and to rebuild the cursor on checkpoint restore
    (election no-ops carry tag 0 and never inflate it). ``fetch``
    overrides the device→host transfer (multihost engines pass their
    local-block fetch so G is the process-local block)."""
    log_tag, last = (np.asarray(x) for x in fetch(
        (state.log_tag, state.last_index)))
    G, _, L = log_tag.shape
    lane = last.argmax(axis=1)                       # [G]
    lt = log_tag[np.arange(G), lane]                 # [G,L]
    ll = last[np.arange(G), lane]                    # [G]
    j = np.arange(L)[None, :]
    idx = ll[:, None] - ((ll[:, None] - (j + 1)) % L)
    in_log = (idx >= 1) & (idx <= ll[:, None])
    return np.where(in_log, lt, 0).max(axis=1).astype(np.int64)


def _window_rank(mask: np.ndarray, starts: np.ndarray, counts: np.ndarray,
                 S: int) -> tuple[np.ndarray, np.ndarray]:
    """First <=S True positions per segment, vectorized.

    ``mask`` lives in group-sorted space with segments described by
    ``starts``/``counts``; returns ``(positions, slots)`` where each
    position's slot is its rank among its segment's True entries. The
    per-pass scheduling core shared by the classic drive and the query
    drive (FIFO by construction: earlier pending ops always outrank
    later ones)."""
    mi = mask.astype(np.int64)
    excl = np.cumsum(mi) - mi
    base = np.repeat(excl[starts], counts)
    rank = excl - base
    sel = mask & (rank < S)
    pos = np.flatnonzero(sel)
    return pos, rank[pos]


@lru_cache(maxsize=None)
def _deep_scan_program(config, onehot: bool = False, donate: bool = False):
    """Jitted :func:`deep_scan` (whole blind phase as one program; W
    specializes by shape). Donation hands the state + accumulators back
    for in-place reuse on accelerators."""
    return jax.jit(partial(deep_scan, config=config, onehot=onehot),
                   donate_argnums=(0, 1, 2, 3, 4) if donate else ())


@lru_cache(maxsize=None)
def _deep_program(config, onehot: bool = False, donate: bool = False):
    """Jitted deep_step shared across drivers with the same static Config.

    ``onehot`` selects the accumulator formulation: sharded engines use
    the one-hot select-reduce (shard-local by construction — the .at[]
    scatter compiled to all-gathers of the [G,B] buffers on a mesh);
    single-device engines keep the O(G*A) scatter (measured faster on
    CPU; scatter never pays a collective off-mesh). ``donate`` hands
    state + accumulators back to XLA for in-place reuse — on for
    accelerators (saves a full state copy per round), off for CPU
    (donation is unimplemented there and only warns)."""
    return jax.jit(partial(deep_step, config=config, onehot=onehot),
                   donate_argnums=(0, 1, 2, 3, 4) if donate else ())


class BulkResult:
    """Results + client-observed latency percentiles for one drive."""

    __slots__ = ("results", "rounds", "wall_s", "dispatch_round",
                 "resolve_round")

    def __init__(self, results, rounds, wall_s, dispatch_round,
                 resolve_round) -> None:
        self.results = results
        self.rounds = rounds
        self.wall_s = wall_s
        self.dispatch_round = dispatch_round
        self.resolve_round = resolve_round

    def latency_rounds(self) -> np.ndarray:
        """Per-op submit→result latency in driver rounds (client view)."""
        return self.resolve_round - self.dispatch_round + 1

    def latency_percentiles_ms(self, qs=(50, 99)) -> dict:
        lat = self.latency_rounds().astype(np.float64)
        ms_per_round = self.wall_s * 1e3 / max(1, self.rounds)
        return {f"p{q}": float(np.percentile(lat, q)) * ms_per_round
                for q in qs}


class BulkDriver:
    """Vectorized pipelined driver over one :class:`RaftGroups` batch."""

    def __init__(self, rg, *, allow_sessions: bool = False,
                 deep_scan: bool = False) -> None:
        # The CLASSIC drive feeds host numpy straight into the step and
        # fetches whole outputs, bypassing the multihost staging/lockstep
        # hooks step_round routes through — single-host engines only.
        # The DEEP drive (monotone-tag engines) goes through the
        # _stage_acc/_fetch_acc/_deep_fn/_stage_submits hooks and agrees
        # on every stop decision, so it runs on multihost engines too.
        from .raft_groups import RaftGroups
        deep = bool(getattr(rg.config, "monotone_tag_accept", False))
        if not deep and (
                getattr(rg, "process_count", 1) > 1
                or type(rg)._stage_submits is not RaftGroups._stage_submits
                or type(rg)._fetch_outputs is not RaftGroups._fetch_outputs):
            raise NotImplementedError(
                "the classic bulk drive needs a single-host RaftGroups; "
                "multihost engines use the queue-managed lockstep path or "
                "the deep drive (Config(monotone_tag_accept=True))")
        # Device-session engines need the session tick + cleanup routing
        # the raw bulk loop never performs — the sessioned client
        # (models/session_client.BulkSessionClient) takes that duty and
        # opts in; refuse otherwise rather than silently expire sessions.
        if rg._sessions is not None and not allow_sessions:
            raise NotImplementedError(
                "BulkDriver does not pump device sessions; drive session "
                "engines through models.session_client.BulkSessionClient")
        # deep_scan: run the whole blind phase as ONE lax.scan program
        # (one dispatch + one stacked payload upload per drive) instead
        # of one dispatch per window. Single-host only: the stacked
        # staging is not wired through the multihost hooks.
        if deep_scan and (not deep or getattr(rg, "process_count", 1) > 1):
            raise NotImplementedError(
                "deep_scan needs a single-host monotone-tag engine")
        self._scan = deep_scan
        self._rg = rg

    def drive(self, groups, opcode, a=0, b=0, c=0,
              max_rounds: int = 10_000,
              deliver_schedule=None) -> BulkResult:
        """Commit one op per entry of ``groups`` (scalars broadcast) and
        return all results; ops of one group keep submission order.

        Scheduling rule (FIFO-safe by construction): each round every
        group dispatches its first ≤S not-yet-ACCEPTED ops in op order —
        an op the engine rejected (backpressure, lease-refusal) is
        re-sent before any later op of its group is ever dispatched.
        The tiny per-round ``accepted`` array is fetched synchronously
        to drive that rule; the large result arrays are harvested one
        round behind (double buffer), so host staging and the bulk of
        the D2H transfer overlap device compute.
        """
        rg = self._rg
        S = rg.submit_slots
        t0 = time.perf_counter()

        g_arr = np.asarray(groups, np.int64).ravel()
        n = g_arr.size
        bc = lambda x: np.broadcast_to(
            np.asarray(x, np.int32).ravel(), (n,)).copy()
        op_a, a_a, b_a, c_a = bc(opcode), bc(a), bc(b), bc(c)
        if getattr(rg.config, "monotone_tag_accept", False):
            return self._drive_deep(g_arr, op_a, a_a, b_a, c_a,
                                    max_rounds, t0, deliver_schedule)
        if deliver_schedule is not None:
            raise NotImplementedError(
                "deliver_schedule is a deep-drive feature (fault "
                "injection with mid-drive recovery); classic engines "
                "take faults through rg.deliver + step_round")

        # fixed group-stable order + segment starts for per-round ranking
        order = np.argsort(g_arr, kind="stable")
        g_sorted = g_arr[order]
        first = np.ones(n, bool)
        first[1:] = g_sorted[1:] != g_sorted[:-1]
        starts = np.flatnonzero(first)
        counts = np.diff(np.append(starts, n))

        # tags are a RESERVED contiguous block off the engine's counter,
        # so bulk tags can never collide with queue-path tags or an
        # earlier drive's re-reported entries
        tag0 = rg._next_tag
        rg._next_tag += n
        results = np.zeros(n, np.int64)
        resolved = np.zeros(n, bool)
        accepted_ops = np.zeros(n, bool)
        dispatched = np.zeros(n, bool)
        dispatch_round = np.zeros(n, np.int64)
        resolve_round = np.zeros(n, np.int64)

        def build(r: int):
            """First ≤S unaccepted ops per group, in op order."""
            pos, slots = _window_rank(~accepted_ops[order], starts,
                                      counts, S)
            idx = order[pos]
            sub = rg._empty_submits()
            gi = g_arr[idx]
            sub.opcode[gi, slots] = op_a[idx]
            sub.a[gi, slots] = a_a[idx]
            sub.b[gi, slots] = b_a[idx]
            sub.c[gi, slots] = c_a[idx]
            sub.tag[gi, slots] = (tag0 + idx).astype(np.int32)
            sub.valid[gi, slots] = True
            fresh = ~dispatched[idx]
            dispatch_round[idx[fresh]] = r
            dispatched[idx] = True
            return sub, idx, gi, slots

        def harvest(r: int, raw) -> None:
            tel_leaves = (jax.tree.leaves(raw.telemetry)
                          if rg.telemetry is not None
                          and raw.telemetry is not None else ())
            for leaf in (raw.out_valid, raw.out_tag, raw.out_result,
                         *tel_leaves):
                leaf.copy_to_host_async()
            if tel_leaves:
                rg.telemetry.ingest(
                    jax.tree.map(np.asarray, raw.telemetry),
                    rg.rounds + r)
            ov = np.asarray(raw.out_valid)
            if ov.any():
                tags = np.asarray(raw.out_tag)[ov]
                vals = np.asarray(raw.out_result)[ov]
                keep = (tags >= tag0) & (tags < tag0 + n)
                t = tags[keep] - tag0
                results[t] = vals[keep]
                newly = ~resolved[t]
                resolve_round[t[newly]] = r
                resolved[t] = True
                # entries reported once: a queue-managed op that applied
                # during this drive must resolve into rg.results, not
                # vanish behind the bulk tag filter
                for tg, vl in zip(tags[~keep].tolist(),
                                  vals[~keep].tolist()):
                    if tg in rg._inflight:
                        rg._inflight.pop(tg)
                        rg._inflight_ops.pop(tg, None)
                        placed = rg._tag_index.pop(tg, None)
                        if placed is not None:
                            rg._drop_placement(placed[0], placed[1])
                        rg.results[tg] = vl
            # session events drained by these rounds must reach the host
            # buffer (the device pops its ring as it drains)
            rg._ingest_events(raw)

        deliver = rg.deliver
        inflight: list[tuple[int, Any]] = []
        r = 0
        while not resolved.all():
            if r > max_rounds:
                missing = int(n - resolved.sum())
                raise TimeoutError(
                    f"bulk drive: {missing} ops unresolved after "
                    f"{max_rounds} rounds (fault-free liveness assumption"
                    f" violated? use the queue-managed path under faults)")
            sub, idx, gi, slots = build(r)
            rg._key, key = jax.random.split(rg._key)
            rg.state, raw = rg._step(rg.state, sub, deliver, key)
            # small synchronous fetch: acceptance gates the NEXT round's
            # dispatch window (FIFO safety)
            if idx.size:
                acc = np.asarray(raw.accepted)
                accepted_ops[idx[acc[gi, slots]]] = True
            # big outputs: one round behind (double buffer)
            inflight.append((r, raw))
            if len(inflight) > 1:
                pr, praw = inflight.pop(0)
                harvest(pr, praw)
            r += 1
            if resolved.all():
                break
            # drain the pipe when nothing is left to dispatch so the
            # last round's results are seen without an extra device step
            if accepted_ops.all() and inflight:
                pr, praw = inflight.pop(0)
                harvest(pr, praw)
        while inflight:
            pr, praw = inflight.pop(0)
            harvest(pr, praw)
        if not resolved.all():  # pragma: no cover - defensive
            missing = int(n - resolved.sum())
            raise TimeoutError(f"bulk drive: {missing} ops unresolved")
        rg.rounds += r
        rg.metrics.counter("ops_committed").inc(n)
        return BulkResult(results=results, rounds=r,
                          wall_s=time.perf_counter() - t0,
                          dispatch_round=dispatch_round,
                          resolve_round=resolve_round)


    def drive_queries(self, groups, opcode, a=0, b=0, c=0,
                      consistency: str = "sequential",
                      max_rounds: int = 200) -> np.ndarray:
        """Serve one READ per entry of ``groups`` through the query lane
        (no log append — ops/consensus.query_step) and return results
        aligned with the input.

        ``consistency``: ``"sequential"``/``"causal"``/``"process"`` read
        the leader's applied state; ``"atomic"`` additionally gates each
        slot on the leader LEASE (BOUNDED_LINEARIZABLE — reference
        Consistency.java:157-176) so the read is linearizable without a
        quorum round. Unserved slots (leaderless group, fresh leader,
        applied < commit, cold lease) retry after stepping a settle
        round. Works on BOTH classic and monotone engines: queries never
        append, so the tag gate is irrelevant.

        Throughput shape: each pass evaluates up to S reads per group in
        ONE jitted call over all groups — B reads/group cost ceil(B/S)
        query calls (plus settle rounds only when slots go unserved).
        """
        rg = self._rg
        if getattr(rg, "process_count", 1) > 1:
            raise NotImplementedError(
                "drive_queries is single-host; multihost engines serve "
                "reads through the lockstep query lane (serve_query / "
                "submit_query)")
        from ..ops.apply import QUERY_OPCODES

        g_arr = np.asarray(groups, np.int64).ravel()
        n = g_arr.size
        if n == 0:
            return np.zeros(0, np.int64)
        bc = lambda x: np.broadcast_to(
            np.asarray(x, np.int32).ravel(), (n,)).copy()
        op_a, a_a, b_a, c_a = bc(opcode), bc(a), bc(b), bc(c)
        bad = set(np.unique(op_a).tolist()) - QUERY_OPCODES
        if bad:
            raise ValueError(
                f"opcodes {sorted(bad)} are not read-only; drive them "
                "as commands")
        levels = ("causal", "process", "sequential", "atomic")
        if consistency not in levels:
            raise ValueError(f"consistency {consistency!r}: one of {levels}")

        S = rg.submit_slots
        G = rg.num_groups
        order = np.argsort(g_arr, kind="stable")
        g_s = g_arr[order]
        op_s, a_s, b_s, c_s = (x[order] for x in (op_a, a_a, b_a, c_a))
        firsts = np.ones(n, bool)
        firsts[1:] = g_s[1:] != g_s[:-1]
        starts = np.flatnonzero(firsts)
        counts = np.diff(np.append(starts, n))

        results = np.zeros(n, np.int64)
        done = np.zeros(n, bool)
        want_atomic = consistency == "atomic"
        rounds = 0
        while not done.all():
            if rounds > max_rounds:
                raise TimeoutError(
                    f"bulk queries: {int(n - done.sum())} unserved after "
                    f"{max_rounds} passes")
            # Queries never mutate state, so EVERY pending window can be
            # dispatched back-to-back against the same state and fetched
            # in ONE device_get — through a tunneled accelerator that is
            # one round-trip for the whole burst, not one per window.
            windows = []
            shadow = done.copy()
            while not shadow.all():
                pos, slots = _window_rank(~shadow, starts, counts, S)
                gi = g_s[pos]
                sub = rg._empty_submits()
                sub.opcode[gi, slots] = op_s[pos]
                sub.a[gi, slots] = a_s[pos]
                sub.b[gi, slots] = b_s[pos]
                sub.c[gi, slots] = c_s[pos]
                sub.valid[gi, slots] = True
                atomic = np.zeros((G, S), bool)
                if want_atomic:
                    atomic[gi, slots] = True
                raw = rg._query(rg.state, sub, atomic)
                windows.append((pos, gi, slots, raw))
                shadow[pos] = True
                rounds += 1
            fetched = jax.device_get([raw for *_, raw in windows])
            any_miss = False
            for (pos, gi, slots, _), (res, served) in zip(windows, fetched):
                hit = np.asarray(served)[gi, slots]
                res = np.asarray(res)
                results[pos[hit]] = res[gi[hit], slots[hit]]
                done[pos[hit]] = True
                any_miss |= not hit.all()
            if any_miss and not done.all():
                # only pay a consensus step when a slot went UNSERVED
                # (cold lease / fresh leader / apply lag)
                rg.step_round()
                rounds += 1

        out = np.zeros(n, np.int64)
        out[order] = results
        return out

    def recover(self, settle_rounds: int = 30,
                max_rounds: int = 500) -> None:
        """Re-arm the deep plane after an abandoned drive (TimeoutError).

        Call AFTER healing faults. Two hazards bracket the tag cursor:

        - too LOW: an entry replicated to a minority lineage can still
          commit (its leader re-wins) — reusing its tag would alias a
          fresh op's accumulator slot (mis-correlated results);
        - too HIGH: an isolated leader may have ACCEPTED a burst into
          its ring (acceptance is lane-local) that a post-heal election
          ERASES by rewind — the abandon-time conservative resync
          (max of host/device views) then leaves the cursor pointing
          past a ring that reverted, and every later drive is
          gate-rejected forever (found by the round-5 abandoned-flush
          test).

        So: settle, then wait until every group's lanes CONVERGE (same
        last/applied index, a leader present — no surviving divergent
        lineage), then trust the device outright (plain assignment).
        On dynamic-membership engines removed lanes never converge, so
        the check is skipped and the conservative max-resync kept — a
        churned group that hit the too-high hazard needs its membership
        restored before recovery (documented limitation; the deep plane
        runs static membership in-tree).
        """
        rg = self._rg
        for _ in range(settle_rounds):
            rg.step_round()
        if rg.config.dynamic_membership:
            self._resync_stream_count()
            return
        # Convergence polls are lockstep-agreed (step_round is a
        # collective program on multihost engines — a process-local
        # break would deadlock peers) and spaced POLL_EVERY rounds apart
        # so a tunneled accelerator pays one blocking fetch per few
        # rounds, not per round.
        POLL_EVERY = 4
        for attempt in range(max_rounds):
            last, applied, role = (np.asarray(x) for x in rg._fetch_acc(
                (rg.state.last_index, rg.state.applied_index,
                 rg.state.role)))
            mine = bool((last.min(1) == last.max(1)).all()
                        and (applied.min(1) == applied.max(1)).all()
                        and ((role == 2).sum(1) >= 1).all())
            if rg._agree(mine):
                break
            for _ in range(POLL_EVERY):
                rg.step_round()
        else:
            raise TimeoutError(
                "recover: cluster did not converge — heal every fault "
                "before calling recover()")
        rg._stream_count = stream_count_from_state(rg.state,
                                                   fetch=rg._fetch_acc)

    def _resync_stream_count(self) -> None:
        """Set each group's stream cursor to the max live-ring tag on the
        most-advanced lane — every tag at or below it was consumed by the
        device, so the next drive's dense stream starts just past it.
        Exact in the deep plane's fault-free world; an error path only
        (one [G,P,L] fetch)."""
        rg = self._rg
        rg._stream_count = np.maximum(
            rg._stream_count,
            stream_count_from_state(rg.state, fetch=rg._fetch_acc))

    def _drive_deep(self, g_arr, op_a, a_a, b_a, c_a,
                    max_rounds: int, t0: float,
                    deliver_schedule=None) -> BulkResult:
        """Zero-sync pipelined drive for monotone-tag engines.

        The classic drive pays one BLOCKING ``accepted`` fetch per round
        to keep dispatch FIFO-safe — through a tunneled accelerator that
        round-trip dominates wall time (round-4 TPU measurement: ~90% of
        the host scenario's budget). With device-enforced FIFO + dedup
        (``Config.monotone_tag_accept``) blind dispatch is safe, so:

        - phase 1 dispatches every op exactly once, S per group per
          round, back-to-back with NO device fetch (async dispatch keeps
          the device ~W rounds deep in useful work), then fetches ALL
          round outputs in one ``jax.device_get`` — every transfer is in
          flight concurrently, amortizing the tunnel latency to ~one
          round-trip total;
        - phase 2 (rare: lease-refusal at a cold leader, backpressure)
          re-dispatches each group's unresolved SUFFIX — resolution is a
          per-group prefix by construction (the gate makes acceptance a
          prefix, applies report in log order), and re-sending an
          already-accepted op is rejected on device, never re-applied.

        Liveness matches the classic bulk plane (fault-free delivery);
        safety is the gate's and holds under any fault.
        """
        rg = self._rg
        S = rg.submit_slots
        G = rg.num_groups
        n = g_arr.size
        multi = getattr(rg, "process_count", 1) > 1

        order = np.argsort(g_arr, kind="stable")
        g_s = g_arr[order]
        op_s, a_s, b_s, c_s = (x[order] for x in (op_a, a_a, b_a, c_a))
        firsts = np.ones(n, bool)
        firsts[1:] = g_s[1:] != g_s[:-1]
        starts = np.flatnonzero(firsts)
        counts = np.diff(np.append(starts, n))
        seg_groups = g_s[starts]
        rank = np.arange(n) - np.repeat(starts, counts)
        seg_base = rg._stream_count[seg_groups]            # [nseg]
        # tag-space check on an AGREED value: a per-process-local raise
        # before the collectives below would leave peer processes hung
        # in their allgather — every process must see the same verdict
        tag_end = rg._global_max_int(
            int((seg_base + counts).max(initial=0)) if n else 0)
        if tag_end > np.iinfo(np.int32).max:
            raise OverflowError(
                "per-group stream exceeds int32 tag space")

        # all bookkeeping lives in SORTED space; unsorted at return.
        # Every op's dispatch round is fixed by the blind phase-1 plan.
        resolved = np.zeros(n, bool)
        results = np.zeros(n, np.int64)
        dispatch_round = (rank // S).astype(np.int64)
        resolve_round = np.zeros(n, np.int64)

        # On-device result accumulators, fetched ONCE per drive: [G, B]
        # keyed by stream rank (ops/consensus.deep_step). B pads to a
        # power of two so repeated drives reuse the compiled program.
        # B is agreed ACROSS processes (multihost engines launch one
        # collective program, so every process must size — and compile —
        # identical buffers; a process with fewer local ops dispatches
        # empty windows for the surplus rounds).
        B = rg._global_max_int(int(counts.max(initial=0)))
        if B == 0:   # agreed: every process is idle this drive
            z = np.zeros(0, np.int64)
            return BulkResult(results=z, rounds=0, wall_s=0.0,
                              dispatch_round=z, resolve_round=z)
        Bpad = 1 << max(0, B - 1).bit_length()
        # accumulators are [G, max-burst]: a skewed drive (one group with
        # a huge burst on a large-G engine) would allocate G*Bpad
        # regardless of total ops — refuse with advice instead of
        # swallowing device memory
        G_total = getattr(rg, "global_groups", G)
        if G_total * Bpad > 64_000_000:
            raise ValueError(
                f"deep drive accumulators would be [{G_total}, {Bpad}] "
                f"({G_total * Bpad / 1e6:.0f}M slots) for {n} ops — burst "
                "sizes are too skewed; split the drive into bursts of "
                "similar per-group size")
        resbuf = rg._stage_acc(np.zeros((G, Bpad), np.int32))
        valbuf = rg._stage_acc(np.zeros((G, Bpad), bool))
        rndbuf = rg._stage_acc(np.full((G, Bpad), 2**30, np.int32))
        evflag = rg._stage_acc(np.zeros(G, bool))  # per-group: no
        #                                            cross-shard reduce
        base_dev = rg._stage_acc(rg._stream_count.astype(np.int32))
        _deep = rg._deep_fn()

        # burst-uniform payload leaves travel as SCALARS (zero H2D bytes);
        # per-op payloads fall back to full [G,S] arrays. Multihost
        # engines always stage full arrays: _stage_submits assembles a
        # global sharded array from each process's local block, and a
        # scalar has no local block (payload uniformity is also a
        # per-process fact the other processes can't see).
        def _const(x):
            return np.int32(x[0]) if (n and (x == x[0]).all()) else None

        consts = ((None,) * 4 if multi
                  else tuple(map(_const, (op_s, a_s, b_s, c_s))))
        vals = (op_s, a_s, b_s, c_s)
        # telemetry stash: per-round [G] delta blocks kept ON DEVICE and
        # fetched with the accumulator harvest — the blind phase stays
        # one transfer per drive even with the flight recorder on
        tel_stash: list[Any] = []
        rounds0 = rg.rounds
        tel_ingested = 0
        # deliver_schedule(r) -> per-round delivery mask (already staged
        # for the engine's topology): the fault-injection seam — the
        # deep plane's liveness needs faults that HEAL, so a verdict/
        # nemesis harness schedules e.g. a partition for rounds < F and
        # full delivery after (testing/verdict.run_deep_verdict).
        deliver = rg.deliver
        ev_stash: list[Any] = []
        r = 0

        def payload_leaves(pos, slots):
            return tuple(
                c if c is not None else _scatter(G, S, g_s[pos], slots,
                                                 v[pos])
                for c, v in zip(consts, vals))

        def dispatch(tagl, vnp, leaves) -> None:
            nonlocal r, resbuf, valbuf, rndbuf, evflag
            sub = rg._stage_submits(
                Submits(opcode=leaves[0], a=leaves[1], b=leaves[2],
                        c=leaves[3], tag=tagl, valid=vnp))
            dl = deliver if deliver_schedule is None else deliver_schedule(r)
            rg._key, key = jax.random.split(rg._key)
            (rg.state, resbuf, valbuf, rndbuf, evflag, out) = _deep(
                rg.state, resbuf, valbuf, rndbuf, evflag, base_dev,
                np.int32(r), sub, dl, key)
            # keep only the ev (+ telemetry) leaves alive — retaining the
            # whole StepOutputs would pin every round's out arrays on device
            ev_stash.append((out.ev_seq, out.ev_code, out.ev_target,
                             out.ev_arg, out.ev_valid))
            if rg.telemetry is not None and out.telemetry is not None:
                tel_stash.append(out.telemetry)
            r += 1

        _idle = (np.zeros((G, 1), np.int32), np.zeros((G, S), bool),
                 (np.zeros((G, S), np.int32),) * 4 if multi
                 else (np.int32(0),) * 4)

        def harvest() -> None:
            """ONE fetch of the [G,B] accumulators (+ telemetry, + the
            rare event leaves)."""
            nonlocal evflag, tel_ingested
            res_np, val_np, rnd_np, ev, tels = rg._fetch_acc(
                (resbuf, valbuf, rndbuf, evflag, tel_stash))
            for tel in tels:
                if np.asarray(tel.elections_started).ndim == 2:
                    w = int(np.asarray(tel.elections_started).shape[0])
                    rg.telemetry.ingest_stacked(
                        tel, rounds0 + tel_ingested)
                    tel_ingested += w
                else:
                    rg.telemetry.ingest(tel, rounds0 + tel_ingested)
                    tel_ingested += 1
            tel_stash.clear()
            colm = np.arange(Bpad)[None, :] < counts[:, None]
            resolved[:] = val_np[seg_groups][colm]
            results[:] = res_np[seg_groups][colm]
            resolve_round[:] = rnd_np[seg_groups][colm]
            if ev.any():
                # rare path (session-event ops in the burst): fetch the
                # stashed per-round event leaves and ingest with seq
                # dedup. Local-only decision — the fetch reads only this
                # process's shards, no collective program is launched.
                # Scan-mode stashes are stacked [W, ...]; unroll them.
                for st in ev_stash:
                    leaves = rg._fetch_acc(st)
                    if leaves[0].ndim == 3:
                        for w in range(leaves[0].shape[0]):
                            rg._ingest_events(
                                _EventView(*(x[w] for x in leaves)))
                    else:
                        rg._ingest_events(_EventView(*leaves))
                evflag = rg._stage_acc(np.zeros(G, bool))
            ev_stash.clear()

        # phase 1: blind pipelined dispatch — NO device fetch at all. The
        # device runs ~windows rounds deep while the host only stages
        # tag bases [G,1] and valid masks [G,S]. Scan mode goes further:
        # the whole phase (windows + settle) is ONE stacked payload and
        # ONE compiled lax.scan dispatch.
        windows = int(np.ceil(B / S))
        tagl = np.zeros((G, 1), np.int32)
        if self._scan and deliver_schedule is not None:
            raise NotImplementedError(
                "deep_scan compiles the whole blind phase with ONE "
                "deliver mask; per-round deliver_schedule fault "
                "injection needs the dispatch mode (BulkDriver without "
                "deep_scan)")
        if self._scan:
            W_total = windows + 3      # + replicate/commit/report settle
            tagl_w = np.zeros((W_total, G, 1), np.int32)
            valid_w = np.zeros((W_total, G, S), bool)

            def _payload_w(c):
                arr = np.zeros((W_total, G, S), np.int32)
                if c is not None:
                    arr[:windows] = c     # burst-uniform: one fill
                return arr

            op_w, a_w, b_w, c_w = (_payload_w(c) for c in consts)
            win_of = rank // S
            slot_of = rank - win_of * S
            for w in range(windows):
                tagl_w[w, seg_groups, 0] = (seg_base + w * S + 1) \
                    .astype(np.int32)
                valid_w[w][seg_groups] = (w * S + np.arange(S))[None, :] \
                    < counts[:, None]
            if consts[0] is None:
                op_w[win_of, g_s, slot_of] = op_s
            if consts[1] is None:
                a_w[win_of, g_s, slot_of] = a_s
            if consts[2] is None:
                b_w[win_of, g_s, slot_of] = b_s
            if consts[3] is None:
                c_w[win_of, g_s, slot_of] = c_s
            _scan = _deep_scan_program(
                rg.config, onehot=rg.mesh is not None,
                donate=jax.default_backend() != "cpu")
            rg._key, key = jax.random.split(rg._key)
            (rg.state, resbuf, valbuf, rndbuf, evflag, evs, tels) = _scan(
                rg.state, resbuf, valbuf, rndbuf, evflag, base_dev,
                Submits(opcode=op_w, a=a_w, b=b_w, c=c_w, tag=tagl_w,
                        valid=valid_w), deliver, key)
            r = W_total
            ev_stash.append(evs)   # stacked [W, ...] leaves
            if rg.telemetry is not None and tels is not None:
                tel_stash.append(tels)  # stacked [W, G] leaves
        else:
            for w in range(windows):
                in_w = (rank >= w * S) & (rank < (w + 1) * S)
                pos = np.flatnonzero(in_w)
                tagl[seg_groups, 0] = (seg_base + w * S + 1) \
                    .astype(np.int32)
                vnp = np.zeros((G, S), bool)
                vnp[seg_groups] = (w * S + np.arange(S))[None, :] \
                    < counts[:, None]
                dispatch(tagl.copy(), vnp,
                         payload_leaves(pos, rank[pos] - w * S))
            for _ in range(3):  # settle: replicate + commit + report lag
                dispatch(*_idle[:2], _idle[2])
        harvest()

        # phase 2: straggler suffixes (lease-cold leaders, backpressure).
        # Resolution is a per-group PREFIX (the gate makes acceptance a
        # prefix and applies report in log order), so the cursor is the
        # per-group resolved count; re-sending an already-accepted op is
        # rejected on device, never re-applied. The stop decision is
        # lockstep-agreed: a process whose local ops are done keeps
        # dispatching EMPTY windows until every process is done (each
        # iteration launches 3 collective rounds + a fetch on multihost).
        while not rg._agree(bool(resolved.all())):
            if r > max_rounds:
                missing = int(n - resolved.sum())
                # abandoning mid-stream: tags up to the device ring max
                # were CONSUMED (some abandoned ops may still commit —
                # at-most-once, like a classic-path timeout). Resync the
                # host cursor from the device so later drives start past
                # every consumed tag instead of being gate-rejected
                # forever (round-4 review finding).
                self._resync_stream_count()
                raise TimeoutError(
                    f"bulk drive (deep): {missing} ops unresolved after "
                    f"{max_rounds} rounds (fault-free liveness assumption"
                    f" violated? use the queue-managed path under faults); "
                    f"stream cursors resynced from the device")
            # reduceat on bool would logical-or, not count — cast first
            fu = np.add.reduceat(resolved.astype(np.int64), starts)
            want = np.minimum(counts - fu, S)
            segs = np.flatnonzero(want > 0)
            reps = want[segs]
            offs = np.arange(reps.sum()) \
                - np.repeat(np.cumsum(reps) - reps, reps)
            pos = np.repeat((starts + fu)[segs], reps) + offs
            tagl[:, 0] = 0
            tagl[seg_groups[segs], 0] = (seg_base[segs] + fu[segs] + 1) \
                .astype(np.int32)
            vnp = np.zeros((G, S), bool)
            vnp[seg_groups] = np.arange(S)[None, :] < want[:, None]
            dispatch(tagl.copy(), vnp, payload_leaves(pos, offs))
            dispatch(*_idle[:2], _idle[2])
            dispatch(*_idle[:2], _idle[2])
            harvest()

        if n:
            rg._stream_count[seg_groups] += counts
        rg.rounds += r
        rg.metrics.counter("ops_committed").inc(n)
        out_res = np.zeros(n, np.int64)
        out_dr = np.zeros(n, np.int64)
        out_rr = np.zeros(n, np.int64)
        out_res[order] = results
        out_dr[order] = dispatch_round
        out_rr[order] = resolve_round
        return BulkResult(results=out_res, rounds=r,
                          wall_s=time.perf_counter() - t0,
                          dispatch_round=out_dr, resolve_round=out_rr)


class _EventView:
    """Adapter: numpy event leaves → the ``ev_*`` attrs _ingest_events reads."""

    __slots__ = ("ev_seq", "ev_code", "ev_target", "ev_arg", "ev_valid")

    def __init__(self, seq, code, target, arg, valid) -> None:
        self.ev_seq, self.ev_code, self.ev_target = seq, code, target
        self.ev_arg, self.ev_valid = arg, valid


def drive_batch(rg, groups, opcode, a=0, b=0, c=0,
                max_rounds: int = 10_000) -> BulkResult:
    """Module-level convenience: ``BulkDriver(rg).drive(...)``."""
    return BulkDriver(rg).drive(groups, opcode, a, b, c,
                                max_rounds=max_rounds)
