"""Host runtime around the batched consensus step.

The reference hosts one state machine per server process and drives it with
asyncio-style RPC (``CopycatServer``, consumed per SURVEY.md §2.3). Here the
host owns G logical Raft groups living on device and drives them round by
round: queue client ops, call the jitted step, harvest per-op results by
correlation tag.

This is the device executor the Resource/StateMachine SPI targets
(SURVEY.md §7.1: "the TPU executor selectable at replica build time");
the session protocol, exactly-once caching and event push stay host-side
in ``copycat_tpu.server`` — the device provides ordered, replicated,
deterministic apply at batch scale.
"""

from __future__ import annotations

from collections import deque
from functools import lru_cache, partial
from typing import Any

import jax
import numpy as np

from ..ops.apply import FAIL, OP_CFG_ADD, OP_CFG_REMOVE, QUERY_OPCODES
from ..ops.consensus import (
    Config,
    RaftState,
    StepOutputs,
    Submits,
    full_delivery,
    init_state,
    install_snapshots,
    query_step,
    step,
)


@lru_cache(maxsize=None)
def _jitted_programs(config: Config):
    """(step, query, install) jit wrappers shared across all RaftGroups
    instances with the same static Config (Config is a hashable NamedTuple,
    so it keys the cache; shapes are handled inside each jit wrapper)."""
    return (jax.jit(partial(step, config=config)),
            jax.jit(partial(query_step, config=config)),
            jax.jit(partial(install_snapshots, config=config)))


@lru_cache(maxsize=None)
def _fused_rounds_program(config: Config, n: int):
    """``n`` consensus rounds in ONE compiled program: round 0 carries
    the caller's submits, rounds 1..n-1 run empty (the commit pipeline —
    replicate, commit, report — advancing). Returns the new state, round
    0's outputs, and the stacked outputs of the remaining rounds. One
    dispatch + one fetch instead of ``n``: through a tunneled
    accelerator that is the difference between ~n round-trips and one
    per SPI window pump cycle (the round-5 spi floor)."""
    import jax.numpy as jnp

    def fused(state, submits, deliver, key):
        keys = jax.random.split(key, n)
        state, out0 = step(state, submits, deliver, keys[0], config=config)
        empty = jax.tree.map(jnp.zeros_like, submits)

        def body(st, kk):
            st, out = step(st, empty, deliver, kk, config=config)
            return st, out

        state, outs = jax.lax.scan(body, state, keys[1:])
        return state, out0, outs

    return jax.jit(fused)


def _group_slot_pack(g: np.ndarray
                     ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Stable per-group slot assignment for ``[N]`` rows: returns
    ``(order, gs, slots)`` such that rows taken in ``order`` land at
    ``(gs[i], slots[i])`` of a ``[G, S]`` buffer, with row order within
    a group preserved (the per-group FIFO witness both the vector
    submit lane and the vector read lane rely on)."""
    order = np.argsort(g, kind="stable")
    gs = g[order]
    n = gs.size
    first = np.ones(n, bool)
    first[1:] = gs[1:] != gs[:-1]
    starts = np.flatnonzero(first)
    cnt = np.diff(np.append(starts, n))
    slots = np.arange(n) - np.repeat(starts, cnt)
    return order, gs, slots


class RaftGroups:
    """G Raft groups × P peers, stepped as one compiled program."""

    MAX_EVENTS_PER_GROUP = 4096

    def __init__(
        self,
        num_groups: int,
        num_peers: int = 3,
        log_slots: int = 64,
        submit_slots: int = 4,
        config: Config | None = None,
        seed: int = 0,
        mesh: Any | None = None,
        voters: int | None = None,
        *,
        _build_state: bool = True,
    ) -> None:
        self.num_groups = num_groups
        self.num_peers = num_peers
        self.log_slots = log_slots
        self.submit_slots = submit_slots
        self.config = config or Config()
        # Environment opt-in for the device-plane flight recorder
        # (COPYCAT_TELEMETRY=1 / COPYCAT_INVARIANTS=observe|strict):
        # flips the static knob BEFORE any program is compiled so CI can
        # run the whole nemesis suite under strict invariants without
        # touching each test's Config. Telemetry never changes the
        # state evolution (it is pure output), so this is safe to apply
        # to any engine.
        from .telemetry import telemetry_env_enabled
        if not self.config.telemetry and telemetry_env_enabled():
            self.config = self.config._replace(telemetry=True)
        self.mesh = mesh
        members = None
        if voters is not None:
            if not 0 < voters <= num_peers:
                raise ValueError(f"voters={voters} outside 1..{num_peers}")
            if voters < num_peers and not self.config.dynamic_membership:
                raise ValueError(
                    "voters < num_peers needs Config(dynamic_membership"
                    "=True) — the static step tallies all P lanes")
            if voters < num_peers:
                members = np.arange(num_peers) < voters

        key = jax.random.PRNGKey(seed)
        self._key, init_key = jax.random.split(key)
        if _build_state:
            self.state: RaftState = init_state(num_groups, num_peers,
                                               log_slots, init_key,
                                               self.config, members=members)
            self.deliver = full_delivery(num_groups, num_peers)
            if mesh is not None:
                from ..parallel import shard_state, shard_step_inputs
                self.state = shard_state(self.state, mesh)
                _, self.deliver = shard_step_inputs(
                    self._empty_submits(), self.deliver, mesh)

            # Config-keyed jit cache: many RaftGroups instances with the
            # same Config (e.g. one device engine per server in a
            # multi-server test) share ONE compiled program instead of
            # recompiling per instance.
            self._step, self._query, self._install = _jitted_programs(
                self.config)
        else:
            # A subclass (parallel/multihost.py) supplies globally sharded
            # state/deliver and sharding-pinned jit wrappers itself —
            # building throwaway local versions here wasted a full state
            # allocation at startup (ADVICE r3 #2).
            self.state = None
            self.deliver = None
            self._step = self._query = self._install = None
        self._queues: dict[int, deque] = {}
        self._query_queues: dict[int, deque] = {}
        self._query_atomic: set[int] = set()  # tags needing the lease gate
        self._next_tag = 1
        self._inflight: dict[int, tuple[int, int]] = {}  # tag -> (group, round)
        # exactly-once retry (queue-managed ops only): an op accepted into
        # a leader log can still be LOST — a partitioned leader's
        # unreplicated tail is overwritten by its successor. The host
        # re-submits only on PROOF of loss: once an entry with term
        # T > term_e applies at index j ≤ idx, the pending placement
        # (idx, term_e) can never be in the committed log (log terms are
        # monotone, so its log had term ≤ term_e < T at j — prefix
        # mismatch), hence re-submitting cannot double-apply. The
        # device-path analogue of the reference's session-sequenced
        # client resubmit (Copycat client runtime, SURVEY §2.3).
        self._inflight_ops: dict[int, tuple[int, int, int, int]] = {}
        # group -> {index -> (tag, append term)} — current placements only
        self._placements: dict[int, dict[int, tuple[int, int]]] = {}
        self._tag_index: dict[int, tuple[int, int]] = {}  # tag -> (group, idx)
        # highest post-round leader term observed per group: while a
        # placement's append term is older, that op's fate is uncertain
        # (its leader changed) and the group's queue is HELD — new ops
        # must not land in a log line that may lack an earlier op, or
        # per-group FIFO completion (the reference's session program
        # order) would break. The held set and the per-group min pending
        # append term (a lower bound — left stale on removals, refreshed
        # during loss scans) are maintained incrementally so the steady
        # state (no leader changes) costs no per-round Python scans.
        self._leader_term = np.zeros(num_groups, np.int64)
        self._held: set[int] = set()
        self._pend_min: dict[int, int] = {}
        self.results: dict[int, int] = {}    # tag -> result
        self.rounds = 0
        # first-class ops/sec + latency metrics (SURVEY.md §5.5)
        from ..utils.metrics import MetricsRegistry
        self.metrics = MetricsRegistry()
        # device-plane flight recorder: hub folds the step's telemetry
        # deltas into the device.* metric family, the flight ring, and
        # the online invariant monitor (models/telemetry.py)
        if self.config.telemetry:
            from .telemetry import DeviceTelemetryHub
            self.telemetry: Any = DeviceTelemetryHub(num_groups)
        else:
            self.telemetry = None
        self.clock = 0                       # mirrors the device logical clock
        # session events per group: list of (seq, code, target, arg);
        # deduped by absolute seq (ring re-delivers across leader changes)
        self.events: dict[int, list[tuple[int, int, int, int]]] = {}
        self._ev_seen: dict[int, int] = {}   # group -> highest seq consumed
        self._sessions: Any = None           # lazy DeviceSessionRegistry
        # monotone-tag engines: per-group count of stream ops committed so
        # far — the next drive's dense tags continue from here (the device
        # gate tracks the same value as the max live-ring tag)
        if self.config.monotone_tag_accept:
            self._stream_count = np.zeros(num_groups, np.int64)
        # direct-staged submit buffer (submit_batch fast lane): rows
        # scattered straight into the next round's Submits, bypassing
        # the per-group deque fan-out + re-drain (two Python loops that
        # dominated the SPI window's loaded round at 1k ops)
        self._staged_sub: Submits | None = None

    @property
    def sessions(self):
        """Device-path session registry (keep-alives + deterministic expiry
        fan-out through the log — see ``models/sessions.py``)."""
        if self._sessions is None:
            from .sessions import DeviceSessionRegistry
            self._sessions = DeviceSessionRegistry(self)
        return self._sessions

    # -- op submission ---------------------------------------------------

    def _empty_submits(self) -> Submits:
        G, S = self.num_groups, self.submit_slots
        return Submits(opcode=np.zeros((G, S), np.int32),
                       a=np.zeros((G, S), np.int32),
                       b=np.zeros((G, S), np.int32),
                       c=np.zeros((G, S), np.int32),
                       tag=np.zeros((G, S), np.int32),
                       valid=np.zeros((G, S), bool))

    def _refuse_monotone(self) -> None:
        """Monotone-tag engines (``Config.monotone_tag_accept``) accept only
        the bulk plane's dense per-group tag streams — a queue-managed
        submit (whose retries re-send OLD tags) would be silently rejected
        by the device gate forever, so refuse it loudly up front. Queries
        never append and stay allowed."""
        if self.config.monotone_tag_accept:
            raise NotImplementedError(
                "queue-managed submits are incompatible with "
                "Config(monotone_tag_accept=True) engines; drive them "
                "through models.bulk.BulkDriver")

    def submit(self, group: int, opcode: int, a: int = 0, b: int = 0,
               c: int = 0) -> int:
        """Queue one op; returns a correlation tag resolved in ``results``."""
        if opcode in (OP_CFG_ADD, OP_CFG_REMOVE):
            # raw config submits get the same validation as
            # add_peer/remove_peer — otherwise an out-of-range lane or a
            # static-membership engine would commit a no-op entry that
            # resolves as a silent success
            if not self.config.dynamic_membership:
                raise ValueError("membership changes need "
                                 "Config(dynamic_membership=True)")
            if not 0 <= a < self.num_peers:
                raise ValueError(
                    f"peer {a} outside 0..{self.num_peers - 1}")
        self._refuse_monotone()
        tag = self._next_tag
        self._next_tag += 1
        self._queues.setdefault(group, deque()).append((opcode, a, b, c, tag))
        self._inflight[tag] = (group, self.rounds)
        self._inflight_ops[tag] = (opcode, a, b, c)
        self.metrics.counter("ops_submitted").inc()
        return tag

    def submit_query(self, group: int, opcode: int, a: int = 0, b: int = 0,
                     c: int = 0, consistency: str = "sequential") -> int:
        """Queue a read-only op on the fast query lane (no log append).

        ``consistency="sequential"`` serves from the leader's applied
        state (the reference's sub-ATOMIC query routing,
        ``Consistency.java``); ``"atomic"`` additionally requires the
        leader LEASE (quorum-acked latest round) — BOUNDED_LINEARIZABLE
        reads without a log entry (``Consistency.java:157-176``). Either
        escalates to the command path automatically when unservable.
        Resolves in ``results`` like :meth:`submit`."""
        if opcode not in QUERY_OPCODES:
            # query_step discards state: a write here would be silently
            # dropped while acking success (reference rejects them too)
            raise ValueError(
                f"opcode {opcode} is not read-only; submit it as a command")
        if consistency not in ("sequential", "atomic"):
            raise ValueError(f"unknown query consistency {consistency!r}")
        tag = self._next_tag
        self._next_tag += 1
        self._query_queues.setdefault(group, deque()).append(
            (opcode, a, b, c, tag))
        if consistency == "atomic":
            self._query_atomic.add(tag)
        self._inflight[tag] = (group, self.rounds)
        self.metrics.counter("queries_submitted").inc()
        return tag

    def _drop_placement(self, g: int, idx: int) -> None:
        """Remove one placement; prune empty per-group state and
        re-evaluate the group's hold."""
        pend = self._placements.get(g)
        if pend is None:
            return
        pend.pop(idx, None)
        if not pend:
            del self._placements[g]
            self._pend_min.pop(g, None)
            self._held.discard(g)
        elif g in self._held:
            lt = self._leader_term[g]
            if all(te >= lt for _, te in pend.values()):
                self._held.discard(g)

    def _drain_into(self, queues: dict[int, deque], sub: Submits,
                    skip: set[int] | None = None) -> list[tuple[int, int]]:
        """Pop up to ``submit_slots`` queued ops per group into ``sub``;
        returns the (group, slot) pairs filled. Values are staged into
        Python lists and written with ONE fancy-indexed assignment per
        array — six scalar numpy ``__setitem__`` calls per op dominated
        the host loop before."""
        placed: list[tuple[int, int]] = []
        ops: list[int] = []
        avs: list[int] = []
        bvs: list[int] = []
        cvs: list[int] = []
        tgs: list[int] = []
        slots = self.submit_slots
        for g, q in list(queues.items()):
            if skip and g in skip:
                continue
            s = 0
            while q and s < slots:
                opcode, a, b, c, tag = q.popleft()
                ops.append(opcode)
                avs.append(a)
                bvs.append(b)
                cvs.append(c)
                tgs.append(tag)
                placed.append((g, s))
                s += 1
            if not q:
                del queues[g]
        if placed:
            rows = np.fromiter((p[0] for p in placed), np.int64,
                               len(placed))
            cols = np.fromiter((p[1] for p in placed), np.int64,
                               len(placed))
            sub.opcode[rows, cols] = ops
            sub.a[rows, cols] = avs
            sub.b[rows, cols] = bvs
            sub.c[rows, cols] = cvs
            sub.tag[rows, cols] = tgs
            sub.valid[rows, cols] = True
        return placed

    def _build_submits(self) -> Submits:
        if self._staged_sub is not None:
            # consume the direct-staged buffer. Queue entries that
            # appeared AFTER staging (post-step requeues, stray
            # submit()s) wait one round — per-group FIFO holds because
            # staging refuses while queues are non-empty, so anything
            # queued is strictly newer than everything staged.
            sub = self._staged_sub
            self._staged_sub = None
            return sub
        sub = self._empty_submits()
        if self._queues:
            self._drain_into(self._queues, sub,
                             skip=self._held or None)
        return sub

    def _stage_direct(self, g: np.ndarray, op, a, b, c,
                      tags: np.ndarray) -> bool:
        """Scatter rows straight into the next round's submit buffer
        (pure numpy, no per-op Python). Refused (``False`` — caller
        takes the deque path) whenever ordering could be observable:
        queued ops exist (FIFO vs them), holds are active, the engine is
        monotone (deep plane owns its streams), or a group would
        overflow its submit window."""
        if (self._queues or self._held or self._staged_sub is not None
                or self.config.monotone_tag_accept):
            return False
        counts = np.bincount(g, minlength=self.num_groups)
        if counts.max(initial=0) > self.submit_slots:
            return False
        order, gs, slots = _group_slot_pack(g)
        sub = self._empty_submits()
        sub.opcode[gs, slots] = op[order]
        sub.a[gs, slots] = a[order]
        sub.b[gs, slots] = b[order]
        sub.c[gs, slots] = c[order]
        sub.tag[gs, slots] = tags[order]
        sub.valid[gs, slots] = True
        self._staged_sub = sub
        return True

    # -- stepping ----------------------------------------------------------

    # Hooks the multi-host driver overrides (parallel/multihost.py): the
    # base engine stages host numpy straight onto the device and fetches
    # whole output arrays; a multi-process driver assembles GLOBAL arrays
    # from each process's local block and fetches only addressable shards.
    # _agree/_any_across are the lockstep primitives: identity on one
    # host, allgathered across processes — every driver loop that stops
    # or branches around a collective program decides through them, so
    # the multi-host subclass needs no copied control flow.

    def _agree(self, mine: bool) -> bool:
        """True when every process's local condition holds (identity on
        a single host)."""
        return mine

    def _any_across(self, mine: bool) -> bool:
        """True when any process's local condition holds (identity on a
        single host)."""
        return mine

    def _stage_submits(self, submits: Submits) -> Submits:
        return submits

    def _stage_deliver(self, deliver: Any) -> Any:
        return deliver

    def _fetch_outputs(self, raw: StepOutputs) -> StepOutputs:
        # ONE overlapped device->host transfer for all output arrays: the
        # lazy per-array np.asarray calls in the harvest each paid a full
        # transfer round-trip (67 ms/array through a tunneled device —
        # it dominated the host loop at 10k groups).
        for leaf in jax.tree.leaves(raw):
            leaf.copy_to_host_async()
        return jax.tree.map(np.asarray, raw)

    def _stale_any(self, raw: StepOutputs, out: StepOutputs) -> bool:
        return bool(out.stale.any())

    def _run_query(self, sub: Submits, atomic) -> tuple[Any, Any]:
        results, served = self._query(self.state, sub, atomic)
        return np.asarray(results), np.asarray(served)

    # Deep-plane hooks (models/bulk.py _drive_deep): accumulator staging,
    # fetch, and the jitted deep program. The multihost subclass overrides
    # them to assemble/fetch global group-sharded arrays and to pin output
    # shardings, which is what lifts the deep pipelined drive to
    # multi-process engines (VERDICT r4 directive 2).

    def _global_max_int(self, v: int) -> int:
        """Max of ``v`` across processes (identity on one host) — sizes
        the deep drive's shared accumulator width so every process
        compiles/launches the same program."""
        return v

    def _stage_acc(self, arr: np.ndarray) -> Any:
        """Host numpy -> device array for a deep-drive accumulator whose
        leading axis is groups. On a single-host mesh the group axis is
        sharded like the state (placement-only, so the deep_step scatter
        stays shard-local — parallel/mesh.py rule)."""
        import jax.numpy as jnp
        x = jnp.asarray(arr)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            g_ax = "groups" if "groups" in self.mesh.axis_names else None
            spec = P(g_ax, *([None] * (arr.ndim - 1)))
            x = jax.device_put(x, NamedSharding(self.mesh, spec))
        return x

    def _fetch_acc(self, arrays: Any) -> Any:
        """Fetch a pytree of group-leading device arrays to host numpy
        (this process's local block on multihost)."""
        return jax.device_get(arrays)

    def _deep_fn(self) -> Any:
        """The jitted ``deep_step`` used by the deep drive. One-hot
        accumulator formulation on a mesh (shard-local by construction);
        donation on accelerators only (unimplemented on CPU)."""
        from .bulk import _deep_program
        return _deep_program(self.config, onehot=self.mesh is not None,
                             donate=jax.default_backend() != "cpu")

    def step_round(self, submits: Submits | None = None,
                   deliver: Any | None = None) -> StepOutputs:
        """Advance every group one round; harvests results into ``results``."""
        explicit = submits is not None
        if submits is None:
            submits = self._build_submits()
        self._key, key = jax.random.split(self._key)
        dl = self.deliver if deliver is None else self._stage_deliver(deliver)
        with self.metrics.timer("step_wall_ms"):
            self.state, raw = self._step(
                self.state, self._stage_submits(submits), dl, key)
            raw = jax.block_until_ready(raw)  # time compute, not dispatch
        out = self._fetch_outputs(raw)
        self.rounds += 1
        self.metrics.counter("rounds").inc()
        if not explicit:
            self._requeue_rejected(submits, out)
        self._harvest(out)
        # Placements are recorded AFTER the harvest: an op that committed
        # in the round it was accepted (the steady state) never enters
        # the retry bookkeeping at all — _record_assigned skips tags
        # _harvest already resolved. Same-round loss is impossible (a
        # loss proof needs a committed entry with a HIGHER term at or
        # before the op's index, and terms can't rise past the accepting
        # leader's within its own round).
        if not explicit:
            self._record_assigned(submits, out)
        if self._any_across(bool(self._query_queues)):
            self._serve_queries()
        # Followers lagging beyond the ring window can't be served by
        # AppendEntries: install a snapshot of the leader's lane (log ring +
        # applied resource state) so they reconverge.
        if self._stale_any(raw, out):
            self.state = self._install(self.state, raw.stale, raw.leader)
        if self._sessions is not None:
            self._sessions.tick()
        return out

    def step_rounds(self, n: int) -> None:
        """Advance ``n`` rounds with ONE device dispatch + ONE fetch.

        Semantically equivalent to ``n`` ``step_round()`` calls whose
        rounds 1..n-1 found empty submit queues: round 0 drains the
        queues as usual; later rounds advance the commit pipeline
        (replicate → commit → report) of whatever round 0 accepted.
        Queued ops beyond round 0's submit window simply ride the next
        call (the caller's drive loop keeps calling until resolved).
        The SPI device window uses this for its pump cycles — on a
        tunneled accelerator it collapses the per-cycle cost from ~n
        blocking round-trips to one.

        Falls back to per-round stepping for n <= 1 and for engines with
        overridden staging hooks (multihost lockstep drives per-round
        decisions). Deliver masks need no fallback: nemesis faults are
        installed via ``self.deliver`` and the fused program reads the
        same mask every round, exactly like n sequential step_round
        calls with an unchanged mask.
        """
        if n <= 1 or type(self)._stage_submits is not RaftGroups._stage_submits:
            for _ in range(n):
                self.step_round()
            return
        submits = self._build_submits()
        self._key, key = jax.random.split(self._key)
        fused = _fused_rounds_program(self.config, n)
        with self.metrics.timer("step_wall_ms"):
            self.state, raw0, raws = fused(self.state, submits,
                                           self.deliver, key)
            raws = jax.block_until_ready(raws)
        # overlap BOTH transfers (round 0 + the stacked tail) before the
        # first blocking conversion — one round-trip for the whole fetch
        for leaf in jax.tree.leaves(raws):
            leaf.copy_to_host_async()
        out0 = self._fetch_outputs(raw0)
        outs = jax.tree.map(np.asarray, raws)
        self.rounds += 1
        self.metrics.counter("rounds").inc()
        self._requeue_rejected(submits, out0)
        self._harvest(out0)
        self._record_assigned(submits, out0)
        if self._sessions is not None:
            self._sessions.tick()
        for i in range(n - 1):
            out_i = jax.tree.map(lambda x, i=i: x[i], outs)
            self.rounds += 1
            self.metrics.counter("rounds").inc()
            self._harvest(out_i)
            if self._sessions is not None:
                self._sessions.tick()
        if self._any_across(bool(self._query_queues)):
            self._serve_queries()
        # snapshot-install decision from the LAST round's view (deferring
        # a mid-scan stale follower one cycle is the same recovery path)
        if bool(outs.stale[-1].any()):
            last = jax.tree.map(lambda x: x[-1], raws)
            self.state = self._install(self.state, last.stale, last.leader)

    def serve_query(self, group: int, opcode: int, a: int = 0, b: int = 0,
                    c: int = 0, max_attempts: int = 50,
                    consistency: str = "sequential") -> int:
        """Serve ONE read-only op from the leader's applied state, never
        touching the log (unlike :meth:`submit_query`, whose unserved
        slots escalate to the command path and append an entry).

        For callers that replicate the engine deterministically across
        processes (the SPI device executor), log content must be a pure
        function of the committed command stream — so the no-leader
        fallback here only *steps* (advancing the clock, which no
        resource state depends on) and retries; it never appends.
        """
        from ..ops.apply import QUERY_OPCODES
        if opcode not in QUERY_OPCODES:
            raise ValueError(
                f"opcode {opcode} is not read-only; submit it as a command")
        sub = self._empty_submits()
        sub.opcode[group, 0] = opcode
        sub.a[group, 0] = a
        sub.b[group, 0] = b
        sub.c[group, 0] = c
        sub.valid[group, 0] = True
        atomic = np.zeros_like(sub.valid)
        atomic[group, 0] = consistency == "atomic"
        mine = False
        for _ in range(max_attempts):
            results, served = self._run_query(sub, atomic)
            mine = bool(served[group, 0])
            if self._agree(mine):
                self.metrics.counter("queries_served").inc()
                return int(results[group, 0])
            self.step_round()  # no leader yet / applied < commit: settle
        raise TimeoutError(
            f"group {group} query unservable after {max_attempts} rounds"
            + (" (local read was served; a peer process is stuck)"
               if mine else ""))

    def _serve_queries(self) -> None:
        """Drain the query lane: serve from the leader's applied state; a
        slot the device can't serve (leaderless group, applied < commit)
        escalates to the command path — same consistency, one log entry."""
        sub = self._empty_submits()
        placed = self._drain_into(self._query_queues, sub)
        atomic = np.zeros_like(sub.valid)
        for g, s in placed:
            if int(sub.tag[g, s]) in self._query_atomic:
                atomic[g, s] = True
        results, served = self._run_query(sub, atomic)
        fell_back = self.metrics.counter("queries_escalated")
        done = self.metrics.counter("queries_served")
        for g, s in placed:
            tag = int(sub.tag[g, s])
            self._query_atomic.discard(tag)
            if served[g, s]:
                if tag in self._inflight:
                    self._inflight.pop(tag)
                    self.results[tag] = int(results[g, s])
                    done.inc()
            else:
                op = (int(sub.opcode[g, s]), int(sub.a[g, s]),
                      int(sub.b[g, s]), int(sub.c[g, s]))
                if self.config.monotone_tag_accept:
                    # the command path is closed on monotone-tag engines
                    # (the gate would reject the escalated tag forever) —
                    # retry on the query lane instead; it becomes
                    # servable once a leader/lease settles
                    self._query_queues.setdefault(g, deque()).append(
                        (*op, tag))
                    if atomic[g, s]:
                        self._query_atomic.add(tag)
                    fell_back.inc()
                    continue
                # escalate: re-enter as a command (quorum-committed read —
                # always at least as strong as the requested level)
                self._queues.setdefault(g, deque()).append((*op, tag))
                self._inflight_ops[tag] = op  # joins the loss-retry protocol
                fell_back.inc()

    def drive_query_vector(self, groups, opcode, a=0, b=0, c=0,
                           atomic=False,
                           max_attempts: int = 50) -> np.ndarray:
        """One-shot vectorized READ serve: stage ``[N]`` read rows into
        per-group slots of ONE :func:`query_step` evaluation (no log
        append, no correlation tags, no per-op dicts) and return results
        aligned with the input rows. The read analog of
        :meth:`drive_vector` — the applying server's batched read pump
        stages a whole window here instead of paying a full device
        round-trip per ``serve_query`` call.

        ``atomic`` (scalar or ``[N]``) marks rows needing the leader
        LEASE (BOUNDED_LINEARIZABLE freshness); the SPI read pump passes
        False — its host-side gate already established the linearization
        point, exactly like the per-op ``DeviceEngine.query`` lane.

        Unserved rows (group mid-election, applied < commit) retry after
        a settling :meth:`step_round`, like :meth:`serve_query`; in the
        warm steady state every row serves on the first evaluation. The
        slot width pads to the next power of two so burst-size jitter
        compiles at most log2 variants of the query program."""
        from ..ops.apply import QUERY_OPCODES
        g = np.asarray(groups, np.int64).ravel()
        n = g.size
        out = np.zeros(n, np.int64)
        if n == 0:
            return out
        bc = lambda x: np.broadcast_to(
            np.asarray(x, np.int32).ravel(), (n,))
        op_a, a_a, b_a, c_a = bc(opcode), bc(a), bc(b), bc(c)
        bad = ~np.isin(op_a, tuple(QUERY_OPCODES))
        if bad.any():
            raise ValueError(
                f"opcode {int(op_a[bad][0])} is not read-only; submit it "
                "as a command")
        at_a = np.broadcast_to(np.asarray(atomic, bool).ravel(), (n,))
        counts = np.bincount(g, minlength=self.num_groups)
        width = int(counts.max(initial=1))
        S = 1 << (width - 1).bit_length()  # pow2: bounded jit variants
        G = self.num_groups
        order, gs, slots = _group_slot_pack(g)
        sub = Submits(opcode=np.zeros((G, S), np.int32),
                      a=np.zeros((G, S), np.int32),
                      b=np.zeros((G, S), np.int32),
                      c=np.zeros((G, S), np.int32),
                      tag=np.zeros((G, S), np.int32),
                      valid=np.zeros((G, S), bool))
        sub.opcode[gs, slots] = op_a[order]
        sub.a[gs, slots] = a_a[order]
        sub.b[gs, slots] = b_a[order]
        sub.c[gs, slots] = c_a[order]
        sub.valid[gs, slots] = True
        at = np.zeros((G, S), bool)
        at[gs, slots] = at_a[order]
        done = np.zeros(n, bool)
        served_ctr = self.metrics.counter("queries_served")
        for _ in range(max_attempts):
            results, served = self._run_query(sub, at)
            hit = served[gs, slots] & ~done[order]
            if hit.any():
                rows = order[hit]
                out[rows] = results[gs[hit], slots[hit]]
                done[rows] = True
                served_ctr.inc(int(hit.sum()))
                sub.valid[gs[hit], slots[hit]] = False
            if self._agree(bool(done.all())):
                self.metrics.counter("query_vector_drives").inc()
                return out
            self.step_round()  # no leader yet / applied < commit: settle
        raise TimeoutError(
            f"query vector: {int((~done).sum())}/{n} rows unservable "
            f"after {max_attempts} attempts")

    def _record_assigned(self, submits: Submits, out: StepOutputs) -> None:
        """Remember the (log index, term) each accepted queue-managed op
        landed at (its current placement) for provable-loss retry — see
        _harvest."""
        if not self._inflight_ops:
            return  # everything accepted this round already resolved
        acc = np.asarray(out.accepted)
        if not acc.any():
            return
        gi, si = np.nonzero(acc)
        g_l = gi.tolist()
        tag_l = np.asarray(submits.tag)[gi, si].tolist()
        idx_l = np.asarray(out.assigned)[gi, si].tolist()
        trm_l = np.asarray(out.assigned_term)[gi, si].tolist()
        for k, tag in enumerate(tag_l):
            if tag in self._inflight_ops:
                g = g_l[k]
                old = self._tag_index.get(tag)
                if old is not None:  # superseded placement (re-accept)
                    self._drop_placement(old[0], old[1])
                te = trm_l[k]
                self._placements.setdefault(g, {})[idx_l[k]] = (tag, te)
                self._tag_index[tag] = (g, idx_l[k])
                if te < self._pend_min.get(g, te + 1):
                    self._pend_min[g] = te
                # _harvest updated _leader_term BEFORE this runs: when the
                # accepting leader was deposed in the SAME step (accept in
                # phase 1, election in phase 4), the term has already
                # risen past te and no future rise would re-trigger the
                # hold scan — engage the hold here
                if te < self._leader_term[g]:
                    self._held.add(g)

    def _requeue_rejected(self, submits: Submits, out: StepOutputs) -> None:
        acc = np.asarray(out.accepted)
        valid = np.asarray(submits.valid)
        refused = np.asarray(out.refused)
        if refused.any():
            # permanent rejection (e.g. a config change that would empty
            # the group): fail to the client now — requeueing would block
            # the group's queue forever behind the FIFO suffix-reject
            failed = self.metrics.counter("ops_refused")
            for g, s in zip(*np.nonzero(refused & valid)):
                tag = int(submits.tag[g, s])
                # recorded for UNTRACKED tags too: drive_vector's rows
                # have no _inflight entry, and without the FAIL record a
                # refused row would spin the whole run to TimeoutError —
                # failing rows that DID commit on device
                self.results[tag] = FAIL
                failed.inc()
                if tag in self._inflight:
                    self._inflight.pop(tag)
                    self._inflight_ops.pop(tag, None)
        rejected = valid & ~acc & ~refused
        if not rejected.any():
            return
        # appendleft in REVERSE slot order so retried ops keep submission order
        for g, s in reversed(list(zip(*np.nonzero(rejected)))):
            self._queues.setdefault(int(g), deque()).appendleft(
                (int(submits.opcode[g, s]), int(submits.a[g, s]),
                 int(submits.b[g, s]), int(submits.c[g, s]),
                 int(submits.tag[g, s])))

    def _harvest(self, out: StepOutputs) -> None:
        if self.telemetry is not None and out.telemetry is not None:
            self.telemetry.ingest(out.telemetry, self.rounds)
        self.clock = int(np.asarray(out.clock).max(initial=self.clock))
        lt = np.asarray(out.leader_term)
        rose = self._placements and bool((lt > self._leader_term).any())
        np.maximum(self._leader_term, lt, out=self._leader_term,
                   casting="unsafe")
        if rose:  # leader changes are rare; only then re-derive holds
            for g, pend in self._placements.items():
                if any(te < self._leader_term[g] for _, te in pend.values()):
                    self._held.add(g)
        valid = np.asarray(out.out_valid)
        if valid.any() and (self._inflight or self._placements):
            # flat native-int views: per-element numpy scalar indexing and
            # int() conversion in this loop were a measurable share of the
            # client-visible op cost at 10k groups. Skipped entirely when
            # nothing is tracked — untracked commits (the vector drive's
            # rows, which correlate from the step outputs themselves)
            # have no routing to do here.
            gi, ii = np.nonzero(valid)
            g_l = gi.tolist()
            tags_l = np.asarray(out.out_tag)[gi, ii].tolist()
            res_l = np.asarray(out.out_result)[gi, ii].tolist()
            idx_l = np.asarray(out.out_index)[gi, ii].tolist()
            term_l = np.asarray(out.out_term)[gi, ii].tolist()
            latency = self.metrics.histogram("commit_latency_rounds")
            resubmitted = self.metrics.counter("ops_resubmitted")
            inflight = self._inflight
            results = self.results
            rounds = self.rounds
            n_done = 0
            for k, tag in enumerate(tags_l):
                g = g_l[k]
                if self._placements:  # retry bookkeeping only when pending
                    j, T = idx_l[k], term_l[k]
                    pend = self._placements.get(g)
                    at_j = pend.get(j) if pend else None
                    if pend and ((at_j is not None and at_j[1] != T)
                                 or T > self._pend_min.get(g, T)):
                        # provable loss: a pending placement (idx, term_e)
                        # can never commit once (a) an entry with term
                        # T > term_e applied at j <= idx — its log
                        # mismatches the committed prefix at j — or (b)
                        # THIS index applied under a different term
                        # (entries never move between indices). Guarded by
                        # the _pend_min lower bound so the steady state
                        # (T == every pending term) skips the scan.
                        lost = sorted(
                            (idx, t) for idx, (t, te) in pend.items()
                            if (idx >= j and te < T)
                            or (idx == j and te != T))
                        # appendleft in reverse idx order: co-lost ops
                        # keep their original relative order in the queue
                        for idx, owner in reversed(lost):
                            self._drop_placement(g, idx)
                            self._tag_index.pop(owner, None)
                            if owner in inflight:
                                self._queues.setdefault(
                                    g, deque()).appendleft(
                                    (*self._inflight_ops[owner], owner))
                                resubmitted.inc()
                        pend = self._placements.get(g)
                        if pend:  # refresh the stale lower bound
                            self._pend_min[g] = min(
                                te for _, te in pend.values())
                if tag and tag in inflight:
                    _, submit_round = inflight.pop(tag)
                    self._inflight_ops.pop(tag, None)
                    if self._tag_index:
                        placed = self._tag_index.pop(tag, None)
                        if placed is not None:
                            self._drop_placement(placed[0], placed[1])
                    results[tag] = res_l[k]
                    n_done += 1
                    latency.record(rounds - submit_round)
            if n_done:
                self.metrics.counter("ops_committed").inc(n_done)
        self._ingest_events(out)

    def _ingest_events(self, out) -> None:
        """Append this round's drained session events to the host buffer
        (dedup by absolute seq). Shared by every driver that steps the
        engine — the device pops events off its ring when drained, so a
        driver that skipped this would LOSE them."""
        ev_valid = np.asarray(out.ev_valid)
        if ev_valid.any():
            seq = np.asarray(out.ev_seq)
            code = np.asarray(out.ev_code)
            target = np.asarray(out.ev_target)
            arg = np.asarray(out.ev_arg)
            for g, i in zip(*np.nonzero(ev_valid)):
                g = int(g)
                s = int(seq[g, i])
                if s <= self._ev_seen.get(g, -1):
                    continue  # re-delivered after a leader change
                self._ev_seen[g] = s
                evs = self.events.setdefault(g, [])
                evs.append(
                    (s, int(code[g, i]), int(target[g, i]), int(arg[g, i])))
                # bounded buffer: facades track absolute seqs, so trimming
                # old events never invalidates a consumer cursor
                if len(evs) > self.MAX_EVENTS_PER_GROUP:
                    del evs[: len(evs) - self.MAX_EVENTS_PER_GROUP]

    def run(self, rounds: int) -> None:
        for _ in range(rounds):
            self.step_round()

    def run_until(self, tags: list[int], max_rounds: int = 200) -> None:
        """Step until all given tags have results (or raise). Lockstep on
        multi-host: every process passes ITS tags ([] if idle) and all
        stop together."""
        for _ in range(max_rounds):
            if self._agree(all(t in self.results for t in tags)):
                return
            self.step_round()
        missing = [t for t in tags if t not in self.results]
        raise TimeoutError(
            f"ops not committed after {max_rounds} rounds: "
            f"{missing if missing else 'local tags done — a peer process is stuck'}")

    def wait_for_leaders(self, max_rounds: int = 100) -> np.ndarray:
        """Step until every group has a leader; returns leader indices [G]
        (this process's local groups on multi-host)."""
        for _ in range(max_rounds):
            out = self.step_round()
            leaders = np.asarray(out.leader)
            if self._agree(bool((leaders >= 0).all())):
                return leaders
        raise TimeoutError(f"not all groups elected a leader in {max_rounds} rounds")

    # -- cluster membership (server join/leave) ----------------------------

    def submit_batch(self, groups, opcode, a=0, b=0, c=0) -> np.ndarray:
        """Vectorized bulk submit: queue one op per entry of ``groups``
        (scalars broadcast) in a single call; returns the correlation
        tags as an array aligned with the input. Amortizes the per-op
        Python staging cost (~5 µs/op through :meth:`submit`) for
        callers driving many groups per round. Config opcodes must go
        through :meth:`add_peer`/:meth:`remove_peer`."""
        groups_a = np.asarray(groups, np.int64).ravel()
        n = groups_a.size
        bc = lambda x: np.broadcast_to(
            np.asarray(x, np.int64).ravel(), (n,))
        op_a, a_a, b_a, c_a = bc(opcode), bc(a), bc(b), bc(c)
        if np.isin(op_a, (OP_CFG_ADD, OP_CFG_REMOVE)).any():
            raise ValueError("membership changes go through "
                             "add_peer/remove_peer, not submit_batch")
        self._refuse_monotone()
        tags = np.arange(self._next_tag, self._next_tag + n)
        if n == 0:
            return tags
        self._next_tag += n
        tag_l = tags.tolist()
        g_l = groups_a.tolist()
        rnd = self.rounds
        self._inflight.update(zip(tag_l, ((g, rnd) for g in g_l)))
        op_l, a_l, b_l, c_l = (op_a.tolist(), a_a.tolist(),
                               b_a.tolist(), c_a.tolist())
        self._inflight_ops.update(
            zip(tag_l, zip(op_l, a_l, b_l, c_l)))
        if not self._stage_direct(groups_a, op_a, a_a, b_a, c_a, tags):
            order = np.argsort(groups_a, kind="stable")
            bounds = np.flatnonzero(np.diff(groups_a[order])) + 1
            for seg in np.split(order, bounds):
                seg_l = seg.tolist()
                q = self._queues.setdefault(g_l[seg_l[0]], deque())
                q.extend((op_l[i], a_l[i], b_l[i], c_l[i], tag_l[i])
                         for i in seg_l)
        self.metrics.counter("ops_submitted").inc(n)
        return tags

    def drive_vector(self, groups, opcode, a, b, c,
                     max_rounds: int = 200) -> np.ndarray | None:
        """One-shot vectorized drive for full-delivery engines (the
        applying server's batched pump): stage every row straight into
        the next round's submit buffer, step shared rounds until all
        rows committed, and correlate results FROM THE STEP OUTPUTS in
        one numpy pass per round — no per-op tag dicts, no harvest
        routing, no result-cache churn. Returns results aligned with the
        input rows, or ``None`` when direct staging is refused (queued
        ops, holds, monotone engines, overfull groups) and the caller
        must take the tracked :meth:`submit_batch` path.

        Per-group FIFO holds because ``_stage_direct``'s stable group
        sort preserves row order within a group and the engine applies
        accepted slots in log order; a rejected row (rare: group mid-
        election) is requeued by ``_requeue_rejected`` and caught by a
        later round's correlation pass."""
        g = np.asarray(groups, np.int64)
        n = g.size
        tags = np.arange(self._next_tag, self._next_tag + n)
        if not self._stage_direct(g, np.asarray(opcode, np.int64),
                                  np.asarray(a, np.int64),
                                  np.asarray(b, np.int64),
                                  np.asarray(c, np.int64), tags):
            return None
        self._next_tag += n
        tag0 = tags[0] if n else 0
        res = np.zeros(n, np.int64)
        done = np.zeros(n, bool)
        self.metrics.counter("ops_submitted").inc(n)
        remaining = n
        for _ in range(max_rounds):
            out = self.step_round()
            valid = np.asarray(out.out_valid)
            if valid.any():
                gi, ii = np.nonzero(valid)
                t = np.asarray(out.out_tag)[gi, ii]
                mine = (t >= tag0) & (t < tag0 + n)
                if mine.any():
                    k = (t[mine] - tag0).astype(np.int64)
                    fresh = ~done[k]
                    k = k[fresh]
                    res[k] = np.asarray(out.out_result)[gi, ii][mine][fresh]
                    done[k] = True
                    remaining -= k.size
            if remaining and self.results:
                # terminal refusals (_requeue_rejected records FAIL for
                # this block's tags): resolve those rows to the sentinel
                # so the rest of the run still returns — the caller maps
                # FAIL to a per-row error
                for t in [t for t in self.results
                          if tag0 <= t < tag0 + n]:
                    k = int(t - tag0)
                    v = self.results.pop(t)
                    if not done[k]:
                        res[k] = v
                        done[k] = True
                        remaining -= 1
            if remaining == 0:
                self.metrics.counter("ops_committed").inc(n)
                return res
        raise TimeoutError(
            f"vector drive: {remaining}/{n} rows uncommitted after "
            f"{max_rounds} rounds")

    def add_peer(self, group: int, peer: int) -> int:
        """Add ``peer``'s lane to ``group``'s voter set (the reference's
        server join — ``AtomixServerTest.testServerJoin``). A single-server
        Raft config change through the log: returns a correlation tag that
        resolves in ``results`` once the entry is APPLIED (the step
        serializes config changes — one in flight per group — by rejecting
        early submits, which simply requeue here). Needs
        ``Config(dynamic_membership=True)``."""
        from ..ops.apply import OP_CFG_ADD
        if not self.config.dynamic_membership:
            raise ValueError("membership changes need "
                             "Config(dynamic_membership=True)")
        if not 0 <= peer < self.num_peers:
            raise ValueError(f"peer {peer} outside 0..{self.num_peers - 1}")
        return self.submit(group, OP_CFG_ADD, peer)

    def remove_peer(self, group: int, peer: int) -> int:
        """Remove ``peer``'s lane from ``group``'s voter set (server leave
        — ``testServerLeave``). Removing the last member is refused: the
        tag resolves to ``apply.FAIL``. A leader removing itself commits
        the change under the old config and then steps down."""
        if not self.config.dynamic_membership:
            raise ValueError("membership changes need "
                             "Config(dynamic_membership=True)")
        if not 0 <= peer < self.num_peers:
            raise ValueError(f"peer {peer} outside 0..{self.num_peers - 1}")
        return self.submit(group, OP_CFG_REMOVE, peer)

    @staticmethod
    def _config_mask(member: np.ndarray, applied: np.ndarray,
                     term: np.ndarray, role: np.ndarray) -> int:
        """Freshest applied config bitmask among one group's [P] lanes.

        Prefers the CURRENT leader's lane (it serializes config changes,
        so it carries the freshest applied config) — guarded by term so a
        partitioned zombie leader (still role==leader at a stale term)
        cannot shadow the committed config. Leaderless, falls back to the
        most-applied lane, which can transiently lag by one change during
        a snapshot-install/catch-up window (callers that need the
        post-change view step the engine first, as the membership tests
        do)."""
        leaders = np.nonzero(role == 2)[0]
        if len(leaders):
            lead = int(leaders[np.argmax(term[leaders])])
            if term[lead] == term.max():
                return int(member[lead])
        return int(member[int(np.argmax(applied))])

    def voting_members(self, group: int) -> list[int]:
        """Current voter lanes of ``group`` (see :meth:`_config_mask` for
        the lane-selection rule)."""
        s = self.state
        mask = self._config_mask(np.asarray(s.member[group]),
                                 np.asarray(s.applied_index[group]),
                                 np.asarray(s.term[group]),
                                 np.asarray(s.role[group]))
        return [p for p in range(self.num_peers) if (mask >> p) & 1]

    # -- inspection --------------------------------------------------------

    def device_snapshot(self) -> dict:
        """The ``device.*`` telemetry family as a mergeable snapshot
        dict (empty when telemetry is off). This is what ``/stats``
        embeds, ``bench.py --metrics-json`` records, and
        ``merge_snapshots`` folds across shards/processes."""
        if self.telemetry is None:
            return {}
        return self.telemetry.snapshot()

    def merged_device_snapshot(self) -> dict:
        """Cluster-wide ``device.*`` snapshot. Identity on one process;
        the multihost subclass allgathers every process's local family
        and folds them with ``merge_snapshots`` (counters sum, gauges
        max) so elections/commit-advance attribute per shard."""
        return self.device_snapshot()

    def leader(self, group: int) -> int:
        role = np.asarray(self.state.role[group])
        term = np.asarray(self.state.term[group])
        leaders = np.nonzero(role == 2)[0]
        if len(leaders) == 0:
            return -1
        return int(leaders[np.argmax(term[leaders])])

    def value(self, group: int, peer: int = 0) -> int:
        return int(self.state.resources.value[group, peer])
