"""Sessioned batch client over the bulk/deep pipeline (plane unification).

The reference has ONE client runtime — sessioned, sequenced,
exactly-once, any topology (the Copycat client consumed per SURVEY.md
§2.3; ``Atomix.java:205`` is its data path). Round 4 left this repo with
two planes that did not compose: the deep bulk plane (≥1M client-visible
ops/s, sessionless) and the queue-managed/SPI plane (sessions + events,
orders of magnitude slower). This module composes them: a batched
SESSION client whose commands carry (session, seq), are deduplicated
exactly-once, and commit through the pipelined bulk drive — the
reference's client contract riding the plane that meets the north star.

Contract (reference parity — Copycat client runtime semantics):

- **per-session/per-group FIFO**: a session's commands to one group
  apply in submission order (the drive schedules each group's ops in
  batch order; on monotone-tag engines the device gate enforces it).
  Groups are independent replicated state machines, so cross-group
  order is not defined — the analogue of the reference's per-cluster
  session sequencing.
- **exactly-once**: retransmits inside the drive protocol never
  double-apply. On monotone engines this is DEVICE-enforced (the tag
  gate rejects any duplicate whose original can still commit —
  ``ops/consensus.py``); on classic engines it is the provable-loss
  retry (``raft_groups._harvest``). Results are cached per
  (session, seq): :meth:`BulkSession.result` correlates any number of
  times, the reference's response-caching session contract
  (``SURVEY.md §2.3 session protocol``).
- **session events**: per-group event streams (lock grants, election
  fire, topic messages) are delivered to session listeners in seq
  order with per-listener cursors (``Listeners`` registrations, closeable
  like the reference's).
- **liveness**: keep-alives ride every flush — all sessions of one
  client share the client runtime, as the reference's sessions share
  their client's connection. A session whose client stops flushing
  expires through :class:`~copycat_tpu.models.sessions.DeviceSessionRegistry`
  and its lock/election interests are released THROUGH THE LOG
  (deterministic fan-out); on monotone engines the cleanup ops are
  drained by the next flush of any surviving client.

Throughput: all sessions' pending commands flush as ONE bulk drive
(deep mode on monotone engines: zero blocking fetches per round, one
result fetch per flush), with per-op bookkeeping held to numpy slicing
+ one dict update per op. Measured by the ``session`` bench scenario
(BENCH_SCENARIOS.md); the round-5 target is ≥100k client-visible
committed ops/s on one chip through THIS sessioned surface.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, NamedTuple

import numpy as np

from ..utils import knobs
from ..utils.listeners import Listener, Listeners
from .bulk import BulkDriver
from .sessions import DeviceSession, SessionExpiredError

logger = logging.getLogger(__name__)


class CommandIndeterminateError(RuntimeError):
    """The drive carrying this command was abandoned (fault-envelope
    violation): the command MAY have applied. The reference surfaces the
    same indeterminacy when a session dies mid-command (Copycat's
    command failure on session loss); correlate a fresh read to learn
    the state."""


class SessionEvent(NamedTuple):
    """One replicated session event, as delivered to listeners."""

    group: int
    seq: int      # absolute per-group event seq (dedup key)
    code: int     # ops.apply.EV_* code
    target: int   # e.g. granted holder id; -1 = broadcast
    arg: int


#: result-cache sentinels (identity-compared in BulkSession.result)
_INDETERMINATE = object()
_EXPIRED = object()


class _EdgeValueCache:
    """Device-plane edge replica (docs/EDGE_READS.md): the post-apply
    state rows of this client's OWN committed value-pool writes, served
    back to CAUSAL-level reads without an engine round.

    On the device plane a Raft group IS the resource, and a committed
    write's post-apply register value is derivable from ``(opcode,
    operands, result)`` — SET/GET_AND_SET install their operand, CAS
    installs its update iff the result says it swapped, LONG_ADD
    returns the new value outright. Read-your-writes and monotone reads
    hold per client by construction (every committed write of this
    client passes through :meth:`observe` in batch order); freshness
    against OTHER processes' writes is exactly what CAUSAL does not
    promise — SEQUENTIAL and above always drive the engine. An
    abandoned drive purges the cache: its ops are INDETERMINATE, and
    serving a pre-abandon row would hide a write that may have applied
    (the correlate-a-fresh-read recovery contract).

    Only groups the client actually reads through the causal lane are
    tracked (the interest set), so write-only workloads pay one
    truthiness check per flush.
    """

    __slots__ = ("state", "interest", "ttl_groups", "_m_serves",
                 "_m_fallbacks", "_m_merges", "_m_purges")

    def __init__(self, metrics: Any) -> None:
        self.state: dict[int, int] = {}
        self.interest: set[int] = set()
        # groups that ever armed a device-side TTL (OP_VALUE_SET with a
        # ttl-ticks operand): the engine will clear them at a deadline
        # the host cannot observe, so they are permanently uncacheable
        self.ttl_groups: set[int] = set()
        self._m_serves = metrics.counter("edge.local_serves")
        self._m_fallbacks = metrics.counter("edge.server_fallbacks")
        self._m_merges = metrics.counter("edge.merges")
        self._m_purges = metrics.counter("edge.purges")

    def observe(self, groups: np.ndarray, opcode: np.ndarray,
                a: np.ndarray, b: np.ndarray, c: np.ndarray,
                results: np.ndarray) -> None:
        """Fold one committed chunk's value-pool writes into the
        replica (vectorized; called from the flush's correlate pass)."""
        if not self.interest:
            return
        from ..ops import apply as ops
        watched = np.isin(groups, np.fromiter(self.interest, np.int64))
        if not watched.any():
            return
        is_set = opcode == ops.OP_VALUE_SET
        # a TTL'd set expires ON DEVICE at a log-time deadline this
        # cache cannot see (ops/apply.py: the register then reads as
        # unset) — blacklist the group from caching outright
        ttl = watched & is_set & (c != 0)
        if ttl.any():
            for g in groups[ttl].tolist():
                self.ttl_groups.add(int(g))
                self.state.pop(int(g), None)
        is_gas = opcode == ops.OP_VALUE_GET_AND_SET
        is_add = opcode == ops.OP_LONG_ADD
        is_cas = (opcode == ops.OP_VALUE_CAS) & (results == 1)
        mask = watched & (is_set | is_gas | is_add | is_cas)
        if self.ttl_groups:
            mask &= ~np.isin(groups,
                             np.fromiter(self.ttl_groups, np.int64))
        if not mask.any():
            return
        value = np.where(is_add, results, np.where(is_cas, b, a))
        for g, v in zip(groups[mask].tolist(), value[mask].tolist()):
            self.state[int(g)] = int(v)
        self._m_merges.inc(int(mask.sum()))

    def serve(self, groups: np.ndarray) -> np.ndarray | None:
        """All-or-nothing local serve of one GET batch; ``None`` falls
        back to the engine's query lane (and marks interest so future
        flushes feed these groups)."""
        state = self.state
        out = np.empty(groups.size, np.int64)
        for k, g in enumerate(groups.tolist()):
            v = state.get(int(g))
            if v is None:
                self.interest.update(int(x) for x in groups.tolist())
                self._m_fallbacks.inc(int(groups.size))
                return None
            out[k] = v
        self._m_serves.inc(int(groups.size))
        return out

    def refresh_from_reads(self, groups: np.ndarray,
                           results: np.ndarray) -> None:
        """Fold an ENGINE-served GET's results back into the replica:
        the engine's answer is at-least-as-new as anything cached, so
        this keeps mixed-level read sequences monotone — a session
        that observed a foreign writer's value through a SEQUENTIAL
        read must never see an older cached value from a later CAUSAL
        read."""
        if not self.interest:
            return
        for g, v in zip(groups.tolist(), results.tolist()):
            g = int(g)
            if g in self.interest and g not in self.ttl_groups:
                self.state[g] = int(v)

    def purge(self) -> None:
        """Drop every cached row (abandoned drive: ops may or may not
        have applied; the next read must come from the engine)."""
        if self.state:
            self.state.clear()
            self._m_purges.inc()

#: SPI read-consistency vocabulary -> device query lane level. The
#: device lane has two serving regimes (leader applied state; leader
#: applied state + lease gate); each SPI level maps to the weakest
#: regime that satisfies it.
_READ_LEVELS = {
    "none": "sequential",
    "causal": "sequential",
    "process": "sequential",
    "sequential": "sequential",
    "atomic": "atomic",
    "bounded_linearizable": "atomic",
    "linearizable": "atomic",
}


class _Chunk(NamedTuple):
    """One buffered batch of commands (vectorized submission unit)."""

    seq0: int
    groups: np.ndarray
    opcode: np.ndarray
    a: np.ndarray
    b: np.ndarray
    c: np.ndarray


class BulkSession:
    """One sessioned client identity over a :class:`BulkSessionClient`.

    ``id`` doubles as the lock-holder / election-candidate id for ops
    submitted through this session (the reference's "state is keyed by
    sessions" discipline), so registry expiry can release exactly this
    session's interests.
    """

    def __init__(self, client: "BulkSessionClient",
                 dev: DeviceSession) -> None:
        self._client = client
        self._dev = dev
        self.id = dev.id
        self._next_seq = 0
        self._pending: list[_Chunk] = []
        # seq -> committed result, or the _INDETERMINATE/_EXPIRED
        # sentinel objects (identity-compared in result())
        self._results: dict[int, int | object] = {}
        # group -> (Listeners, last-delivered event seq)
        self._subs: dict[int, tuple[Listeners, int]] = {}

    # -- command submission (buffered; committed by client.flush()) -------

    def submit(self, group: int, opcode: int, a: int = 0, b: int = 0,
               c: int = 0) -> int:
        """Buffer one command; returns its session sequence number.

        The seq is assigned exactly once — a client-level retry is a
        re-read of :meth:`result`, never a re-submit, so the op can
        never double-apply through this API.
        """
        return int(self.submit_batch([group], opcode, a, b, c)[0])

    def submit_batch(self, groups, opcode, a=0, b=0, c=0) -> np.ndarray:
        """Vectorized submit: one command per entry of ``groups``
        (scalars broadcast); returns the assigned seqs. The per-op cost
        is pure numpy — this is the API the ≥100k ops/s surface uses."""
        self._check_open()
        g = np.asarray(groups, np.int64).ravel()
        n = g.size
        bc = lambda x: np.broadcast_to(
            np.asarray(x, np.int32).ravel(), (n,)).copy()
        chunk = _Chunk(self._next_seq, g, bc(opcode), bc(a), bc(b), bc(c))
        self._next_seq += n
        if n:
            self._pending.append(chunk)
        return np.arange(chunk.seq0, chunk.seq0 + n)

    def lock_acquire(self, group: int, timeout_ticks: int = -1) -> int:
        """Convenience: queue a lock acquire keyed by THIS session (and
        bind the interest so expiry releases it)."""
        from ..ops import apply as ops
        self._dev.bind(group, "lock")
        return self.submit(group, ops.OP_LOCK_ACQUIRE, self.id,
                           timeout_ticks)

    def elect_listen(self, group: int) -> int:
        from ..ops import apply as ops
        self._dev.bind(group, "election")
        return self.submit(group, ops.OP_ELECT_LISTEN, self.id)

    # -- result correlation (exactly-once read side) ----------------------

    def result(self, seq: int) -> int:
        """Committed result of command ``seq``. Raises ``KeyError`` while
        the command is still buffered/in-flight (flush first);
        :class:`CommandIndeterminateError` if the drive carrying it was
        abandoned; :class:`SessionExpiredError` if the session died
        before the command committed."""
        val = self._results[seq]
        if val is _INDETERMINATE:
            raise CommandIndeterminateError(
                f"session {self.id} seq {seq}: drive abandoned; the "
                "command may or may not have applied")
        if val is _EXPIRED:
            raise SessionExpiredError(
                f"session {self.id} expired before seq {seq} committed")
        return val

    def results_window(self, seq0: int, n: int) -> np.ndarray:
        """Vectorized :meth:`result` for a contiguous seq window."""
        return np.fromiter((self.result(s) for s in range(seq0, seq0 + n)),
                           np.int64, n)

    # -- queries (no log append) ------------------------------------------

    def query_batch(self, groups, opcode, a=0, b=0, c=0,
                    consistency: str = "sequential") -> np.ndarray:
        """Serve reads through the query lane (no log entry), tagged
        with their ``consistency`` and routed by it — the full SPI read
        vocabulary is accepted so both planes speak one language:
        ``causal``/``sequential`` serve from the leader lane's applied
        state (the reference's sub-ATOMIC routing), while
        ``bounded_linearizable``/``linearizable``/``atomic`` gate each
        slot on the leader LEASE (``RaftState.lease``) — in the
        synchronous round model the lease round IS the linearization
        point (no other leader can have committed), so lease-gated reads
        serve both levels without a log append (reference
        ``Consistency.java:157-176``). Counts as session activity
        (keep-alive)."""
        level = _READ_LEVELS.get(consistency)
        if level is None:
            raise ValueError(
                f"unknown read consistency {consistency!r}; pick one of "
                f"{sorted(_READ_LEVELS)}")
        self._check_open()
        g = np.asarray(groups, np.int64).ravel()
        self._client._rg.metrics.counter(
            "session_reads", consistency=consistency).inc(int(g.size))
        self._client._registry.keep_alive(self.id)
        edge = self._client._edge
        all_get = False
        if edge is not None:
            from ..ops import apply as ops
            all_get = bool(np.all(np.asarray(opcode) == ops.OP_VALUE_GET))
            if all_get and consistency in ("causal", "none", "process"):
                # edge read tier (docs/EDGE_READS.md): CAUSAL-level GETs
                # may serve from the client's replica of its own
                # committed post-apply state rows — no engine round.
                # SEQUENTIAL and above always drive (cross-process
                # freshness).
                served = edge.serve(g)
                if served is not None:
                    return served
        out = self._client._driver.drive_queries(
            g, opcode, a, b, c, consistency=level)
        if edge is not None and all_get:
            # engine-served answers refresh the replica so a later
            # causal read can never regress behind what this session
            # just observed (mixed-level monotonicity)
            edge.refresh_from_reads(g, out)
        return out

    # -- events ------------------------------------------------------------

    def on_event(self, group: int, callback: Callable[[SessionEvent], Any]
                 ) -> Listener:
        """Register a listener for ``group``'s session events; delivery
        happens during :meth:`BulkSessionClient.flush`, in event-seq
        order, starting from events newer than registration time."""
        listeners, cursor = self._subs.get(group, (None, None))
        if listeners is None:
            evs = self._client._rg.events.get(group, [])
            listeners = Listeners()
            cursor = evs[-1][0] if evs else -1
            self._subs[group] = (listeners, cursor)
        return listeners.add(callback)

    # -- lifecycle ---------------------------------------------------------

    @property
    def is_open(self) -> bool:
        return not (self._dev.expired or self._dev.closed)

    def keep_alive(self) -> None:
        self._dev.keep_alive()

    def close(self) -> None:
        """Graceful close: deterministic release of every bound interest
        (same fan-out as expiry), committed by the next flush."""
        if self.is_open:
            self._dev.close()
            self._client._closed.append(self)

    def _check_open(self) -> None:
        if not self.is_open:
            raise SessionExpiredError(f"session {self.id} is dead")


class BulkSessionClient:
    """The unified client runtime: sessions + exactly-once + events over
    the pipelined bulk drive (deep mode on monotone-tag engines).

    One client per process/engine is the intended shape (the reference's
    ``AtomixClient`` with many sessions over one connection). All
    sessions' buffered commands commit in ONE drive per :meth:`flush`.
    """

    def __init__(self, rg, *, deep_scan: bool = False) -> None:
        self._rg = rg
        self._driver = BulkDriver(rg, allow_sessions=True,
                                  deep_scan=deep_scan)
        self._registry = rg.sessions            # instantiates lazily
        self._sessions: dict[int, BulkSession] = {}
        self._closed: list[BulkSession] = []
        # the device-plane edge replica (docs/EDGE_READS.md); the same
        # COPYCAT_EDGE_READS knob removes it bit-identically
        self._edge = (_EdgeValueCache(rg.metrics)
                      if knobs.get_bool("COPYCAT_EDGE_READS") else None)

    # -- sessions ----------------------------------------------------------

    def open_session(self) -> BulkSession:
        s = BulkSession(self, self._registry.open_session())
        self._sessions[s.id] = s
        return s

    # -- the data path -----------------------------------------------------

    def flush(self, max_rounds: int = 10_000) -> int:
        """Commit every session's buffered commands in one bulk drive;
        correlate results, run session housekeeping (keep-alives, expiry
        fan-out, cleanup commits), deliver events. Returns the number of
        session commands committed."""
        rg = self._rg
        metrics = rg.metrics
        t_flush = time.perf_counter()
        # 1. liveness: flushing proves this client's sessions are alive
        #    (they share this runtime), exactly like the reference's
        #    connection-level keep-alive covering all its sessions.
        t_ka = time.perf_counter()
        live = 0
        for s in self._sessions.values():
            if s.is_open:
                live += 1
                self._registry.keep_alive(s.id)
        metrics.histogram("session_keepalive_ms").record(
            (time.perf_counter() - t_ka) * 1e3)
        metrics.gauge("sessions_live").set(live)
        metrics.gauge("sessions_closing").set(len(self._closed))
        # 2. expiry sweep — fans out cleanup ops for dead sessions
        #    (pending_cleanup on monotone engines, submit queues on
        #    classic ones).
        self._registry.tick()

        # 3. gather: session chunks + staged cleanup ops, one drive.
        #    A gracefully CLOSED session's buffered commands still
        #    commit (they were accepted before close; its release
        #    fan-out rides the same drive, behind them in batch order).
        #    An EXPIRED session's buffered commands do NOT — its
        #    interests were already released, so applying them now would
        #    reorder against its own cleanup; they fail as
        #    SessionExpiredError (the reference's unknown-session
        #    command failure).
        chunks: list[tuple[BulkSession | None, _Chunk]] = []
        # Sessions leaving this client after THIS flush (graceful closes
        # whose fan-out commits here, expiries detected here). They stay
        # in _sessions until after _deliver_events: the reference's
        # deliver-until-close contract — a close's own final events
        # (lock release grants, election promotions) reach the closing
        # session's listeners on the flush that commits the close, not
        # never.
        leaving: list[BulkSession] = []
        expired = 0
        for s in list(self._sessions.values()):
            if s._dev.expired:
                expired += 1
                for ch in s._pending:
                    s._results.update(
                        (q, _EXPIRED)
                        for q in range(ch.seq0, ch.seq0 + ch.groups.size))
                s._pending = []
                leaving.append(s)
                continue
            for ch in s._pending:
                chunks.append((s, ch))
            s._pending = []
        leaving.extend(self._closed)
        self._closed.clear()
        cleanup = self._registry.pending_cleanup
        if cleanup:
            cl = np.asarray(cleanup, np.int64)
            chunks.append((None, _Chunk(0, cl[:, 0],
                                        cl[:, 1].astype(np.int32),
                                        cl[:, 2].astype(np.int32),
                                        np.zeros(len(cl), np.int32),
                                        np.zeros(len(cl), np.int32))))
            self._registry.pending_cleanup = []

        committed = 0
        if chunks or getattr(rg, "process_count", 1) > 1:
            cat = lambda i: (np.concatenate([c[i] for _, c in chunks])
                             if chunks else np.zeros(0, np.int64))
            tag_mark = rg._next_tag
            try:
                res = self._driver.drive(cat(1), cat(2), cat(3), cat(4),
                                         cat(5), max_rounds=max_rounds)
            except Exception as exc:
                if cleanup:
                    # Cleanup ops are RE-STAGED on every failure —
                    # CANCEL/RELEASE/RESIGN are idempotent no-ops when
                    # already applied, so retrying them is always safe,
                    # and dropping them would wedge a dead session's
                    # locks forever.
                    self._registry.pending_cleanup = (
                        cleanup + self._registry.pending_cleanup)
                if (isinstance(exc, TimeoutError)
                        or rg._next_tag != tag_mark):
                    if self._edge is not None:
                        # the abandoned ops may have applied: a cached
                        # row could hide a write RYW must surface
                        self._edge.purge()
                    # Abandoned drive (fault-envelope violation), or any
                    # error raised AFTER the drive reserved its tag block
                    # — device dispatch may have begun, so the commands
                    # may have committed. Mark them INDETERMINATE so
                    # result() reports the truth instead of a bare
                    # KeyError. The tag-counter check is the dispatch
                    # boundary: exception TYPE alone must not decide this
                    # (an XLA runtime error mid-drive is not a preflight
                    # refusal, and restoring it for retry would
                    # double-apply non-idempotent ops).
                    for s, ch in chunks:
                        if s is not None:
                            metrics.counter(
                                "session_commands_indeterminate").inc(
                                    int(ch.groups.size))
                            s._results.update(
                                (q, _INDETERMINATE)
                                for q in range(ch.seq0,
                                               ch.seq0 + ch.groups.size))
                else:
                    # Raised BEFORE any device dispatch (the drive's
                    # preflight refusals: tag-space OverflowError,
                    # accumulator-skew ValueError) — no tags were
                    # consumed, so these commands definitely did not
                    # apply. Restore them to their sessions' _pending
                    # (original order: the chunk walk preserves
                    # per-session submission order) and re-raise; the
                    # caller can split the burst and re-flush without
                    # the correlate-a-read recovery path.
                    for s, ch in chunks:
                        if s is not None:
                            s._pending.append(ch)
                self._closed.extend(
                    s for s in leaving if not s._dev.expired)
                raise
            # 4. correlate: slice results back per chunk, cache by seq.
            off = 0
            for s, ch in chunks:
                n = ch.groups.size
                if s is not None:
                    vals = res.results[off:off + n]
                    if self._edge is not None:
                        # post-apply state rows feed the edge replica
                        self._edge.observe(ch.groups, ch.opcode, ch.a,
                                           ch.b, ch.c, vals)
                    s._results.update(
                        zip(range(ch.seq0, ch.seq0 + n), vals.tolist()))
                    committed += n
                off += n
        # 5. classic engines: expiry fan-out rode the queue-managed path;
        #    pump it so releases land now, not at an arbitrary later step.
        #    (Lockstep-agreed: step_round is a collective program on
        #    multihost engines, so all processes pump together.)
        pump = 0
        while rg._any_across(bool(rg._queues)) and pump < 16:
            rg.step_round()
            pump += 1
        if pump >= 16 and rg._any_across(bool(rg._queues)):
            # Backpressure: the expiry/close fan-out (lock releases,
            # resigns) did not drain within the cap — it is deferred to
            # a later flush's pump. Loud, and counted, so a wedged
            # cleanup shows up in metrics instead of silently delaying
            # lock handoff.
            rg.metrics.counter("cleanup_pump_deferred").inc()
            logger.warning(
                "session cleanup pump hit its %d-round cap with ops "
                "still queued; fan-out deferred to the next flush", pump)
        # 6. events (the drive ingested them into rg.events with seq
        #    dedup): deliver to listeners in order, per-group cursors —
        #    including to sessions this flush closes/expires (the
        #    deliver-until-close contract), which are popped only after.
        self._deliver_events()
        for s in leaving:
            self._sessions.pop(s.id, None)
        if expired:
            # a counter, not a gauge: expiry is an EVENT per flush — a
            # gauge would read 0 again one flush later and lose history
            metrics.counter("sessions_expired_total").inc(expired)
        metrics.gauge("session_event_backlog").set(
            sum(len(evs) for evs in rg.events.values()))
        metrics.counter("session_ops_committed").inc(committed)
        metrics.histogram("session_flush_ms").record(
            (time.perf_counter() - t_flush) * 1e3)
        return committed

    def _deliver_events(self) -> None:
        for s in self._sessions.values():
            for group, (listeners, cursor) in list(s._subs.items()):
                if not len(listeners):
                    continue
                new_cursor = cursor
                try:
                    for seq, code, target, arg in self._rg.events.get(
                            group, []):
                        if seq <= cursor:
                            continue
                        # cursor advances BEFORE dispatch: a sync
                        # listener that raises (into the emitter, the
                        # Listeners contract) must not cause redelivery
                        # of already-delivered events on the next flush
                        new_cursor = seq
                        listeners.accept(
                            SessionEvent(group, seq, code, target, arg))
                finally:
                    if new_cursor != cursor:
                        s._subs[group] = (listeners, new_cursor)

    def recover(self, settle_rounds: int = 30) -> None:
        """Re-arm after an abandoned flush (``TimeoutError``): heal-time
        protocol delegating to :meth:`BulkDriver.recover` — settle every
        surviving lineage and resync the tag cursors so post-abandon tag
        reuse is impossible. Call after restoring delivery (faults
        healed); then flush as normal. Abandoned commands stay
        indeterminate (read the state to learn their fate)."""
        if self._edge is not None:
            self._edge.purge()
        self._driver.recover(settle_rounds=settle_rounds)

    def close(self) -> None:
        """Close every session and commit their cleanup."""
        for s in list(self._sessions.values()):
            s.close()
        self.flush()
