"""Checkpoint/resume for the batched consensus state.

The reference has **no snapshots** — durability is the replicated log with
segmented storage (SURVEY.md §5.4); recovery = replay. The rebuild adds
real snapshots (named there as "a capability gap worth fixing"): the whole
``RaftState`` pytree (logs, indices, every resource pool, event rings) plus
driver counters serializes to one compressed ``.npz``. Restore yields a
driver that continues exactly where the snapshot was taken — in-flight
client ops are *not* checkpointed (clients re-submit, the same contract as
the reference's session recovery).
"""

from __future__ import annotations

import io
import json
import pathlib

import jax
import numpy as np

from ..ops.consensus import Config
from ..ops.apply import ResourceConfig


def _leaf_name(path) -> str:
    """Dotted field path of a pytree leaf ('resources.mm_key', 'term')."""
    return ".".join(getattr(p, "name", str(p)) for p in path)


def save(rg, path: str | pathlib.Path) -> None:
    """Snapshot a ``RaftGroups`` driver to ``path`` (.npz).

    State leaves are stored BY FIELD PATH (``state.resources.mm_key``),
    not positionally, so restoring stays correct no matter where future
    fields are inserted in ``RaftState``/``ResourceState`` — a missing
    (newer) field simply keeps the fresh template value on load.
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(rg.state)
    arrays = {f"state.{_leaf_name(p)}": np.asarray(x) for p, x in flat}
    meta = {
        "num_groups": rg.num_groups,
        "num_peers": rg.num_peers,
        "log_slots": rg.log_slots,
        "submit_slots": rg.submit_slots,
        "config": rg.config._asdict() | {
            "resource": rg.config.resource._asdict()},
        "rounds": rg.rounds,
        "clock": rg.clock,
        "next_tag": rg._next_tag,
        "ev_seen": rg._ev_seen,
        # the host-side event buffer (consumption cursors are facade-local,
        # so this includes consumed events): restores the buffer faithfully
        # and keeps seq dedup (_ev_seen) consistent with it. Facades
        # created after restore start their cursor past these (session
        # events die with the session) and re-query authoritative state.
        "events": {str(g): evs for g, evs in rg.events.items()},
        "key": np.asarray(rg._key).tolist(),
        "num_leaves": len(flat),
    }
    arrays["deliver"] = np.asarray(rg.deliver)
    target = path if hasattr(path, "write") else str(path)
    np.savez_compressed(target, meta=json.dumps(meta), **arrays)
    del treedef  # structure is reconstructed from a fresh init on load


def save_bytes(rg) -> bytes:
    """Snapshot a ``RaftGroups`` driver to in-memory bytes (the same
    field-path ``.npz`` format as :func:`save`) — the server-plane
    snapshot subsystem embeds this blob for device-backed machines."""
    bio = io.BytesIO()
    save(rg, bio)
    return bio.getvalue()


def load_bytes(data: bytes, mesh=None):
    """Restore a ``RaftGroups`` driver from :func:`save_bytes` output."""
    return load(io.BytesIO(data), mesh=mesh)


def load(path: str | pathlib.Path, mesh=None):
    """Restore a ``RaftGroups`` driver from a snapshot."""
    from .raft_groups import RaftGroups

    source = path if hasattr(path, "read") else str(path)
    with np.load(source, allow_pickle=False) as data:
        meta = json.loads(str(data["meta"]))
        cfg = dict(meta["config"])
        cfg["resource"] = ResourceConfig(**cfg["resource"])
        # Tolerate snapshots from older Configs: drop fields that no
        # longer exist (e.g. apply_unroll, removed with the conflict-
        # partitioned apply) instead of failing the whole restore; new
        # fields get their defaults. pool_budgets round-trips through
        # JSON as a list — restore the hashable tuple.
        cfg = {k: v for k, v in cfg.items() if k in Config._fields}
        if isinstance(cfg.get("pool_budgets"), list):
            cfg["pool_budgets"] = tuple(cfg["pool_budgets"])
        config = Config(**cfg)
        rg = RaftGroups(meta["num_groups"], meta["num_peers"],
                        log_slots=meta["log_slots"],
                        submit_slots=meta["submit_slots"],
                        config=config, mesh=mesh)
        template = rg.state
        treedef = jax.tree_util.tree_structure(template)
        if any(k.startswith("state.") for k in data.files):
            # Path-keyed format: robust to fields inserted ANYWHERE — a
            # field absent from the snapshot keeps its fresh template
            # value (e.g. a pool added after the snapshot was taken).
            flat = jax.tree_util.tree_flatten_with_path(template)[0]
            leaves = [data[f"state.{_leaf_name(p)}"]
                      if f"state.{_leaf_name(p)}" in data else np.asarray(x)
                      for p, x in flat]
        else:
            # Legacy positional format (leaf_0..leaf_N in the field order
            # of the SAVING code). Fields were strictly appended while
            # this format was in use, so missing leaves are the trailing
            # ones: pad with the template's fresh arrays.
            leaves = [data[f"leaf_{i}"] for i in range(meta["num_leaves"])]
            expected = jax.tree_util.tree_leaves(template)
            if len(leaves) < len(expected):
                leaves = leaves + expected[len(leaves):]
        state = jax.tree_util.tree_unflatten(treedef, leaves)
        if mesh is not None:
            from ..parallel import shard_state
            state = shard_state(state, mesh)
        else:
            state = jax.tree.map(jax.numpy.asarray, state)
        rg.state = state
        rg.deliver = jax.numpy.asarray(data["deliver"])
        rg.rounds = meta["rounds"]
        rg.clock = meta["clock"]
        rg._next_tag = meta["next_tag"]
        rg._ev_seen = {int(k): int(v) for k, v in meta["ev_seen"].items()}
        rg.events = {int(g): [tuple(e) for e in evs]
                     for g, evs in meta.get("events", {}).items()}
        import jax.numpy as jnp
        rg._key = jnp.asarray(np.asarray(meta["key"], np.uint32))
        if config.monotone_tag_accept:
            # the monotone stream cursor is DERIVED, not stored: the
            # restored log ring is authoritative (works for snapshots
            # taken before the cursor existed)
            from .bulk import stream_count_from_state
            rg._stream_count = stream_count_from_state(rg.state)
    return rg
