"""Flagship device-resident models.

``RaftGroups`` is the framework's flagship: every Raft group in the cluster
batched into one XLA program (the TPU equivalent of the reference's
one-``ResourceManager``-per-server design, ``AtomixReplica.java:374``).
"""

from .raft_groups import RaftGroups  # noqa: F401
from .bulk import BulkDriver, BulkResult, drive_batch  # noqa: F401
from .telemetry import (  # noqa: F401
    DeviceTelemetryHub,
    FlightRecorder,
    InvariantMonitor,
    InvariantViolation,
)
from .session_client import (  # noqa: F401
    BulkSession,
    BulkSessionClient,
    SessionEvent,
)
from .device_resources import (  # noqa: F401
    DeviceElection,
    DeviceLock,
    DeviceLong,
    DeviceMap,
    DeviceMultiMap,
    DeviceQueue,
    DeviceResourceError,
    DeviceSet,
    DeviceTopic,
    DeviceValue,
)
