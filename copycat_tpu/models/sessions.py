"""Sessions for the direct device path (RaftGroups + Device* facades).

The reference's entire resource-level failure-recovery story is "session
death is a deterministic, replicated event" applied through the log
(``ResourceManager.java:238-266``, ``LeaderElectionState.close:36-49``).
The Atomix SPI path inherits that from the CPU session layer; THIS module
gives the raw device path the same property: without it, a crashed client
whose facade holds a device lock wedges the lock forever — precisely the
reference defect the CPU path fixes (``coordination/state.py:21-23``).

Design: the host driving the batch is the session authority (the leader
role in the reference). A :class:`DeviceSessionRegistry` hangs off
``RaftGroups``; clients open :class:`DeviceSession`\\ s whose ids double as
the lock-holder / election-candidate ids their facades use. Liveness is
keep-alives measured in engine rounds (the logical clock the whole device
path runs on — never wall time). On expiry (or graceful close) the
registry submits cleanup ops THROUGH THE LOG — ``OP_LOCK_CANCEL`` +
``OP_LOCK_RELEASE`` for every lock interest, ``OP_ELECT_RESIGN`` for every
election interest — so recovery is totally ordered with every concurrent
grant/acquire, exactly like the ``OP_LOCK_CANCEL`` timeout discipline
(``ops/apply.py``). Cleanup ops are safe no-ops when the session turned
out not to hold/queue anything.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from .raft_groups import RaftGroups


class SessionExpiredError(RuntimeError):
    """The session missed its keep-alives; its locks/leaderships have been
    (or are being) released through the log. Open a new session."""


class DeviceSession:
    """One device-path client identity.

    ``session.id`` is the int the facades use as lock-holder id and
    election-candidate id, so every replicated interest of this client is
    keyed by it — the reference's "state is keyed by sessions" discipline
    (SURVEY.md §3.4).
    """

    def __init__(self, registry: "DeviceSessionRegistry", sid: int) -> None:
        self.id = sid
        self._registry = registry
        self.expired = False
        self.closed = False

    def keep_alive(self) -> None:
        if self.expired or self.closed:
            raise SessionExpiredError(f"session {self.id} is dead")
        self._registry.keep_alive(self.id)

    def close(self) -> None:
        """Graceful close: same deterministic fan-out as expiry, now."""
        if not (self.expired or self.closed):
            self._registry._terminate(self.id, graceful=True)

    def bind(self, group: int, kind: str) -> None:
        """Declare a lock/election interest in ``group`` (facades call this
        so death cleanup knows where to fan out)."""
        self._registry.bind(self.id, group, kind)


class DeviceSessionRegistry:
    """Host-side session table + expiry fan-out for one RaftGroups batch."""

    #: Session ids start here so they can NEVER collide with manually
    #: chosen holder/candidate ids of session-less facades — a collision
    #: would let one session's expiry release a lock a different, live
    #: client holds under the same int. Manual ids must stay below this.
    SESSION_ID_BASE = 1 << 30

    def __init__(self, groups: "RaftGroups",
                 timeout_rounds: int = 100) -> None:
        self._groups = groups
        self.timeout_rounds = timeout_rounds
        self._next_id = self.SESSION_ID_BASE
        self._sessions: dict[int, DeviceSession] = {}
        self._last_seen: dict[int, int] = {}        # sid -> round
        # sid -> set of (group, kind) with kind in {"lock", "election"}
        self._interests: dict[int, set[tuple[int, str]]] = {}
        self._pinned: dict[int, int] = {}           # sid -> in-flight calls
        self._cleanup_tags: set[int] = set()        # fan-out op tags to reap
        #: (group, opcode, sid) cleanup ops awaiting a bulk drive —
        #: monotone-tag engines refuse queue-managed submits, so expiry
        #: fan-out is staged here and committed by the sessioned bulk
        #: client's next flush (log-ordered there like any other op).
        self.pending_cleanup: list[tuple[int, int, int]] = []

    # -- lifecycle ---------------------------------------------------------

    def open_session(self) -> DeviceSession:
        sid = self._next_id
        self._next_id += 1
        session = DeviceSession(self, sid)
        self._sessions[sid] = session
        self._last_seen[sid] = self._groups.rounds
        self._interests[sid] = set()
        return session

    def keep_alive(self, sid: int) -> None:
        if sid in self._sessions:
            self._last_seen[sid] = self._groups.rounds

    def bind(self, sid: int, group: int, kind: str) -> None:
        """Record that ``sid`` may hold/queue state of ``kind`` in
        ``group``; cleanup on death covers every bound interest (cleanup
        ops are no-ops for interests that turned out inactive)."""
        interests = self._interests.get(sid)
        if interests is not None:
            interests.add((group, kind))

    def pin(self, sid: int) -> None:
        """Exempt ``sid`` from expiry while one of its own calls is in
        flight: a client blocked inside run_until IS alive (driving the
        very rounds that would otherwise expire it), and expiring it
        mid-call would release its lock while reporting the call a
        success."""
        self._pinned[sid] = self._pinned.get(sid, 0) + 1

    def unpin(self, sid: int) -> None:
        n = self._pinned.get(sid, 0) - 1
        if n <= 0:
            self._pinned.pop(sid, None)
            self.keep_alive(sid)  # the call just finished: it was alive
        else:
            self._pinned[sid] = n

    # -- expiry ------------------------------------------------------------

    def tick(self) -> None:
        """Called once per engine round (from ``RaftGroups.step_round``):
        expire sessions whose last keep-alive is older than the timeout."""
        now = self._groups.rounds
        for sid, seen in list(self._last_seen.items()):
            if now - seen > self.timeout_rounds and sid not in self._pinned:
                self._terminate(sid, graceful=False)
        # Reap resolved cleanup-op results: nothing else pops these tags,
        # and a long-lived batch with session churn must stay bounded.
        if self._cleanup_tags:
            results = self._groups.results
            self._cleanup_tags = {
                t for t in self._cleanup_tags
                if results.pop(t, None) is None}

    def _terminate(self, sid: int, graceful: bool) -> None:
        session = self._sessions.pop(sid, None)
        self._last_seen.pop(sid, None)
        interests = self._interests.pop(sid, set())
        if session is None:
            return
        if graceful:
            session.closed = True
        else:
            session.expired = True
        from ..ops import apply as ops
        for group, kind in sorted(interests):
            if kind == "lock":
                # CANCEL dequeues a waiting interest; RELEASE frees a held
                # one (granting the next waiter). Both are log-ordered
                # with every concurrent grant, so there is no window in
                # which a racing grant can leak to the dead session: if
                # the grant commits first, the RELEASE behind it frees it.
                self._submit_cleanup(group, ops.OP_LOCK_CANCEL, sid)
                self._submit_cleanup(group, ops.OP_LOCK_RELEASE, sid)
            elif kind == "election":
                self._submit_cleanup(group, ops.OP_ELECT_RESIGN, sid)

    def _submit_cleanup(self, group: int, opcode: int, sid: int) -> None:
        # Cleanup fan-out is lock/election ops ONLY — disjoint from the
        # value pool by construction. The device-plane edge replica
        # (models/session_client.py::_EdgeValueCache) observes only the
        # sessioned chunks of a flush, so this path bypassing its
        # observe pass is sound exactly as long as that disjointness
        # holds; a cleanup op that mutated a value register would make
        # cached causal reads stale past the TTL-less device cache's
        # contract (docs/EDGE_READS.md "The device plane").
        if self._groups.config.monotone_tag_accept:
            self.pending_cleanup.append((group, opcode, sid))
        else:
            self._cleanup_tags.add(self._groups.submit(group, opcode, sid))
