"""Compartmentalized deployment plane (docs/DEPLOYMENT.md).

The subsystem that turns the in-process cluster into a deployable,
supervisable topology: a :class:`~copycat_tpu.deploy.topology.TopologySpec`
describes members × groups × an optional standalone ingress/proxy tier
(ports, log dirs, stats ports); the
:class:`~copycat_tpu.deploy.supervisor.Supervisor` launches one OS
process per role over real sockets and real fsync, watches each child's
``/healthz``, restarts crashes with backoff, and tears the cluster down
cleanly. :class:`~copycat_tpu.deploy.ingress.IngressServer` is the new
role: a wire-facing process that owns client connections and the global
ingress batching the server plane used to do in-process, forwarding
sealed sub-blocks to group leaders — scaled out independently of write
quorums per "Scaling Replicated State Machines with
Compartmentalization" (PAPERS.md).
"""

from .ingress import IngressServer
from .supervisor import Supervisor
from .topology import (
    IngressSpec,
    MemberSpec,
    TopologySpec,
    allocate_ports,
)

__all__ = [
    "IngressServer",
    "IngressSpec",
    "MemberSpec",
    "Supervisor",
    "TopologySpec",
    "allocate_ports",
]
