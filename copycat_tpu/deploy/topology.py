"""Topology specs for the deployment plane (docs/DEPLOYMENT.md).

A :class:`TopologySpec` is the whole deployable shape in one value:
N Raft members hosting G groups each, plus an optional standalone
ingress/proxy tier of wire-facing processes — every role with its own
port, stats port and (for members) log directory. The
:class:`~copycat_tpu.deploy.supervisor.Supervisor` launches one OS
process per spec entry via the argv each spec renders
(``python -m copycat_tpu.deploy.child <role> ...``), so a spec is also
an exact, reproducible description of what ran.

Import-light on purpose (stdlib only): the supervisor, the CLI and the
tests all load specs without touching jax or the server stack.
"""

from __future__ import annotations

import json
import os
import socket
import sys
from dataclasses import asdict, dataclass, field


def allocate_ports(n: int, host: str = "127.0.0.1") -> list[int]:
    """``n`` free TCP ports via the bind-port-0 probe: every socket is
    held open until ALL are bound (so the kernel cannot hand the same
    port out twice within one call), then released together. The
    standard ephemeral-port idiom — a parallel CI run or a leftover
    listener on a hardcoded port can no longer collide
    (tests/test_cluster_processes.py used to pin 19361-19363)."""
    socks: list[socket.socket] = []
    try:
        for _ in range(n):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind((host, 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


@dataclass
class MemberSpec:
    """One Raft member process: hosts every group's log + apply plane."""

    name: str
    address: str  # host:port the Raft transport listens on
    peers: list[str]  # every member's address, self included
    stats_port: int
    log_dir: str
    storage: str = "disk"  # memory | mapped | disk
    groups: int = 1
    machine: str | None = None  # "module:factory" (None = ResourceManager)
    role: str = "member"

    def argv(self) -> list[str]:
        out = [sys.executable, "-m", "copycat_tpu.deploy.child", "member",
               self.address,
               *[a for a in self.peers if a != self.address],
               "--name", self.name,
               "--stats-port", str(self.stats_port),
               "--log-dir", self.log_dir,
               "--storage", self.storage,
               "--groups", str(self.groups)]
        if self.machine:
            out += ["--machine", self.machine]
        return out


@dataclass
class IngressSpec:
    """One standalone ingress/proxy process: owns client connections +
    global ingress batching, forwards sealed sub-blocks to group
    leaders (docs/DEPLOYMENT.md "The ingress tier")."""

    name: str
    address: str  # host:port clients connect to
    members: list[str]  # the Raft members this proxy fronts
    peers: list[str]  # the whole ingress tier, self included
    stats_port: int
    groups: int = 1
    machine: str | None = None
    role: str = "ingress"

    def argv(self) -> list[str]:
        out = [sys.executable, "-m", "copycat_tpu.deploy.child", "ingress",
               self.address,
               "--members", ",".join(self.members),
               "--peers", ",".join(self.peers),
               "--name", self.name,
               "--stats-port", str(self.stats_port),
               "--groups", str(self.groups)]
        if self.machine:
            out += ["--machine", self.machine]
        return out


@dataclass
class TopologySpec:
    """Members × groups × optional ingress tier — the deployable shape."""

    members: list[MemberSpec] = field(default_factory=list)
    ingresses: list[IngressSpec] = field(default_factory=list)
    groups: int = 1
    base_dir: str | None = None  # member log dirs live under it
    control_port: int = 0  # supervisor control listener (0 = ephemeral)

    @classmethod
    def local(cls, members: int = 3, ingresses: int = 1, groups: int = 1,
              base_dir: str | None = None, storage: str = "disk",
              machine: str | None = None, host: str = "127.0.0.1",
              control_port: int = 0) -> "TopologySpec":
        """A loopback topology with every port ephemeral (one
        :func:`allocate_ports` call covers the whole shape, so no two
        roles — or two concurrently-built topologies — can collide)."""
        if members < 1:
            raise ValueError("a topology needs at least one member")
        if ingresses < 0:
            raise ValueError("ingresses must be >= 0")
        ports = allocate_ports(2 * (members + ingresses), host)
        member_ports = ports[:members]
        member_stats = ports[members:2 * members]
        ingress_ports = ports[2 * members:2 * members + ingresses]
        ingress_stats = ports[2 * members + ingresses:]
        member_addrs = [f"{host}:{p}" for p in member_ports]
        ingress_addrs = [f"{host}:{p}" for p in ingress_ports]
        base = base_dir or os.path.join(
            os.environ.get("TMPDIR", "/tmp"),
            f"copycat-topology-{os.getpid()}-{member_ports[0]}")
        spec = cls(groups=groups, base_dir=base, control_port=control_port)
        for i in range(members):
            spec.members.append(MemberSpec(
                name=f"member-{i}", address=member_addrs[i],
                peers=list(member_addrs), stats_port=member_stats[i],
                log_dir=os.path.join(base, f"member-{i}"),
                storage=storage, groups=groups, machine=machine))
        for i in range(ingresses):
            spec.ingresses.append(IngressSpec(
                name=f"ingress-{i}", address=ingress_addrs[i],
                members=list(member_addrs), peers=list(ingress_addrs),
                stats_port=ingress_stats[i], groups=groups,
                machine=machine))
        return spec

    # -- views -------------------------------------------------------------

    def children(self) -> list:
        """Every process spec, members first (the tier that must be up
        before an ingress proxy can find a leader)."""
        return [*self.members, *self.ingresses]

    def member_addrs(self) -> list[str]:
        return [m.address for m in self.members]

    def ingress_addrs(self) -> list[str]:
        return [i.address for i in self.ingresses]

    def client_addrs(self) -> list[str]:
        """Where clients should connect: the ingress tier when one is
        deployed, else the members directly (the in-server ingress)."""
        return self.ingress_addrs() or self.member_addrs()

    def stats_addrs(self) -> dict[str, str]:
        """``{child name: stats host:port}`` for the whole topology —
        what per-tier attribution (``bench compartment``), ``copycat-tpu
        doctor`` and the supervisor's health watch scrape."""
        return {c.name: f"{c.address.rsplit(':', 1)[0]}:{c.stats_port}"
                for c in self.children()}

    # -- serialization (the control surface's /topology payload) -----------

    def to_json(self) -> str:
        return json.dumps(asdict(self), indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "TopologySpec":
        raw = json.loads(text)
        return cls(
            members=[MemberSpec(**m) for m in raw.get("members", ())],
            ingresses=[IngressSpec(**i) for i in raw.get("ingresses", ())],
            groups=raw.get("groups", 1),
            base_dir=raw.get("base_dir"),
            control_port=raw.get("control_port", 0),
        )


def load_machine(spec: str | None):
    """Resolve a ``module:factory`` machine spec to the callable the
    server builds per group; ``None`` resolves to the ResourceManager
    factory (the full resource catalog — what ``copycat-server``
    deploys). Importing the module also registers the machine's op
    types with the serializer, which every process that decodes the
    workload's frames needs."""
    if not spec:
        return None
    module_name, _, attr = spec.partition(":")
    if not module_name or not attr:
        raise ValueError(
            f"bad machine spec {spec!r} — expected module.path:factory")
    import importlib

    module = importlib.import_module(module_name)
    try:
        return getattr(module, attr)
    except AttributeError:
        raise ValueError(
            f"machine spec {spec!r}: {module_name} has no attribute "
            f"{attr!r}") from None
