"""Child-process entry point for the deployment plane
(``python -m copycat_tpu.deploy.child {member|ingress} ...``).

One OS process per topology role (docs/DEPLOYMENT.md): ``member`` is
``copycat-server`` (the full Raft node — real sockets, real fsync) with
the deployment flags; ``ingress`` runs a standalone
:class:`~copycat_tpu.deploy.ingress.IngressServer` fronting the member
tier. Both speak the supervisor's exit-code contract: 0 = clean
shutdown, 2 = config error (don't restart — fix the spec), anything
else = crash (restart with backoff).
"""

from __future__ import annotations

import argparse
import asyncio
import contextlib
import signal
import sys


def _ingress_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m copycat_tpu.deploy.child ingress",
        description="Run a standalone ingress/proxy-tier process.")
    parser.add_argument("address", metavar="host:port",
                        help="where clients connect to this proxy")
    parser.add_argument("--members", required=True, metavar="A,B,...",
                        help="comma-separated Raft member addresses this "
                             "proxy fronts")
    parser.add_argument("--peers", default="", metavar="A,B,...",
                        help="the whole ingress tier (self included) — "
                             "advertised to clients as the cluster, so "
                             "they re-route within the tier")
    parser.add_argument("--stats-port", type=int, default=None,
                        metavar="PORT",
                        help="serve /stats /metrics /healthz on this port")
    parser.add_argument("--stats-host", default="127.0.0.1",
                        metavar="HOST")
    parser.add_argument("--groups", type=int, default=1, metavar="N",
                        help="the cluster's Raft group count (must match "
                             "the members')")
    parser.add_argument("--machine", default=None, metavar="MOD:FACTORY",
                        help="machine spec — resolves routing "
                             "(route_group) and registers the workload's "
                             "op types with the serializer")
    parser.add_argument("--name", default="ingress", metavar="NAME")
    return parser


async def _serve_ingress(args: argparse.Namespace) -> None:
    from ..cli import ConfigError
    from ..io.tcp import TcpTransport
    from ..io.transport import Address
    from ..server.stats import StatsListener
    from .ingress import IngressServer
    from .topology import load_machine

    try:
        address = Address.parse(args.address)
        members = [Address.parse(a)
                   for a in args.members.split(",") if a]
        tier = [Address.parse(a) for a in args.peers.split(",") if a]
    except (ValueError, TypeError) as e:
        raise ConfigError(f"bad address: {e}") from e
    if not members:
        raise ConfigError("--members must name at least one Raft member")
    try:
        factory = load_machine(args.machine)
    except (ValueError, ImportError) as e:
        raise ConfigError(f"--machine: {e}") from e
    if factory is None:
        from ..manager.state import ResourceManager
        route_machine: type = ResourceManager
    elif isinstance(factory, type):
        route_machine = factory
    else:
        route_machine = type(factory(0))

    ingress = IngressServer(address, members, TcpTransport(),
                            groups=max(1, args.groups),
                            tier=tier or None,
                            route_machine=route_machine, name=args.name)
    stats: StatsListener | None = None

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()

    def _on_signal() -> None:
        stop.set()
        for s in (signal.SIGINT, signal.SIGTERM):
            with contextlib.suppress(Exception):
                loop.remove_signal_handler(s)

    for sig in (signal.SIGINT, signal.SIGTERM):
        with contextlib.suppress(NotImplementedError, ValueError):
            loop.add_signal_handler(sig, _on_signal)

    try:
        try:
            from ..cli import _open_with_bind_retry

            await _open_with_bind_retry(ingress.open)
            if args.stats_port is not None:
                stats = await StatsListener(
                    ingress, host=args.stats_host,
                    port=args.stats_port).open()
        except OSError as e:
            raise ConfigError(
                f"cannot start ingress at {address}: {e}") from e
        print(f"ingress listening at {address} "
              f"(fronting {len(members)} member(s), "
              f"{max(1, args.groups)} group(s))", flush=True)
        if stats is not None:
            print(f"stats listener on port {stats.port} "
                  f"(/stats /metrics /healthz)", flush=True)
        await stop.wait()
        print("shutting down...", flush=True)
    finally:
        if stats is not None:
            with contextlib.suppress(Exception):
                await stats.close()
        try:
            await asyncio.wait_for(ingress.close(), 10)
        except (Exception, asyncio.TimeoutError):
            pass


def main(argv: list[str] | None = None) -> None:
    from ..cli import ConfigError

    raw = sys.argv[1:] if argv is None else argv
    if not raw or raw[0] not in ("member", "ingress"):
        print("usage: python -m copycat_tpu.deploy.child "
              "{member|ingress} ...", file=sys.stderr)
        raise SystemExit(2)
    role, rest = raw[0], raw[1:]
    if role == "member":
        # copycat-server IS the member role (same flags, same exit-code
        # contract) — one code path for operators and the supervisor
        from ..cli import server

        server(rest)
        return
    args = _ingress_parser().parse_args(rest)
    try:
        asyncio.run(_serve_ingress(args))
    except KeyboardInterrupt:
        pass
    except ConfigError as e:
        print(f"copycat-ingress: config error: {e}", file=sys.stderr)
        raise SystemExit(2) from None
    except Exception as e:  # noqa: BLE001 — a crash, diagnosed in one line
        print(f"copycat-ingress: fatal: {type(e).__name__}: {e}",
              file=sys.stderr)
        raise SystemExit(1) from None


if __name__ == "__main__":
    main()
