"""The standalone ingress/proxy tier (docs/DEPLOYMENT.md).

:class:`IngressServer` is the server plane's ingress half lifted into
its own wire-facing process — the compartmentalization move (PAPERS.md:
"Scaling Replicated State Machines with Compartmentalization"): client
connections, session fan-out, per-group routing and the global ingress
batching no longer share a GIL with the Raft groups they front, and the
tier scales out independently of write quorums (add ingress processes
without touching the replication plane).

What it does per request, mirroring ``RaftServer``'s multi-group
ingress (``_ms_*`` handlers):

- owns the client connection: registers/keep-alives fan out to every
  group's leader, commands bucket by ``route_group`` into per-group
  sub-blocks dispatched in per-(session, group) submission order,
  reads route to the owning group's leader (or any member for
  sub-linearizable levels);
- forwards each sealed sub-block as a :class:`ProxyRequest` with an
  ``ingress:``-prefixed kind over a correlated peer connection to the
  group's current leader, learning leader views from ``NOT_LEADER``
  hints (the same retry discipline as the in-server proxy);
- relays event pushes: the group leader binds the proxied session to
  the ingress's peer connection (``RaftServer._on_proxy``), pushes
  ``PublishRequest`` frames to the ingress, and the ingress forwards
  them to the client connection it holds — acks travel back the same
  path, so the at-least-once + gap-detect event contract is unchanged;
- rewrites every ``members`` field it returns to the INGRESS tier's
  addresses: clients re-route between ingress proxies on failure and
  never learn (or dial) the Raft members behind the tier.

``COPYCAT_INGRESS_TIER=0`` removes the server-side acceptance of
ingress-kind proxy traffic and pins the in-server ingress path
bit-identically (the A/B knob); topologies built under it deploy no
ingress processes.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Callable

from ..io.transport import Address, Connection, Transport, TransportError
from ..protocol import messages as msg
from ..protocol.operations import QueryConsistency
from ..utils import knobs, profiler
from ..utils.managed import Managed
from ..utils.metrics import MetricsRegistry
from ..utils.scheduled import Scheduled, schedule_repeating
from ..utils.tasks import spawn
from ..utils.timeseries import SeriesStore
from ..utils.tracing import TRACER

logger = logging.getLogger(__name__)

# Commit-latency floor for command/query forwards. A RESTARTED ingress
# serves sessions that registered through its predecessor — their
# timeouts never replay here, so without a floor the per-try budget
# falls back to the constructor default (5 s) and saturated commit
# latency re-opens the cancel-and-resend retry-storm window. The floor
# is deliberately far above any plausible fsync/replication tail;
# register/keepalive/unregister keep the session-derived budget (they
# are leadership-bound, not commit-bound, and want fast feedback).
_COMMAND_BUDGET_FLOOR_S = 30.0


class IngressServer(Managed):
    """A wire-facing ingress/proxy process fronting a Raft cluster."""

    # StatsListener duck-typing: the routes probe ``state_machine`` /
    # ``health`` / ``blackbox`` with getattr defaults; an ingress has
    # none of them, and must say so with real attributes, not AttributeError
    state_machine = None
    health = None
    blackbox = None

    def __init__(
        self,
        address: Address,
        members: list[Address],
        transport: Transport,
        groups: int = 1,
        tier: list[Address] | None = None,
        route_machine: type | None = None,
        session_timeout: float = 5.0,
        election_timeout: float = 0.5,
        name: str = "ingress",
    ) -> None:
        super().__init__()
        self.address = address
        self.members = list(members)
        self.transport = transport
        self.num_groups = max(1, groups)
        self.tier = list(tier) if tier else [address]
        self.session_timeout = session_timeout
        # The proxy's per-try budget must cover COMMIT latency, not just
        # the wire (the in-server proxy's hard-won lesson: a timeout
        # here CANCELS the in-flight send, and re-sending a block whose
        # first copy already appended is a retry storm — dedup keeps it
        # exactly-once but the duplicate work collapses throughput).
        # The server plane keys that budget off ITS session timeout;
        # the ingress doesn't own sessions, so it tracks the longest
        # timeout a client actually registered and budgets off that.
        self._budget_timeout = session_timeout
        self.election_timeout = election_timeout
        self.name = name
        self._route_group_fn = getattr(route_machine, "route_group", None)

        self._server = transport.server()
        self._client = transport.client()
        self._peer_connections: dict[Address, Connection] = {}
        self._closing = False

        # session_id -> the client connection holding it (event relay
        # target; replaced on reconnect, dropped on unregister)
        self._sessions: dict[int, Connection] = {}
        # per-(session, group) in-order dispatch chains — the same
        # launch-order gate as RaftServer._chained, so a session's
        # sub-blocks for one group reach the leader in submission order
        # while keeping a full pipeline of blocks in flight
        self._chains: dict[tuple, asyncio.Future] = {}
        # per-group leader view, learned from responses/hints
        self._leaders: dict[int, Address | None] = {}
        self._probe_rr = 0
        self._read_rr = 0

        m = self.metrics = MetricsRegistry()
        self._m_sessions = m.gauge("ingress.sessions")
        self._m_commands = m.counter("ingress.commands_forwarded")
        self._m_reads = m.counter("ingress.reads_forwarded")
        self._m_registers = m.counter("ingress.registers")
        self._m_block_ops = m.histogram("ingress.sub_block_ops")
        self._m_events = m.counter("ingress.events_relayed")
        self._m_retries = m.counter("ingress.proxy_retries")
        self._m_reroutes = m.counter("ingress.reroutes")
        # Retrospective telemetry for the proxy tier: the ingress has
        # no health monitor to piggyback, so its series ring runs on
        # one tiny repeating timer (opened/cancelled with the process;
        # skip-if-overlapping like every Scheduled). COPYCAT_SERIES=0
        # removes store, timer and route (A/B).
        self.series = (SeriesStore(node=address, role="ingress",
                                   metrics=m)
                       if knobs.get_bool("COPYCAT_SERIES") else None)
        self._series_timer: Scheduled | None = None
        # Continuous profiling plane (docs/OBSERVABILITY.md
        # "Profiling"): the proxy tier profiles too — the refcounted
        # process-wide sampler, released in _do_close. No flight ring
        # on this tier, so no stall-note callback; holds still surface
        # via profile.hold_* and /profile. COPYCAT_PROFILE=0 -> None:
        # no thread, no keys, no routes (A/B).
        self.profiler = profiler.acquire(m, note_fn=None)
        # Same names/semantics as the server-side ingress phases
        # (docs/OBSERVABILITY.md) so per-tier attribution reads one
        # vocabulary; recorded for EVERY forward on this tier (its whole
        # job is the hop, and the process pays no apply path), where the
        # in-server ingress records them for traced requests only.
        self._m_lat_queue = m.histogram("latency.ingress_queue_ms")
        self._m_lat_hop = m.histogram("latency.proxy_hop_ms")

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def _do_open(self) -> None:
        self._closing = False
        await self._server.listen(self.address, self._accept)
        if self.series is not None:
            self._series_timer = schedule_repeating(
                self.series.interval_s, self.series.interval_s,
                lambda: self.series.maybe_sample(self.metrics.snapshot))
        logger.info("%s listening at %s (fronting %s, %d group(s))",
                    self.name, self.address, self.members, self.num_groups)

    async def _do_close(self) -> None:
        self._closing = True
        if self._series_timer is not None:
            self._series_timer.cancel()
            self._series_timer = None
        await self._server.close()
        await self._client.close()
        self._peer_connections.clear()
        self._sessions.clear()
        self._m_sessions.set(0)
        profiler.release(self.profiler, self.metrics)
        self.profiler = None

    # ------------------------------------------------------------------
    # client side: one handler set per accepted connection
    # ------------------------------------------------------------------

    def _accept(self, connection: Connection) -> None:
        connection.handler(
            msg.RegisterRequest,
            lambda m: self._on_register(connection, m))
        connection.handler(
            msg.KeepAliveRequest,
            lambda m: self._on_keepalive(connection, m))
        connection.handler(msg.UnregisterRequest, self._on_unregister)
        connection.handler(
            msg.CommandRequest,
            lambda m: self._on_command(connection, m))
        connection.handler(
            msg.CommandBatchRequest,
            lambda m: self._on_command_batch(connection, m))
        connection.handler(msg.QueryRequest, self._on_query)
        connection.handler(msg.QueryBatchRequest, self._on_query_batch)

    # ------------------------------------------------------------------
    # member side: leader-seeking proxy forwarding
    # ------------------------------------------------------------------

    async def _peer_connection(self, peer: Address) -> Connection | None:
        conn = self._peer_connections.get(peer)
        if conn is not None and not conn.closed:
            return conn
        try:
            conn = await self._client.connect(peer)
        except (TransportError, OSError):
            return None
        # the event-relay return path: group leaders push PublishRequest
        # frames for sessions this ingress bound over this connection
        conn.handler(msg.PublishRequest, self._relay_publish)
        self._peer_connections[peer] = conn
        return conn

    def _next_member(self) -> Address:
        self._probe_rr += 1
        return self.members[self._probe_rr % len(self.members)]

    def _wire_group(self, g: int) -> int | None:
        # the single-group wire shape carries group=None (docs/SHARDING.md)
        return g if self.num_groups > 1 else None

    async def _proxy(self, g: int, kind: str, payload: Any,
                     trace: int | None = None) -> msg.ProxyResponse:
        """Forward one sealed sub-request to group ``g``'s leader,
        retrying toward the current leader view: ``NOT_LEADER`` hints
        update the view, an unreachable target rotates the probe. Every
        wire attempt records a ``proxy.hop`` sample (failed attempts
        tagged on the trace timeline when tracing)."""
        backoff = 0.01
        base = self._budget_timeout
        if kind in ("commands", "query"):
            base = max(base, _COMMAND_BUDGET_FLOOR_S)
        try_budget = max(base, self.election_timeout * 4)
        deadline = time.monotonic() + max(base,
                                          self.election_timeout * 8)
        first = True
        while True:
            if self._closing:
                return msg.ProxyResponse(error=msg.NO_LEADER,
                                         error_detail="ingress closing")
            if not first:
                self._m_retries.inc()
            first = False
            target = self._leaders.get(g) or self._next_member()
            conn = await self._peer_connection(target)
            response = None
            if conn is not None:
                t_hop = time.perf_counter()
                try:
                    response = await asyncio.wait_for(
                        conn.send(msg.ProxyRequest(
                            group=self._wire_group(g),
                            kind=f"ingress:{kind}", payload=payload,
                            trace=trace)),
                        try_budget)
                except (TransportError, OSError, asyncio.TimeoutError):
                    response = None
                t1 = time.perf_counter()
                self._m_lat_hop.record((t1 - t_hop) * 1e3)
                if trace is not None:
                    TRACER.span(trace, "proxy.hop", t_hop, t1,
                                member=str(self.address), group=g,
                                to=str(target),
                                **({} if response is not None
                                   else {"error": "unreachable"}))
            if response is None:
                # target gone: forget the leader view, probe the tier
                if self._leaders.get(g) == target:
                    self._leaders[g] = None
            elif response.error in (msg.NOT_LEADER, msg.NO_LEADER):
                hint = response.leader
                if hint is not None and hint != target:
                    self._leaders[g] = hint
                    self._m_reroutes.inc()
                    continue  # straight to the hinted leader, no backoff
                self._leaders[g] = hint
            else:
                if self._leaders.get(g) != target:
                    self._leaders[g] = target
                return response
            if time.monotonic() > deadline:
                return (response if response is not None
                        else msg.ProxyResponse(
                            error=msg.NO_LEADER,
                            error_detail=f"group {g}: no reachable leader "
                                         f"behind {self.name}"))
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, 0.1)

    async def _proxy_read(self, g: int, payload: Any,
                          consistency: QueryConsistency
                          ) -> msg.ProxyResponse:
        """Reads: linearizable levels go to the group's leader (they
        join its read window and share its confirm round);
        sequential/causal levels rotate across ALL members — any member
        serves them at or after the client's index, so read throughput
        scales with the member tier, not the leader."""
        if consistency in (QueryConsistency.LINEARIZABLE,
                           QueryConsistency.BOUNDED_LINEARIZABLE):
            return await self._proxy(g, "query", payload)
        self._read_rr += 1
        target = self.members[self._read_rr % len(self.members)]
        conn = await self._peer_connection(target)
        if conn is not None:
            t0 = time.perf_counter()
            try:
                response = await asyncio.wait_for(
                    conn.send(msg.ProxyRequest(
                        group=self._wire_group(g), kind="ingress:query",
                        payload=payload)),
                    self._budget_timeout)
            except (TransportError, OSError, asyncio.TimeoutError):
                response = None
            self._m_lat_hop.record((time.perf_counter() - t0) * 1e3)
            if response is not None and not response.error:
                return response
        # lagging/refusing/unreachable member: the leader path settles it
        return await self._proxy(g, "query", payload)

    # ------------------------------------------------------------------
    # event relay (member -> ingress -> client)
    # ------------------------------------------------------------------

    async def _relay_publish(self, request: msg.PublishRequest
                             ) -> msg.PublishResponse:
        conn = self._sessions.get(request.session_id)
        if conn is None or conn.closed:
            # no client connection right now: report no progress; the
            # group keeps the batch queued and retries on the next
            # keep-alive (the same catch-up contract as a direct client)
            return msg.PublishResponse(event_index=request.prev_event_index)
        try:
            response = await asyncio.wait_for(conn.send(request),
                                              self.session_timeout)
        except (TransportError, OSError, asyncio.TimeoutError):
            return msg.PublishResponse(event_index=request.prev_event_index)
        self._m_events.inc()
        return response

    # ------------------------------------------------------------------
    # session ingress (the _ms_* handlers, tier edition)
    # ------------------------------------------------------------------

    def _tier_members(self) -> list[Address]:
        """What clients are told the cluster is: the ingress tier."""
        return list(self.tier)

    def _bind(self, session_id: int, connection: Connection) -> None:
        self._sessions[session_id] = connection
        self._m_sessions.set(len(self._sessions))

    async def _on_register(self, connection: Connection,
                           request: msg.RegisterRequest
                           ) -> msg.RegisterResponse:
        timeout = request.timeout or self.session_timeout
        self._budget_timeout = max(self._budget_timeout, timeout)
        self._m_registers.inc()
        response = await self._proxy(
            0, "register", (request.client_id, timeout, None))
        if response.error:
            return msg.RegisterResponse(error=response.error,
                                        error_detail=response.error_detail,
                                        members=self._tier_members())
        sid = response.result
        outs = await asyncio.gather(*(
            self._proxy(g, "register", (request.client_id, timeout, sid))
            for g in range(1, self.num_groups)))
        for out in outs:
            if out.error:
                return msg.RegisterResponse(
                    error=out.error, error_detail=out.error_detail,
                    members=self._tier_members())
        self._bind(sid, connection)
        return msg.RegisterResponse(session_id=sid, timeout=timeout,
                                    members=self._tier_members(),
                                    groups=self.num_groups)

    async def _on_keepalive(self, connection: Connection,
                            request: msg.KeepAliveRequest
                            ) -> msg.KeepAliveResponse:
        sid = request.session_id
        self._bind(sid, connection)
        ev = request.event_index
        seq = request.command_seq or 0

        def ev_for(g: int) -> int:
            if isinstance(ev, dict):
                return ev.get(g, 0) or 0
            return (ev or 0) if g == 0 else 0

        outs = await asyncio.gather(*(
            self._proxy(g, "keepalive", (sid, seq, ev_for(g)))
            for g in range(self.num_groups)))
        if outs[0].error:
            if outs[0].error == msg.UNKNOWN_SESSION:
                self._sessions.pop(sid, None)
                self._m_sessions.set(len(self._sessions))
            return msg.KeepAliveResponse(error=outs[0].error,
                                         members=self._tier_members())
        return msg.KeepAliveResponse(members=self._tier_members())

    async def _on_unregister(self, request: msg.UnregisterRequest
                             ) -> msg.UnregisterResponse:
        outs = await asyncio.gather(*(
            self._proxy(g, "unregister", request.session_id)
            for g in range(self.num_groups)))
        self._sessions.pop(request.session_id, None)
        self._m_sessions.set(len(self._sessions))
        first = outs[0]
        if first.error and first.error != msg.UNKNOWN_SESSION:
            return msg.UnregisterResponse(error=first.error)
        return msg.UnregisterResponse()

    # -- commands ------------------------------------------------------

    def _route(self, operation: Any) -> int:
        fn = self._route_group_fn
        if fn is None:
            return 0
        g = fn(operation, self.num_groups)
        return g if 0 <= g < self.num_groups else 0

    def _tag_index(self, index: int, g: int) -> int:
        return index * self.num_groups + g if index else index

    def _client_index(self, index: Any, g: int) -> int:
        if isinstance(index, dict):
            return index.get(g, 0) or 0
        if g == 0 and isinstance(index, int):
            return index
        return 0

    async def _chained(self, key: tuple, thunk: Callable) -> Any:
        """Launch-order gate per (session, group) — see
        ``RaftServer._chained``: sub-blocks reach the transport in
        submission order without serializing their round trips."""
        loop = asyncio.get_running_loop()
        prev = self._chains.get(key)
        gate: asyncio.Future = loop.create_future()
        self._chains[key] = gate
        try:
            if prev is not None:
                await asyncio.shield(prev)
            task = spawn(thunk(), name="ingress-dispatch")
        finally:
            if not gate.done():
                gate.set_result(None)
            if self._chains.get(key) is gate:
                del self._chains[key]
        return await task

    async def _dispatch_commands(self, g: int, session_id: int, sub: list,
                                 trace: int | None, t0: float) -> Any:
        """One group's command sub-block in per-(session, group) order;
        returns tagged per-entry outcomes or ``(code, detail, leader)``.
        The wait from ingress receipt until the chain released the
        dispatch records as ``ingress.queue``."""
        self._m_commands.inc(len(sub))
        self._m_block_ops.record(len(sub))

        async def dispatch() -> msg.ProxyResponse:
            t1 = time.perf_counter()
            self._m_lat_queue.record((t1 - t0) * 1e3)
            if trace is not None:
                TRACER.span(trace, "ingress.queue", t0, t1,
                            member=str(self.address), group=g, n=len(sub))
            return await self._proxy(g, "commands", (session_id, sub),
                                     trace)

        response = await self._chained((session_id, g), dispatch)
        if response.error:
            return (response.error, response.error_detail or "", None)
        out = response.result or []
        return [(seq, self._tag_index(idx, g), res, code, det)
                for seq, idx, res, code, det in (tuple(e) for e in out)]

    async def _on_command_batch(self, connection: Connection,
                                request: msg.CommandBatchRequest
                                ) -> msg.CommandBatchResponse:
        sid = request.session_id
        self._bind(sid, connection)
        entries = request.entries or []
        trace = request.trace
        t0 = time.perf_counter()
        buckets: dict[int, list] = {}
        for seq, op in entries:
            buckets.setdefault(self._route(op), []).append((seq, op))
        results = await asyncio.gather(*(
            self._dispatch_commands(g, sid, sub, trace, t0)
            for g, sub in buckets.items()))
        merged: dict[int, tuple] = {}
        for res in results:
            if isinstance(res, tuple):  # response-level (code, detail, _)
                code, detail, _ = res
                # never leak a Raft member as a leader hint: clients
                # re-route WITHIN the ingress tier
                return msg.CommandBatchResponse(
                    error=code, error_detail=detail)
            for entry in res:
                merged[entry[0]] = entry
        out = [merged.get(seq, (seq, 0, None, msg.INTERNAL,
                                "sub-block outcome missing"))
               for seq, _ in entries]
        return msg.CommandBatchResponse(event_index=0, entries=out)

    async def _on_command(self, connection: Connection,
                          request: msg.CommandRequest
                          ) -> msg.CommandResponse:
        sid = request.session_id
        self._bind(sid, connection)
        g = self._route(request.operation)
        res = await self._dispatch_commands(
            g, sid, [(request.seq, request.operation)], request.trace,
            time.perf_counter())
        if isinstance(res, tuple):
            code, detail, _ = res
            return msg.CommandResponse(error=code, error_detail=detail)
        _, index, result, code, detail = res[0]
        if code:
            return msg.CommandResponse(error=code, error_detail=detail,
                                       index=index, event_index=0)
        return msg.CommandResponse(index=index, result=result,
                                   event_index=0)

    # -- reads ---------------------------------------------------------

    async def _serve_reads(self, g: int, session_id: int, index: Any,
                           consistency: QueryConsistency, operations: list
                           ) -> tuple[int, list | None, tuple | None]:
        self._m_reads.inc(len(operations))
        response = await self._proxy_read(
            g, (session_id, self._client_index(index, g),
                consistency.value, operations), consistency)
        if response.error:
            return 0, None, (response.error, response.error_detail or "",
                             None)
        served_index, entries = response.result
        return served_index, entries, None

    async def _on_query(self, request: msg.QueryRequest
                        ) -> msg.QueryResponse:
        consistency = QueryConsistency(request.consistency or "linearizable")
        g = self._route(request.operation)
        served_index, entries, err = await self._serve_reads(
            g, request.session_id, request.index, consistency,
            [request.operation])
        if err is not None:
            code, detail, _ = err
            if code in (msg.NOT_LEADER, msg.NO_LEADER):
                return msg.QueryResponse(error=code)
            return msg.QueryResponse(error=code, error_detail=detail)
        result, code, detail = entries[0]
        tagged = self._tag_index(served_index, g)
        if code:
            return msg.QueryResponse(error=code, error_detail=detail,
                                     index=tagged)
        return msg.QueryResponse(index=tagged, result=result)

    async def _on_query_batch(self, request: msg.QueryBatchRequest
                              ) -> msg.QueryBatchResponse:
        consistency = QueryConsistency(request.consistency or "linearizable")
        operations = request.operations or []
        buckets: dict[int, list] = {}
        for pos, op in enumerate(operations):
            buckets.setdefault(self._route(op), []).append((pos, op))
        outs = await asyncio.gather(*(
            self._serve_reads(g, request.session_id, request.index,
                              consistency, [op for _, op in sub])
            for g, sub in buckets.items()))
        entries: list = [None] * len(operations)
        index: dict[int, int] = {}
        for (g, sub), (served_index, served, err) in zip(buckets.items(),
                                                         outs):
            if err is not None:
                code, detail, _ = err
                if code in (msg.NOT_LEADER, msg.NO_LEADER):
                    return msg.QueryBatchResponse(error=code)
                return msg.QueryBatchResponse(error=code,
                                              error_detail=detail)
            if served_index:
                index[g] = served_index
            for (pos, _op), entry in zip(sub, served):
                entries[pos] = tuple(entry)
        return msg.QueryBatchResponse(index=index, entries=entries)

    # ------------------------------------------------------------------
    # observability (docs/OBSERVABILITY.md; served by StatsListener)
    # ------------------------------------------------------------------

    def healthz_info(self) -> dict:
        """The ``/healthz`` payload: liveness + tier identity, no
        snapshot cost — what the deployment supervisor polls."""
        return {"ok": True, "node": str(self.address), "role": "ingress",
                "sessions": len(self._sessions)}

    def stats_snapshot(self) -> dict:
        snap: dict = {
            "node": str(self.address),
            "role": "ingress",
            "groups": self.num_groups,
            "members": [str(m) for m in self.members],
            "tier": [str(a) for a in self.tier],
            "leaders": {str(g): str(a) for g, a in self._leaders.items()
                        if a is not None},
            "ingress": self.metrics.snapshot(),
        }
        transport_metrics = getattr(self.transport, "metrics", None)
        if transport_metrics is not None:
            snap["transport"] = transport_metrics.snapshot()
        return snap
