"""The deployment supervisor (docs/DEPLOYMENT.md).

Runs a :class:`~copycat_tpu.deploy.topology.TopologySpec` like
production: one OS process per role (members first, then the ingress
tier), each child's stdout/stderr captured to ``<base_dir>/<name>.log``,
a ``/healthz`` watch at ``COPYCAT_DEPLOY_HEALTH_INTERVAL_S``, and a
restart policy keyed off the child exit-code contract
(``copycat_tpu/deploy/child.py``):

- ``0`` — clean shutdown: the child stays down (the operator asked).
- ``2`` — config error: NEVER restarted. A port that cannot bind or a
  machine spec that cannot import fails identically on every attempt;
  the supervisor surfaces the spec problem instead of crash-looping it.
- anything else (crashes, ``kill -9``) — relaunched with exponential
  backoff (``COPYCAT_DEPLOY_RESTART_BACKOFF_S`` doubling to
  ``COPYCAT_DEPLOY_RESTART_MAX_S``; a child that then stays up resets
  the backoff). A running child whose ``/healthz`` fails repeatedly
  after it has once been healthy is killed onto the same path — a
  wedged process is a crash the kernel hasn't noticed yet.

Teardown is the reverse of launch: SIGTERM to the ingress tier first
(stop taking client traffic), then the members, ``COPYCAT_DEPLOY_GRACE_S``
for graceful exits, SIGKILL for whatever remains.

The control surface is a :class:`ControlListener` — the stats listener
plus ``/topology`` (the spec as JSON) and ``/kill/<name>`` (the
process-level nemesis hook / ``copycat-tpu cluster kill-member``). The
supervisor's own ``deploy.*`` registry rides ``/stats`` and
``/metrics`` like every other plane (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import os
import signal
import sys
import time

from ..server.stats import StatsListener, fetch_stats
from ..utils import knobs, profiler
from ..utils.managed import Managed
from ..utils.metrics import MetricsRegistry
from ..utils.tasks import spawn
from ..utils.timeseries import SeriesStore
from .topology import IngressSpec, MemberSpec, TopologySpec

logger = logging.getLogger(__name__)

# Child lifecycle states (Supervisor.status()["children"][name]["state"])
LAUNCHING = "launching"
RUNNING = "running"
BACKOFF = "backoff"
STOPPED = "stopped"  # exit 0 — stays down
CONFIG_ERROR = "config-error"  # exit 2 — never restarted
SPAWN_FAILED = "spawn-failed"  # exec itself failed

# /healthz failures in a row (once ever-healthy) before the supervisor
# kills a wedged-but-alive child onto the restart path
_UNHEALTHY_KILL_AFTER = 3


class _Child:
    """One supervised process and its restart bookkeeping."""

    def __init__(self, spec: MemberSpec | IngressSpec, log_path: str
                 ) -> None:
        self.spec = spec
        self.log_path = log_path
        self.process: asyncio.subprocess.Process | None = None
        self.pid: int | None = None
        self.state = LAUNCHING
        self.restarts = 0
        self.last_exit: int | None = None
        self.started_at = 0.0
        self.ever_healthy = False
        self.healthz: dict | None = None
        self.health_strikes = 0  # consecutive /healthz failures

    @property
    def alive(self) -> bool:
        return self.process is not None and self.process.returncode is None

    def status(self) -> dict:
        return {
            "role": self.spec.role,
            "address": self.spec.address,
            "stats": f"127.0.0.1:{self.spec.stats_port}",
            "state": self.state,
            "pid": self.pid if self.alive else None,
            "restarts": self.restarts,
            "last_exit": self.last_exit,
            "uptime_s": (round(time.monotonic() - self.started_at, 1)
                         if self.alive else 0.0),
            "healthy": self.ever_healthy and self.health_strikes == 0,
            "healthz": self.healthz,
            "log": self.log_path,
        }


class Supervisor(Managed):
    """Launches, watches, restarts and tears down one topology."""

    # StatsListener duck-typing (see IngressServer): the shared routes
    # probe these; a supervisor has none of them
    state_machine = None
    health = None
    blackbox = None
    transport = None

    def __init__(self, spec: TopologySpec) -> None:
        super().__init__()
        self.spec = spec
        self.address = f"supervisor/{os.getpid()}"
        self._children: dict[str, _Child] = {}
        self._monitors: list[asyncio.Task] = []
        self._watch_task: asyncio.Task | None = None
        self._closing = False
        self.control: ControlListener | None = None

        self._backoff0 = knobs.get_float("COPYCAT_DEPLOY_RESTART_BACKOFF_S")
        self._backoff_max = knobs.get_float("COPYCAT_DEPLOY_RESTART_MAX_S")
        self._grace = knobs.get_float("COPYCAT_DEPLOY_GRACE_S")
        self._health_interval = knobs.get_float(
            "COPYCAT_DEPLOY_HEALTH_INTERVAL_S")

        m = self.metrics = MetricsRegistry()
        # retrospective telemetry for the deploy plane: the supervisor's
        # own /series (deploy.* restart/health-check rates over time),
        # sampled inside the EXISTING health watch — no extra task.
        # COPYCAT_SERIES=0 removes the store and the route (A/B).
        self.series = (SeriesStore(node=self.address, role="supervisor",
                                   metrics=m)
                       if knobs.get_bool("COPYCAT_SERIES") else None)
        self._m_children = m.gauge("deploy.children")
        self._m_children_up = m.gauge("deploy.children_up")
        self._m_restarts = m.counter("deploy.restarts")
        self._m_config_errors = m.counter("deploy.config_errors")
        self._m_health_checks = m.counter("deploy.health_checks")
        self._m_health_failures = m.counter("deploy.health_failures")
        self._m_kills = m.counter("deploy.kills")
        # Continuous profiling plane (docs/OBSERVABILITY.md
        # "Profiling"): the supervisor process profiles itself too —
        # refcounted acquire, released in _do_close. No flight ring,
        # so no stall-note callback. COPYCAT_PROFILE=0 -> None (A/B).
        self.profiler = profiler.acquire(m, note_fn=None)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def _do_open(self) -> None:
        self._closing = False
        base = self.spec.base_dir or "."
        self._ensure_base_dir(base)
        # members first: the tier an ingress proxy needs reachable to
        # find a leader; the ingress tier follows in the same pass (its
        # own retry loop tolerates a still-electing member tier)
        for child_spec in self.spec.children():
            child = _Child(child_spec,
                           os.path.join(base, f"{child_spec.name}.log"))
            self._children[child_spec.name] = child
            self._monitors.append(
                spawn(self._run_child(child),
                      name=f"deploy-monitor-{child_spec.name}"))
        self._m_children.set(len(self._children))
        self._watch_task = spawn(self._watch_health(), name="deploy-health")
        self.control = ControlListener(self, port=self.spec.control_port)
        await self.control.open()
        logger.info("supervisor: %d member(s) + %d ingress(es), control "
                    "on port %d", len(self.spec.members),
                    len(self.spec.ingresses), self.control.port)

    async def _do_close(self) -> None:
        self._closing = True
        if self._watch_task is not None:
            self._watch_task.cancel()
            self._watch_task = None
        # teardown is launch reversed: ingress tier first (stop taking
        # client traffic), then the members
        ordered = list(reversed(self.spec.children()))
        for child_spec in ordered:
            child = self._children.get(child_spec.name)
            if child is not None and child.alive:
                with contextlib.suppress(ProcessLookupError):
                    child.process.terminate()
        deadline = time.monotonic() + self._grace
        for child_spec in ordered:
            child = self._children.get(child_spec.name)
            if child is None or child.process is None:
                continue
            budget = max(0.05, deadline - time.monotonic())
            try:
                await asyncio.wait_for(child.process.wait(), budget)
            except asyncio.TimeoutError:
                with contextlib.suppress(ProcessLookupError):
                    child.process.kill()
                await child.process.wait()
        for task in self._monitors:
            task.cancel()
        await asyncio.gather(*self._monitors, return_exceptions=True)
        self._monitors.clear()
        self._m_children_up.set(0)
        if self.control is not None:
            await self.control.close()
            self.control = None
        profiler.release(self.profiler, self.metrics)
        self.profiler = None

    # ------------------------------------------------------------------
    # child launch + crash loop
    # ------------------------------------------------------------------

    def _ensure_base_dir(self, base: str) -> None:
        os.makedirs(base, exist_ok=True)
        for member in self.spec.members:
            os.makedirs(member.log_dir, exist_ok=True)

    def _child_env(self) -> dict:
        env = dict(os.environ)
        # the repo layout must be importable from the child no matter
        # where the supervisor was launched from (tests, bench, a
        # checked-out tree without `pip install -e .`)
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        prior = env.get("PYTHONPATH")
        env["PYTHONPATH"] = root + (os.pathsep + prior if prior else "")
        return env

    def _open_log(self, child: _Child) -> int:
        # sync helper on purpose: one O_APPEND open per (re)launch
        return os.open(child.log_path,
                       os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)

    async def _launch(self, child: _Child) -> None:
        log_fd = self._open_log(child)
        try:
            child.process = await asyncio.create_subprocess_exec(
                *child.spec.argv(), stdout=log_fd,
                stderr=asyncio.subprocess.STDOUT, env=self._child_env(),
                start_new_session=True)
        finally:
            os.close(log_fd)
        child.pid = child.process.pid
        child.state = RUNNING
        child.started_at = time.monotonic()
        child.health_strikes = 0
        self._m_children_up.set(self._live_count())
        logger.info("supervisor: launched %s (pid %d) at %s",
                    child.spec.name, child.pid, child.spec.address)

    async def _run_child(self, child: _Child) -> None:
        """The per-child crash loop: launch, wait, classify the exit,
        restart with backoff — or stop, per the exit-code contract."""
        backoff = self._backoff0
        while not self._closing:
            try:
                await self._launch(child)
            except (OSError, ValueError) as e:
                child.state = SPAWN_FAILED
                logger.error("supervisor: cannot spawn %s: %s",
                             child.spec.name, e)
                return
            started = child.started_at
            rc = await child.process.wait()
            child.last_exit = rc
            self._m_children_up.set(self._live_count())
            if self._closing or rc == 0:
                child.state = STOPPED
                return
            if rc == 2:
                # config error (deploy/child.py contract): restarting
                # replays the same failure — surface it instead
                child.state = CONFIG_ERROR
                self._m_config_errors.inc()
                logger.error("supervisor: %s exited with a CONFIG error "
                             "— not restarting (see %s)",
                             child.spec.name, child.log_path)
                return
            uptime = time.monotonic() - started
            if uptime > 10 * max(self._backoff0, 0.05):
                backoff = self._backoff0  # it ran healthy: forgive history
            child.state = BACKOFF
            logger.warning("supervisor: %s exited rc=%s after %.1fs — "
                           "restart in %.2fs", child.spec.name, rc,
                           uptime, backoff)
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, self._backoff_max)
            if self._closing:
                return
            child.restarts += 1
            self._m_restarts.inc()

    def _live_count(self) -> int:
        return sum(1 for c in self._children.values() if c.alive)

    # ------------------------------------------------------------------
    # health watch
    # ------------------------------------------------------------------

    async def _watch_health(self) -> None:
        while not self._closing:
            await asyncio.sleep(self._health_interval)
            if self.series is not None:
                # the deploy plane's series ring rides this cadence
                self.series.maybe_sample(self.metrics.snapshot)
            for child in list(self._children.values()):
                if child.state != RUNNING or not child.alive:
                    continue
                self._m_health_checks.inc()
                try:
                    body = await fetch_stats(
                        f"127.0.0.1:{child.spec.stats_port}", "/healthz",
                        timeout=max(1.0, self._health_interval))
                    child.healthz = json.loads(body)
                    child.ever_healthy = True
                    child.health_strikes = 0
                except (OSError, RuntimeError, ValueError,
                        asyncio.TimeoutError):
                    self._m_health_failures.inc()
                    if not child.ever_healthy:
                        continue  # still booting (jax import, elections)
                    child.health_strikes += 1
                    if child.health_strikes >= _UNHEALTHY_KILL_AFTER:
                        # alive but wedged: make it a crash the restart
                        # loop understands
                        logger.warning(
                            "supervisor: %s failed /healthz %d times — "
                            "killing onto the restart path",
                            child.spec.name, child.health_strikes)
                        self.kill(child.spec.name)

    async def wait_healthy(self, timeout: float = 60.0) -> None:
        """Block until every child's ``/healthz`` answers (fresh probes,
        not the watch cadence) — the launch gate benches and tests use
        before opening client load. Raises ``TimeoutError`` with the
        stragglers named."""
        deadline = time.monotonic() + timeout
        pending = set(self._children)
        while pending:
            for name in sorted(pending):
                child = self._children[name]
                if child.state in (CONFIG_ERROR, SPAWN_FAILED):
                    raise RuntimeError(
                        f"{name} cannot become healthy: {child.state} "
                        f"(see {child.log_path})")
                try:
                    body = await fetch_stats(
                        f"127.0.0.1:{child.spec.stats_port}", "/healthz",
                        timeout=2.0)
                    child.healthz = json.loads(body)
                    child.ever_healthy = True
                    pending.discard(name)
                except (OSError, RuntimeError, ValueError,
                        asyncio.TimeoutError):
                    pass
            if not pending:
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"children never became healthy: {sorted(pending)}")
            await asyncio.sleep(0.2)

    # ------------------------------------------------------------------
    # control surface
    # ------------------------------------------------------------------

    def kill(self, name: str, sig: int = signal.SIGKILL
             ) -> tuple[bool, str]:
        """Send ``sig`` to a child — the process-level nemesis hook and
        ``copycat-tpu cluster kill-member``. The crash loop notices the
        exit and restarts with backoff (that is the point: the nemesis
        proves re-route AND recovery)."""
        child = self._children.get(name)
        if child is None:
            return False, (f"unknown member {name!r} — topology has "
                           f"{sorted(self._children)}")
        if not child.alive:
            return False, f"{name} is not running (state {child.state})"
        try:
            child.process.send_signal(sig)
        except ProcessLookupError:
            return False, f"{name} already exited"
        self._m_kills.inc()
        return True, f"sent signal {sig} to {name} (pid {child.pid})"

    def status(self) -> dict:
        return {
            "role": "supervisor",
            "pid": os.getpid(),
            "control": (f"127.0.0.1:{self.control.port}"
                        if self.control is not None else None),
            "groups": self.spec.groups,
            "client_addrs": self.spec.client_addrs(),
            "stats_addrs": self.spec.stats_addrs(),
            "children": {name: child.status()
                         for name, child in sorted(self._children.items())},
        }

    # -- StatsListener surface ----------------------------------------

    def healthz_info(self) -> dict:
        up = self._live_count()
        return {"ok": up == len(self._children), "role": "supervisor",
                "children": len(self._children), "children_up": up}

    def stats_snapshot(self) -> dict:
        return {**self.status(), "deploy": self.metrics.snapshot()}


class ControlListener(StatsListener):
    """The supervisor's control surface: every stats route
    (``/stats`` = topology status + the ``deploy.*`` registry,
    ``/metrics``, ``/healthz``) plus ``/topology`` (the exact spec as
    JSON — what ran, reproducibly) and ``/kill/<name>`` (SIGKILL a
    child; the crash loop restarts it). Loopback-bound like the stats
    listener: the surface is unauthenticated and ``/kill`` is a write."""

    def __init__(self, supervisor: Supervisor, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        super().__init__(supervisor, host=host, port=port)
        self._sup = supervisor

    def _route(self, path: str, query: str = "") -> tuple[bytes, str]:
        if path == "/topology":
            return self._sup.spec.to_json().encode(), "application/json"
        if path.startswith("/kill/"):
            name = path[len("/kill/"):]
            ok, detail = self._sup.kill(name)
            return (json.dumps({"ok": ok, "detail": detail}).encode(),
                    "application/json")
        return super()._route(path, query)


def run_foreground(spec: TopologySpec) -> int:
    """``copycat-tpu cluster spawn``'s engine: run the supervised
    topology until SIGINT/SIGTERM, then tear it down. Returns the exit
    code (0 unless the topology could not even start)."""

    async def drive() -> int:
        sup = Supervisor(spec)
        stop = asyncio.Event()
        signals = 0
        loop = asyncio.get_running_loop()

        def _on_signal() -> None:
            # The handlers stay installed through teardown on purpose:
            # children run in their own sessions (start_new_session), so
            # a raw KeyboardInterrupt mid-close would orphan them with
            # nothing left to reap. First signal = graceful teardown;
            # an insistent second signal hard-kills every child NOW and
            # lets the (then-instant) teardown finish.
            nonlocal signals
            signals += 1
            stop.set()
            if signals >= 2:
                for child in sup._children.values():
                    if child.alive:
                        with contextlib.suppress(ProcessLookupError):
                            child.process.kill()

        for sig in (signal.SIGINT, signal.SIGTERM):
            with contextlib.suppress(NotImplementedError, ValueError):
                loop.add_signal_handler(sig, _on_signal)
        await sup.open()
        try:
            print(f"cluster up: {len(spec.members)} member(s), "
                  f"{len(spec.ingresses)} ingress(es), "
                  f"{spec.groups} group(s)", flush=True)
            print(f"  control: 127.0.0.1:{sup.control.port} "
                  f"(/stats /topology /kill/<name>)", flush=True)
            print(f"  clients connect to: "
                  f"{', '.join(spec.client_addrs())}", flush=True)
            for name, addr in spec.stats_addrs().items():
                print(f"  {name}: stats {addr}", flush=True)
            await stop.wait()
            print("tearing down...", flush=True)
        finally:
            await sup.close()
        return 0

    try:
        return asyncio.run(drive())
    except KeyboardInterrupt:
        return 0
    except Exception as e:  # noqa: BLE001 — one-line diagnosis, exit 1
        print(f"copycat-tpu cluster: fatal: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 1


__all__ = ["ControlListener", "Supervisor", "run_foreground"]
