"""History recorder: concurrent clients over ``RaftGroups`` → checker input.

Wraps the batch driver so each submitted op records its real-time window:
``invoke`` = driver round at submission, ``complete`` = round its result
was harvested. Ops still pending when the recording ends stay incomplete
(``complete = inf``) — the checker treats them as maybe-applied, exactly
how a Jepsen client handles a crashed request.
"""

from __future__ import annotations

import math

from .linearize import HOp


class HistoryRecorder:
    def __init__(self, rg) -> None:
        self._rg = rg
        self._pending: dict[int, tuple[int, tuple, int]] = {}
        self._done: dict[int, list[HOp]] = {}
        self._pending_per_group: dict[int, int] = {}

    def invoke(self, group: int, opcode: int, model_op: tuple,
               a: int = 0, b: int = 0, c: int = 0,
               query: str | None = None) -> int:
        """Submit a device op and start its history window.

        ``query="atomic"`` routes a read through the lease-gated query
        lane instead of the log (``query="sequential"`` for the plain
        leader-served lane) — the checker then validates the lease reads
        against real time like any other op."""
        if query is not None:
            tag = self._rg.submit_query(group, opcode, a, b, c,
                                        consistency=query)
        else:
            tag = self._rg.submit(group, opcode, a, b, c)
        self._pending[tag] = (group, model_op, self._rg.rounds)
        self._pending_per_group[group] = \
            self._pending_per_group.get(group, 0) + 1
        return tag

    def pending_count(self, group: int) -> int:
        """In-flight recorded ops for ``group`` — drivers bound this like
        a real client's concurrency window (unbounded pipelining under a
        long fault otherwise piles up incomplete ops, which both distorts
        the workload and blows up the checker's search)."""
        return self._pending_per_group.get(group, 0)

    def tick(self, n: int = 1) -> None:
        """Advance the cluster, harvesting completions."""
        for _ in range(n):
            self._rg.step_round()
            self._collect()

    def _collect(self) -> None:
        finished = [t for t in self._pending if t in self._rg.results]
        for tag in finished:
            group, model_op, invoke = self._pending.pop(tag)
            self._pending_per_group[group] -= 1
            self._done.setdefault(group, []).append(HOp(
                op_id=tag, op=model_op, result=self._rg.results[tag],
                invoke=invoke, complete=self._rg.rounds))

    def history(self, group: int) -> list[HOp]:
        """Completed + still-pending ops for one group."""
        out = list(self._done.get(group, []))
        for tag, (g, model_op, invoke) in self._pending.items():
            if g == group:
                out.append(HOp(op_id=tag, op=model_op, result=None,
                               invoke=invoke, complete=math.inf))
        return sorted(out, key=lambda h: (h.invoke, h.op_id))
