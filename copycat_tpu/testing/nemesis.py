"""Fault injection for the batched consensus step.

Faults are ``deliver[g, from, to]`` boolean masks consumed *inside* the
compiled step (``ops/consensus.py`` masks every exchange), so partitions
and message loss run at full batch speed — the reference's fake-transport
test strategy (SURVEY.md §4, `LocalTransport`) plus the Jepsen nemesis the
reference outsources, fused into the XLA program.
"""

from __future__ import annotations

import numpy as np

FAULTS = ("heal", "loss", "partition", "isolate")


class Nemesis:
    """Random fault schedule over a ``RaftGroups`` batch.

    Call :meth:`tick` once per driver round; every ``period`` rounds it
    re-rolls a fault and installs the deliver mask. ``heal()`` restores
    full connectivity (call before asserting convergence).
    """

    def __init__(self, rg, seed: int = 0, period: int = 10,
                 faults: tuple = FAULTS, drop_p: float = 0.3) -> None:
        self._rg = rg
        self._rng = np.random.default_rng(seed)
        self._period = max(1, period)
        self._faults = faults
        self._drop_p = drop_p
        self._rounds = 0
        self.current = "heal"

    def _mask(self, fault: str) -> np.ndarray:
        G = self._rg.num_groups
        P = self._rg.num_peers
        if fault == "heal":
            return np.ones((G, P, P), bool)
        if fault == "loss":
            return self._rng.random((G, P, P)) > self._drop_p
        if fault == "partition":
            side = self._rng.integers(0, 2, (G, P))
            return side[:, :, None] == side[:, None, :]
        if fault == "isolate":
            victim = self._rng.integers(0, P, G)
            mask = np.ones((G, P, P), bool)
            g = np.arange(G)
            mask[g, victim, :] = False
            mask[g, :, victim] = False
            return mask
        raise ValueError(f"unknown fault {fault!r}")

    def tick(self) -> str:
        """Advance the schedule; installs a fresh fault every period."""
        if self._rounds % self._period == 0:
            self.current = str(self._rng.choice(self._faults))
            self._install(self.current)
        self._rounds += 1
        return self.current

    def heal(self) -> None:
        self.current = "heal"
        self._install("heal")

    def _install(self, fault: str) -> None:
        import jax.numpy as jnp

        self._rg.deliver = jnp.asarray(self._mask(fault))
        # fault-correlated flight recorder (models/telemetry.py): the
        # injected fault lands in the SAME bounded event ring as the
        # device telemetry, so an election spike and the partition that
        # caused it sit adjacent in one /flight dump
        hub = getattr(self._rg, "telemetry", None)
        if hub is not None:
            hub.flight.record("fault", self._rg.rounds, fault=fault)
