"""Fault injection: the device-plane nemesis and the host storage nemesis.

Device plane (:class:`Nemesis`): faults are ``deliver[g, from, to]``
boolean masks consumed *inside* the compiled step (``ops/consensus.py``
masks every exchange), so partitions and message loss run at full batch
speed — the reference's fake-transport test strategy (SURVEY.md §4,
`LocalTransport`) plus the Jepsen nemesis the reference outsources, fused
into the XLA program.

Host plane (:func:`crash_server` + :class:`StorageNemesis`): the
crash/torn-write family over a server's storage directory — SIGKILL-shaped
stops, torn segment tails, zeroed frame pages, dropped fsyncs, corrupt
snapshots, torn vote-state meta (docs/DURABILITY.md) — driving the
restart-recovery differential in ``tests/test_recovery.py``.
"""

from __future__ import annotations

import os

import numpy as np

FAULTS = ("heal", "loss", "partition", "isolate")

#: The storage-fault vocabulary of :class:`StorageNemesis` (the host-plane
#: crash/torn-write family, docs/DURABILITY.md).
STORAGE_FAULTS = ("torn_tail", "partial_frame", "dropped_fsync",
                  "corrupt_snapshot", "torn_meta")


class Nemesis:
    """Random fault schedule over a ``RaftGroups`` batch.

    Call :meth:`tick` once per driver round; every ``period`` rounds it
    re-rolls a fault and installs the deliver mask. ``heal()`` restores
    full connectivity (call before asserting convergence).
    """

    def __init__(self, rg, seed: int = 0, period: int = 10,
                 faults: tuple = FAULTS, drop_p: float = 0.3) -> None:
        self._rg = rg
        self._rng = np.random.default_rng(seed)
        self._period = max(1, period)
        self._faults = faults
        self._drop_p = drop_p
        self._rounds = 0
        self.current = "heal"

    def _mask(self, fault: str) -> np.ndarray:
        G = self._rg.num_groups
        P = self._rg.num_peers
        if fault == "heal":
            return np.ones((G, P, P), bool)
        if fault == "loss":
            return self._rng.random((G, P, P)) > self._drop_p
        if fault == "partition":
            side = self._rng.integers(0, 2, (G, P))
            return side[:, :, None] == side[:, None, :]
        if fault == "isolate":
            victim = self._rng.integers(0, P, G)
            mask = np.ones((G, P, P), bool)
            g = np.arange(G)
            mask[g, victim, :] = False
            mask[g, :, victim] = False
            return mask
        raise ValueError(f"unknown fault {fault!r}")

    def tick(self) -> str:
        """Advance the schedule; installs a fresh fault every period."""
        if self._rounds % self._period == 0:
            self.current = str(self._rng.choice(self._faults))
            self._install(self.current)
        self._rounds += 1
        return self.current

    def heal(self) -> None:
        self.current = "heal"
        self._install("heal")

    def _install(self, fault: str) -> None:
        import jax.numpy as jnp

        self._rg.deliver = jnp.asarray(self._mask(fault))
        # fault-correlated flight recorder (models/telemetry.py): the
        # injected fault lands in the SAME bounded event ring as the
        # device telemetry, so an election spike and the partition that
        # caused it sit adjacent in one /flight dump
        hub = getattr(self._rg, "telemetry", None)
        if hub is not None:
            hub.flight.record("fault", self._rg.rounds, fault=fault)


# ---------------------------------------------------------------------------
# host plane: crash / torn-write faults over a server's storage directory
# ---------------------------------------------------------------------------


async def crash_server(server) -> None:
    """Kill a ``RaftServer`` the way a SIGKILL would: stop its timers,
    replication, and transport WITHOUT the graceful close path — no
    ``log.close()``, no final msync/fsync, pending commit futures
    abandoned.  What recovery then sees on disk is exactly what the
    storage level's durability contract promised and nothing more; pair
    with :class:`StorageNemesis` to tear what the crash left behind."""
    server._closing = True
    server._open = False  # Managed bookkeeping: a crashed server is closed
    server._cancel_timers()
    server._stop_replication()
    for group in getattr(server, "groups", None) or (server,):
        for fut in group._commit_futures.values():
            if not fut.done():
                fut.cancel()
        group._commit_futures.clear()
    await server._server.close()
    await server._client.close()
    server._peer_connections.clear()
    # NOTE: deliberately NOT server.log.close() — buffered/page-cache
    # state stays wherever the fsync policy last left it


class SlowDiskNemesis:
    """Inject fsync latency into a server's log(s): the slow/failing
    disk the health plane's fsync-spike detector (and, via the
    follower's pre-ack fsync, the leader's AIMD window collapse) exists
    to catch. Wraps each group log's ``sync()`` with a blocking sleep —
    blocking on purpose: a real slow fsync stalls the event loop the
    same way."""

    def __init__(self, server, delay_s: float = 0.02) -> None:
        self._server = server
        self.delay_s = delay_s
        self._originals: list[tuple] = []

    def install(self) -> None:
        import time as _time

        for group in getattr(self._server, "groups", None) or (self._server,):
            log = group.log
            original = log.sync

            def slow_sync(_orig=original) -> None:
                _time.sleep(self.delay_s)
                _orig()

            self._originals.append((log, original))
            log.sync = slow_sync  # type: ignore[method-assign]
        hub = self._hub()
        if hub is not None:
            hub.flight.record("fault", 0, fault="slow_disk",
                              delay_s=self.delay_s)

    def remove(self) -> None:
        for log, original in self._originals:
            log.sync = original  # type: ignore[method-assign]
        self._originals.clear()

    def _hub(self):
        machine = getattr(self._server, "state_machine", None)
        engine = getattr(machine, "_engine", None)
        groups = getattr(engine, "_groups", None)
        return getattr(groups, "telemetry", None)


def _nemesis_synchronous_hold(delay_s: float) -> None:
    """The named blocking call :class:`LoopHoldNemesis` schedules — a
    module-level function ON PURPOSE: the profiling plane's ground
    truth is that the folded leaf frame NAMES the blocking code, and a
    lambda/closure would fold to an anonymous frame."""
    import time as _time

    _time.sleep(delay_s)


class LoopHoldNemesis:
    """Inject a synchronous event-loop hold: the blocking-call fault
    the profiling plane's hold attribution and the ``loop_stall``
    detector exist to catch (the runtime sibling of the copycheck
    loop-blocking rule — this one actually happens). Schedules
    :func:`_nemesis_synchronous_hold` straight onto the running loop,
    so every co-resident server's loop freezes for ``delay_s`` — the
    same shape as an accidental ``time.sleep`` / cold ``jit`` compile /
    synchronous disk read on the loop."""

    def __init__(self, server, delay_s: float = 0.15) -> None:
        self._server = server
        self.delay_s = delay_s
        self.injected = 0

    def inject(self) -> None:
        """Schedule one hold on the running loop (call from a
        coroutine; the hold lands on the next loop turn)."""
        import asyncio as _asyncio

        _asyncio.get_running_loop().call_soon(
            _nemesis_synchronous_hold, self.delay_s)
        self.injected += 1
        hub = self._hub()
        if hub is not None:
            hub.flight.record("fault", 0, fault="loop_hold",
                              delay_s=self.delay_s)

    def _hub(self):
        machine = getattr(self._server, "state_machine", None)
        engine = getattr(machine, "_engine", None)
        groups = getattr(engine, "_groups", None)
        return getattr(groups, "telemetry", None)


class StorageNemesis:
    """Crash/torn-write fault injection over one server's storage
    directory (the host-plane sibling of :class:`Nemesis`): mutates the
    on-disk artifacts a crashed process leaves behind — log segments,
    snapshot files, the vote-state meta file — the way real torn writes,
    reordered writeback, and lost page-cache flushes do.  Recovery must
    shrug all of it off (tests/test_recovery.py)."""

    def __init__(self, directory: str, seed: int = 0) -> None:
        self.directory = directory
        self._rng = np.random.default_rng(seed)
        self.injected: list[tuple[str, str]] = []  # (fault, path)

    # -- file discovery ----------------------------------------------------

    def _files(self, *exts: str) -> list[str]:
        out = []
        for fname in sorted(os.listdir(self.directory)):
            if fname.endswith(exts):
                out.append(os.path.join(self.directory, fname))
        return out

    def newest_segment(self) -> str | None:
        segs = self._files(".seg", ".mseg")
        return segs[-1] if segs else None

    def newest_snapshot(self) -> str | None:
        snaps = self._files(".snap")
        return snaps[-1] if snaps else None

    def meta_file(self) -> str | None:
        metas = self._files(".meta")
        return metas[0] if metas else None

    def _note(self, fault: str, path: str | None) -> str | None:
        if path is not None:
            self.injected.append((fault, path))
        return path

    # -- the fault family --------------------------------------------------

    @staticmethod
    def _written_end(path: str) -> int:
        """End of the WRITTEN region: mapped segments are sparse with a
        leading watermark (mutating their zero tail would be a no-op), so
        the fault lands at ``header + watermark``; buffered segments are
        written densely to their file size."""
        if path.endswith(".mseg"):
            with open(path, "rb") as f:
                used = int.from_bytes(f.read(8), "little")
            return 8 + used
        return os.path.getsize(path)

    def torn_tail(self, nbytes: int = 11) -> str | None:
        """Chop ``nbytes`` off the newest log segment's written region: a
        write that was mid-flight when the process died."""
        path = self.newest_segment()
        if path is None:
            return None
        # never truncate a mapped segment below its watermark header (an
        # empty file cannot be mmapped back)
        floor = 8 if path.endswith(".mseg") else 0
        with open(path, "r+b") as f:
            f.truncate(max(floor, self._written_end(path) - nbytes))
        return self._note("torn_tail", path)

    def partial_frame(self, nbytes: int = 24) -> str | None:
        """Zero the last ``nbytes`` of the newest segment's written region
        in place: frame header/payload pages that never hit the platter
        even though the file length (or mmap watermark) says they did —
        the reordered-writeback shape the seeded CRC framing exists for."""
        path = self.newest_segment()
        if path is None:
            return None
        end = self._written_end(path)
        with open(path, "r+b") as f:
            f.seek(max(0, end - nbytes))
            f.write(b"\x00" * min(nbytes, end))
        return self._note("partial_frame", path)

    def dropped_fsync(self, frames: int = 1) -> str | None:
        """Rewind the newest DISK segment by its last ``frames``
        length-framed entries — a buffered write the kernel never flushed
        (the ``fsync="never"`` failure mode).  Falls back to
        :meth:`torn_tail` for MAPPED segments (page-cache granularity)."""
        path = self.newest_segment()
        if path is None:
            return None
        if path.endswith(".mseg"):
            return self.torn_tail(64)
        from ..io.buffer import BufferInput
        with open(path, "rb") as f:
            raw = f.read()
        buf = BufferInput(raw)
        ends = []
        while buf.remaining > 0:
            try:
                buf.read_bytes()   # payload
                buf.read_varint()  # trailing frame CRC
            except EOFError:
                break
            ends.append(len(raw) - buf.remaining)
        keep = ends[-1 - frames] if len(ends) > frames else 0
        with open(path, "r+b") as f:
            f.truncate(keep)
        return self._note("dropped_fsync", path)

    def corrupt_snapshot(self, nbytes: int = 16) -> str | None:
        """Flip bytes inside the newest snapshot's payload so its CRC
        frame check fails: recovery must skip it and fall back to an
        older snapshot or full replay, never crash or restore garbage."""
        path = self.newest_snapshot()
        if path is None:
            return None
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            # land inside the payload (past the 20-byte frame header)
            start = int(self._rng.integers(20, max(21, size - nbytes)))
            f.seek(start)
            chunk = f.read(nbytes)
            f.seek(start)
            f.write(bytes(b ^ 0xFF for b in chunk))
        return self._note("corrupt_snapshot", path)

    def torn_meta(self) -> str | None:
        """Truncate the (term, voted_for) meta file mid-write: the torn
        state a non-atomic writer leaves; boot must fall back to
        zero-state instead of dying on a JSON parse error."""
        path = self.meta_file()
        if path is None:
            return None
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(1, size // 2))
        return self._note("torn_meta", path)

    def inject(self, fault: str) -> str | None:
        """Inject one named fault from :data:`STORAGE_FAULTS`."""
        if fault not in STORAGE_FAULTS:
            raise ValueError(f"unknown storage fault {fault!r}")
        return getattr(self, fault)()
