"""Verification harness: linearizability checking + fault injection.

The reference outsources consistency verification to an external Jepsen
suite (``/root/reference/README.md:8,27-30``); SURVEY.md §4 lists an
in-tree checker as a build obligation. This package provides:

- :mod:`linearize` — a Wing & Gong style linearizability checker over
  recorded operation histories with sequential models for the device
  resource types;
- :mod:`nemesis` — fault schedules (partitions, message loss, leader
  isolation) expressed as ``deliver[g, from, to]`` masks, injected *inside*
  the compiled consensus step;
- :mod:`history` — a recorder that drives ``RaftGroups`` with concurrent
  clients and captures invoke/complete windows for the checker.
"""

from .linearize import (  # noqa: F401
    CounterModel,
    HOp,
    LockModel,
    MapModel,
    RegisterModel,
    check_linearizable,
)
from .nemesis import Nemesis  # noqa: F401
from .history import HistoryRecorder  # noqa: F401
